"""Task output buffers with the token-acknowledge pull protocol.

The analog of the reference's OutputBuffer family
(presto-main-base/.../execution/buffer/PartitionedOutputBuffer.java,
BroadcastOutputBuffer.java) and the results endpoint semantics of
TaskResource (presto-main/.../server/TaskResource.java:256-308): a consumer
GETs /results/{bufferId}/{token}, pages at sequence numbers >= token are
returned, an acknowledge GET frees everything below the new token, and a
complete flag tells the consumer the stream is finished.
"""
from __future__ import annotations

import os
import struct
import tempfile
from typing import List, Optional, Tuple

from ..common.compression import compress, decompress
from ..common.locks import OrderedCondition


DEFAULT_MAX_BUFFERED_BYTES = 64 << 20


class PageBuffer:
    """One buffer id: an append-only sequence of serialized pages with
    client-driven compaction and producer backpressure (the reference's
    OutputBufferMemoryManager bounds buffered bytes and blocks the
    producer; acknowledges free memory and unblock it).

    With `retain=True` (fault-tolerant streaming: remote task retry
    enabled) acknowledged pages stay resident instead of being freed, so
    a RESTARTED consumer task can replay the stream from token 0 exactly
    — the streaming analog of the batch scheduler's durable shuffle
    files, paid in buffer memory.  Backpressure still counts only
    UNacknowledged bytes, matching the non-retain threshold behavior.

    With a `memory` context the retained (acknowledged) bytes are charged
    to the owning task as a REVOCABLE reservation — they were previously
    invisible to every pool — and the arbitrator can reclaim them by
    spilling the acknowledged prefix to an LZ4-compressed disk file
    (`revoke_to_disk`); a replaying consumer transparently reads spilled
    pages back.  The charge uses arbitrate=False + self-spill because it
    runs under this buffer's own condition lock (see
    RevocableHolder.try_reserve).

    With a `spool` (retry-policy=task: worker/spooling.TaskSpool) the
    buffer stores NOTHING itself: every page is durably staged in the
    spool before add() returns (the producer's acknowledgement point),
    gets replay token-indexed from the spool, and the consumer's
    end-of-stream DELETE only marks the stream consumed — the spool
    outlives both the task and this buffer, released by destroy_all().
    Durability decouples producer and consumer lifetimes, so spool mode
    has no consumer backpressure: resident bytes are bounded by the
    spool's revocable staging budget and its disk tier instead."""

    def __init__(self, max_buffered_bytes: int = DEFAULT_MAX_BUFFERED_BYTES,
                 retain: bool = False, coalesce_target_bytes: int = 0,
                 memory=None, spill_dir: Optional[str] = None,
                 spool=None, buffer_id: int = 0):
        self._spool = spool
        self._buffer_id = buffer_id
        self._spool_count = 0             # pages appended to the spool
        self._client_released = False     # consumer DELETE seen (drain gate)
        self._pages: List[bytes] = []
        self._base = 0                    # sequence number of _pages[0]
        self._bytes = 0                   # UNacknowledged bytes (backpressure)
        self._max_bytes = max_buffered_bytes
        self._retain = retain
        self._acked = 0                   # retain mode: acknowledge watermark
        self._memory = memory             # task MemoryContext (or pool)
        self._holder = None               # lazy revocable registration
        self._spill_dir = spill_dir
        self._disk_fd: Optional[int] = None
        self._disk_path: Optional[str] = None
        # token t (t < _base) -> (offset, compressed_len, raw_len)
        self._disk_records: List[Tuple[int, int, int]] = []
        self._disk_end = 0                # file append offset
        # coalescing (exchange.max-response-size): small serialized pages
        # accumulate in _pending until ~target bytes, then flush as ONE
        # buffer entry so tiny-page stages stop paying a pull round trip
        # per page.  SerializedPages are self-delimiting, so concatenation
        # is transparent to every consumer.  A get() that would otherwise
        # wait flushes first — coalescing never withholds available data.
        self._coalesce_target = max(0, int(coalesce_target_bytes))
        self._pending: List[bytes] = []
        self._pending_bytes = 0
        self._complete = False
        self._destroyed = False
        self._error: Optional[str] = None
        # rank 30: nests INTO the task spool (32) on _store_locked and
        # the memory pool (40) on the retained-page charge; acquired
        # UNDER the arbitrator (20) in _revoke
        self._cond = OrderedCondition(
            "output-buffer", 30)  # lint: guarded-by(_cond)

    def _store_locked(self, data: bytes) -> None:
        if self._spool is not None:
            self._spool.append(self._buffer_id, data)  # durable before return
            self._spool_count += 1
        else:
            self._pages.append(data)

    def _end_locked(self) -> int:
        return (self._spool_count if self._spool is not None
                else self._base + len(self._pages))

    def _flush_pending_locked(self) -> None:
        if self._pending:
            self._store_locked(b"".join(self._pending))
            self._pending = []
            self._pending_bytes = 0
            self._cond.notify_all()

    def add(self, page_bytes: bytes) -> None:
        with self._cond:
            while (self._spool is None and self._bytes >= self._max_bytes
                   and not self._destroyed and self._error is None):
                self._cond.wait(1.0)
            if self._destroyed:
                return
            if self._spool is None:
                self._bytes += len(page_bytes)  # pending counts toward limit
            if self._coalesce_target > 0:
                self._pending.append(page_bytes)
                self._pending_bytes += len(page_bytes)
                if self._pending_bytes >= self._coalesce_target:
                    self._flush_pending_locked()
                else:
                    # wake a parked long-poll getter: a caught-up consumer
                    # demand-flushes rather than sleeping out its maxWait
                    self._cond.notify_all()
            else:
                self._store_locked(page_bytes)
                self._cond.notify_all()

    def set_complete(self) -> None:
        with self._cond:
            self._flush_pending_locked()  # flush boundaries are now final:
            self._complete = True         # replay after retry is identical
            self._cond.notify_all()

    def set_error(self, message: str) -> None:
        with self._cond:
            self._error = message
            self._complete = True
            self._cond.notify_all()

    def get(self, token: int, max_wait_s: float,
            max_bytes: Optional[int] = None
            ) -> Tuple[List[bytes], int, bool]:
        """Pages from `token` on; blocks up to max_wait_s for data.
        Returns (pages, next_token, buffer_complete).  `max_bytes` caps the
        response size (always at least one page) — the consumer's
        X-Presto-Max-Size.  Raises on task failure (propagates the
        producer's error to the consumer)."""
        deadline = None
        with self._cond:
            while True:
                if self._error is not None:
                    raise BufferError(self._error)
                end = self._end_locked()
                if token >= end and self._pending:
                    # the consumer caught up to the coalescer: flush the
                    # partial batch rather than make it wait for more data
                    self._flush_pending_locked()
                    end = self._end_locked()
                if token < end or self._complete:
                    if self._spool is not None:
                        # token-indexed replay straight from the durable
                        # spool (RAM-staged or disk, tier-transparent)
                        pages, size, t = [], 0, max(0, token)
                        while t < end:
                            p = self._spool.read(self._buffer_id, t)
                            if (pages and max_bytes is not None
                                    and size + len(p) > max_bytes):
                                break
                            pages.append(p)
                            size += len(p)
                            t += 1
                        next_token = t if pages else token
                        at_end = self._complete and next_token >= end
                        return pages, next_token, at_end
                    if self._retain and 0 <= token < self._base:
                        # replaying consumer asked for pages already
                        # revoked to disk: read them back transparently
                        pages = (self._read_spilled_locked(token)
                                 + self._pages)
                        first = token
                    else:
                        pages = self._pages[max(0, token - self._base):]
                        first = max(token, self._base)
                    if max_bytes is not None and len(pages) > 1:
                        taken, size = [], 0
                        for p in pages:
                            if taken and size + len(p) > max_bytes:
                                break
                            taken.append(p)
                            size += len(p)
                        pages = taken
                    next_token = first + len(pages)
                    at_end = self._complete and next_token >= end
                    return pages, next_token, at_end
                import time
                if deadline is None:
                    deadline = time.monotonic() + max_wait_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], token, False
                self._cond.wait(remaining)

    def acknowledge(self, token: int) -> None:
        with self._cond:
            if self._spool is not None:
                # spooled pages are never freed by acks (a retried consumer
                # replays from 0); just track consumption for the drain gate
                self._acked = max(self._acked, min(token, self._spool_count))
                self._cond.notify_all()
                return
            if self._retain:
                # advance the watermark and release backpressure, but keep
                # the pages for replay by a retried consumer — now CHARGED
                # to the task's memory context as revocable bytes
                upto = max(self._acked,
                           min(token, self._base + len(self._pages)))
                if upto > self._acked:
                    newly = sum(
                        len(p) for p in
                        self._pages[self._acked - self._base:
                                    upto - self._base])
                    self._bytes -= newly
                    self._acked = upto
                    self._charge_retained_locked(newly)
                    self._cond.notify_all()
                return
            drop = max(0, min(token - self._base, len(self._pages)))
            if drop:
                self._bytes -= sum(len(p) for p in self._pages[:drop])
                self._pages = self._pages[drop:]
                self._base += drop
                self._cond.notify_all()  # unblock a backpressured producer

    # -- retained-page memory charge + disk revocation ---------------------
    def _charge_retained_locked(self, nb: int) -> None:
        if self._memory is None or nb <= 0:
            return
        if self._holder is None:
            self._holder = self._memory.register_revocable(
                "output-buffer", self._revoke)
        if not self._holder.try_reserve(nb, arbitrate=False):
            # no headroom for the retained pages: give them to disk now
            # (self-spill) rather than fail a fault-tolerance feature
            self._spill_acked_locked()

    def _revoke(self) -> int:
        """Arbitrator callback: spill the acknowledged prefix to disk.
        Never blocks — if the buffer lock is contended, decline."""
        if not self._cond.acquire(timeout=0.05):
            return 0
        try:
            return self._spill_acked_locked()
        finally:
            self._cond.release()

    def _spill_acked_locked(self) -> int:
        """Write pages [_base, _acked) as length-prefixed LZ4 records,
        advance _base, and free their revocable charge.  Returns bytes
        freed."""
        n = self._acked - self._base
        if n <= 0 or self._destroyed:
            return 0
        if self._disk_fd is None:
            d = self._spill_dir or tempfile.gettempdir()
            os.makedirs(d, exist_ok=True)
            self._disk_fd, self._disk_path = tempfile.mkstemp(
                prefix="presto-tpu-buffer-", suffix=".spill", dir=d)
        freed = 0
        chunks = []
        for p in self._pages[:n]:
            cp = compress("LZ4", p)
            self._disk_records.append((self._disk_end + 4, len(cp), len(p)))
            chunks.append(struct.pack("<i", len(cp)) + cp)
            self._disk_end += 4 + len(cp)
            freed += len(p)
        os.pwrite(self._disk_fd, b"".join(chunks),
                  self._disk_records[-n][0] - 4)
        self._pages = self._pages[n:]
        self._base = self._acked
        if self._holder is not None:
            self._holder.free(freed)
        from ..exec.memory import MEMORY_METRICS
        MEMORY_METRICS.incr("spilled_bytes", freed)
        MEMORY_METRICS.incr("disk_spilled_bytes", freed)
        if self._memory is not None:
            self._memory.note_spill(freed)
            self._memory.note_disk_spill(freed)
        return freed

    def _read_spilled_locked(self, token: int) -> List[bytes]:
        """Replay path: pages [token, _base) back from the spill file."""
        out = []
        for off, clen, rawlen in self._disk_records[token:self._base]:
            out.append(decompress("LZ4", os.pread(self._disk_fd, clen, off),
                                  rawlen))
        if out:
            from ..exec.memory import MEMORY_METRICS
            MEMORY_METRICS.incr("unspilled_bytes", sum(len(p) for p in out))
            if self._memory is not None:
                self._memory.note_unspill(sum(len(p) for p in out))
        return out

    @property
    def retained_bytes(self) -> int:
        return 0 if self._holder is None else self._holder.bytes

    @property
    def spilled_tokens(self) -> int:
        return self._base if self._retain else 0

    @property
    def consumed(self) -> bool:
        """True once the consumer is definitively done with this stream:
        acked (or DELETEd) through end-of-stream, errored, or destroyed.
        The graceful-drain gate — a SHUTTING_DOWN worker may only exit
        after every buffer it produced has been consumed."""
        with self._cond:
            if (self._destroyed or self._error is not None
                    or self._client_released):
                return True
            if not self._complete:
                return False
            if self._retain or self._spool is not None:
                return self._acked >= self._end_locked()
            return not self._pages and not self._pending

    def destroy(self, force: bool = True) -> None:
        # a retained/spooled buffer survives the consumer's end-of-stream
        # DELETE (a retried consumer may still need to replay it); only
        # task teardown (cancel/evict -> destroy_all) reclaims it.  The
        # DELETE still marks the stream consumed for the drain gate.
        with self._cond:
            if not force and (self._retain or self._spool is not None):
                self._client_released = True
                self._cond.notify_all()
                return
            self._pages = []
            self._pending = []
            self._pending_bytes = 0
            self._bytes = 0
            self._complete = True
            self._destroyed = True
            if self._holder is not None:
                self._holder.close()   # frees the retained charge
                self._holder = None
            if self._disk_fd is not None:
                try:
                    os.close(self._disk_fd)
                    os.unlink(self._disk_path)
                except OSError:
                    pass
                self._disk_fd = None
                self._disk_records = []
            self._cond.notify_all()


class OutputBufferManager:
    """All buffers of one task.  PARTITIONED routes page partition p to
    buffer p; BROADCAST replicates every page into each consumer's buffer."""

    def __init__(self, buffer_type: str, n_buffers: int,
                 retain: bool = False, coalesce_target_bytes: int = 0,
                 memory=None, spill_dir: Optional[str] = None, spool=None):
        self.buffer_type = buffer_type
        self.spool = spool                # shared TaskSpool (or None)
        self.buffers = [PageBuffer(retain=retain,
                                   coalesce_target_bytes=coalesce_target_bytes,
                                   memory=memory, spill_dir=spill_dir,
                                   spool=spool, buffer_id=i)
                        for i in range(max(1, n_buffers))]

    @property
    def retained_bytes(self) -> int:
        return sum(b.retained_bytes for b in self.buffers)

    @property
    def spooled_bytes(self) -> int:
        """Cumulative raw bytes durably spooled (TaskInfo spooledBytes)."""
        return 0 if self.spool is None else self.spool.spooled_bytes

    def flush_spool(self) -> int:
        """Graceful drain: force the spool's staged pages onto disk so the
        output survives this process exiting."""
        return 0 if self.spool is None else self.spool.flush()

    def all_consumed(self) -> bool:
        """Every buffer acked/DELETEd through end-of-stream (drain gate)."""
        return all(b.consumed for b in self.buffers)

    def add(self, partition: int, page_bytes: bytes) -> None:
        if self.buffer_type == "BROADCAST":
            for b in self.buffers:
                b.add(page_bytes)
        else:
            self.buffers[partition].add(page_bytes)

    def set_complete(self) -> None:
        for b in self.buffers:
            b.set_complete()

    def set_error(self, message: str) -> None:
        for b in self.buffers:
            b.set_error(message)

    def get(self, buffer_id: int, token: int, max_wait_s: float,
            max_bytes: Optional[int] = None):
        return self.buffers[buffer_id].get(token, max_wait_s,
                                           max_bytes=max_bytes)

    def acknowledge(self, buffer_id: int, token: int) -> None:
        self.buffers[buffer_id].acknowledge(token)

    def destroy(self, buffer_id: int) -> None:
        # consumer-driven destroy: honored immediately unless retained
        self.buffers[buffer_id].destroy(force=False)

    def destroy_all(self) -> None:
        for b in self.buffers:
            b.destroy(force=True)
        if self.spool is not None:
            self.spool.close()
