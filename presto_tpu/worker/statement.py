"""Coordinator statement protocol + dispatch queueing + resource groups.

The analog of the reference coordinator's query intake path:

  POST /v1/statement                    QueuedStatementResource.java:200
  GET  /v1/statement/queued/{id}/{slug}/{token}      queued polling :339
  GET  /v1/statement/executing/{id}/{slug}/{token}   ExecutingStatementResource.java:97
  DELETE ...                            client cancel
  GET  /v1/query, /v1/query/{id}        QueryResource (UI / ops listing)

with DispatchManager.java:70-style admission through resource groups
(InternalResourceGroupManager.java:84): each query is matched to a group by
(user, source) selectors; a group runs at most `hardConcurrencyLimit`
queries, queues at most `maxQueued` more (FIFO), and rejects beyond that —
the same semantics as the reference's static resource-group configs
(presto-resource-group-managers).

The client walks `nextUri` exactly like StatementClientV1.advance()
(StatementClientV1.java:359-372): queued URIs poll admission, the executing
URI streams result rows in chunks with a monotonically increasing token.
"""
from __future__ import annotations

import itertools
import json
import re
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from decimal import Decimal
from typing import Callable, Dict, List, Optional

from ..common.locks import OrderedLock

QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELED = "CANCELED"

_query_ids = itertools.count(1)


class QueryQueueFullError(RuntimeError):
    pass


class QueryMemoryLimitError(RuntimeError):
    """The query's memory estimate can NEVER be admitted (it exceeds the
    admission pool's total headroom) — immediate rejection, the reference
    coordinator's INSUFFICIENT_RESOURCES."""


@dataclass
class ResourceGroupSpec:
    name: str
    hard_concurrency_limit: int = 10
    max_queued: int = 100
    # fair-share weight (reference schedulingWeight): under a global
    # concurrency cap, a group with weight 2 is admitted twice as often
    # as a weight-1 group when both have queued work
    weight: float = 1.0


@dataclass
class Selector:
    """First matching selector wins (reference StaticSelector)."""
    group: str
    user: Optional[str] = None      # regex
    source: Optional[str] = None    # regex

    def matches(self, user: str, source: str) -> bool:
        if self.user and not re.fullmatch(self.user, user or ""):
            return False
        if self.source and not re.fullmatch(self.source, source or ""):
            return False
        return True


class ResourceGroupManager:
    """Admission control (InternalResourceGroupManager.java:84).

    Per-group: FIFO up to hard_concurrency_limit running, max_queued
    waiting, reject beyond.  Across groups, two serving-tier additions:

    - WEIGHTED FAIR SHARE (reference WEIGHTED_FAIR scheduling policy):
      under a global `total_concurrency` cap, each admission advances the
      group's virtual time by 1/weight; when capacity frees, the eligible
      group with the LEAST virtual time admits next.  Two groups with
      equal weights hammering the coordinator interleave ~1:1 regardless
      of arrival order; a weight-3 group gets ~3x the admissions.

    - MEMORY HEADROOM (reference ClusterMemoryManager / resource-group
      softMemoryLimit): admission holds each query's memory estimate
      against `memory_pool` (exec/memory.MemoryPool) capped at
      headroom_fraction * budget.  An estimate that can never fit rejects
      immediately (QueryMemoryLimitError); one that is only temporarily
      blocked queues until running queries release their claim.
    """

    DEFAULT_QUERY_MEMORY_ESTIMATE = 64 << 20

    def __init__(self, groups: Optional[List[ResourceGroupSpec]] = None,
                 selectors: Optional[List[Selector]] = None,
                 total_concurrency: Optional[int] = None,
                 memory_pool=None, headroom_fraction: float = 0.8,
                 query_memory_estimate: Optional[int] = None):
        self.groups = {g.name: g for g in (groups or [])}
        if "global" not in self.groups:
            self.groups["global"] = ResourceGroupSpec("global")
        self.selectors = list(selectors or [])
        self.total_concurrency = total_concurrency
        self.memory_pool = memory_pool
        self.headroom_fraction = headroom_fraction
        self.query_memory_estimate = (
            query_memory_estimate if query_memory_estimate is not None
            else self.DEFAULT_QUERY_MEMORY_ESTIMATE)
        self._running: Dict[str, set] = {n: set() for n in self.groups}
        self._queues: Dict[str, deque] = {n: deque() for n in self.groups}
        self._vtime: Dict[str, float] = {n: 0.0 for n in self.groups}
        self._total_running = 0
        self._mem_admitted = 0
        # rank 12: admission reads the memory pool's gauges but never
        # acquires its lock; sits between dispatch (10) and tasks (14)
        self._lock = OrderedLock("resource-groups", 12)  # lint: guarded-by(_lock)

    def select(self, user: str, source: str) -> str:
        for s in self.selectors:
            if s.matches(user, source) and s.group in self.groups:
                return s.group
        return "global"

    # -- admission --------------------------------------------------------
    def _mem_cap(self) -> Optional[int]:
        if self.memory_pool is None or self.memory_pool.budget is None:
            return None
        return int(self.memory_pool.budget * self.headroom_fraction)

    def _estimate(self, query: "ManagedQuery") -> int:
        est = getattr(query, "memory_estimate", None)
        return est if est is not None else self.query_memory_estimate

    def _mem_used(self) -> int:
        """The claim admission holds new queries against: the larger of
        the admission-time estimates and the pool's LIVE arbitrated
        accounting (reserved + revocable) — a running query whose actual
        reservations outgrew its estimate shrinks the headroom for
        everyone else, exactly like the reference ClusterMemoryManager
        tracking real pool reservation, not estimates."""
        live = (self.memory_pool.total_reserved
                if self.memory_pool is not None
                and hasattr(self.memory_pool, "total_reserved") else 0)
        return max(self._mem_admitted, live)

    def _can_run_locked(self, g: str, est: int) -> bool:
        if len(self._running[g]) >= self.groups[g].hard_concurrency_limit:
            return False
        if self.total_concurrency is not None \
                and self._total_running >= self.total_concurrency:
            return False
        cap = self._mem_cap()
        if cap is not None and self._mem_used() + est > cap:
            return False
        return True

    def _admit_locked(self, query: "ManagedQuery", est: int) -> None:
        g = query.resource_group
        self._running[g].add(query.query_id)
        self._total_running += 1
        self._mem_admitted += est
        query._admitted_bytes = est
        # virtual-time fair queueing: each admission costs 1/weight of
        # virtual service, so min-vtime selection interleaves groups in
        # proportion to their weights
        self._vtime[g] += 1.0 / max(self.groups[g].weight, 1e-9)

    def admit(self, query: "ManagedQuery") -> bool:
        """True = run now; False = queued.  Raises QueryQueueFullError on
        a full queue (reference QUERY_QUEUE_FULL) and
        QueryMemoryLimitError when the memory estimate exceeds the
        admission pool's total headroom (can never run)."""
        g = query.resource_group
        spec = self.groups[g]
        est = self._estimate(query)
        with self._lock:
            cap = self._mem_cap()
            if cap is not None and est > cap:
                raise QueryMemoryLimitError(
                    f"query memory estimate {est} bytes exceeds the "
                    f"admission headroom {cap} bytes "
                    f"({self.headroom_fraction:g} of pool budget "
                    f"{self.memory_pool.budget})")
            if self._can_run_locked(g, est):
                self._admit_locked(query, est)
                return True
            if len(self._queues[g]) >= spec.max_queued:
                raise QueryQueueFullError(
                    f"Too many queued queries for {g!r} "
                    f"(maxQueued {spec.max_queued})")
            self._queues[g].append(query)
            return False

    def release(self, query: "ManagedQuery") -> List["ManagedQuery"]:
        """Free the slot + memory claim; admit every now-eligible queued
        query, fair-share order (least virtual time first).  Returns the
        admitted queries — one release can unblock several when it was
        the memory claim, not a concurrency slot, that gated them."""
        with self._lock:
            g = query.resource_group
            if query.query_id in self._running[g]:
                self._running[g].discard(query.query_id)
                self._total_running -= 1
                self._mem_admitted -= getattr(
                    query, "_admitted_bytes", self._estimate(query))
            admitted: List["ManagedQuery"] = []
            while True:
                best = None
                for name, qd in self._queues.items():
                    while qd and qd[0].state != QUEUED:
                        qd.popleft()      # cancelled while queued
                    if not qd or not self._can_run_locked(
                            name, self._estimate(qd[0])):
                        continue
                    if best is None \
                            or self._vtime[name] < self._vtime[best]:
                        best = name
                if best is None:
                    return admitted
                nxt = self._queues[best].popleft()
                self._admit_locked(nxt, self._estimate(nxt))
                admitted.append(nxt)

    def remove_queued(self, query: "ManagedQuery") -> None:
        with self._lock:
            try:
                self._queues[query.resource_group].remove(query)
            except ValueError:
                pass

    def info(self) -> dict:
        with self._lock:
            out = {n: {"running": len(self._running[n]),
                       "queued": len(self._queues[n]),
                       "hardConcurrencyLimit":
                           self.groups[n].hard_concurrency_limit,
                       "maxQueued": self.groups[n].max_queued,
                       "weight": self.groups[n].weight,
                       "virtualTime": self._vtime[n]}
                   for n in self.groups}
            pool = self.memory_pool
            out["__admission"] = {
                "totalRunning": self._total_running,
                "totalConcurrency": self.total_concurrency,
                "memoryAdmittedBytes": self._mem_admitted,
                "memoryHeadroomBytes": self._mem_cap(),
                # live arbitrated accounting (what _can_run_locked gates
                # on, and what /v1/cluster reports as reservedMemoryBytes)
                "memoryReservedBytes": (
                    getattr(pool, "reserved", 0) if pool is not None else 0),
                "memoryRevocableBytes": (
                    getattr(pool, "revocable", 0)
                    if pool is not None else 0),
            }
            return out


@dataclass
class StreamingResult:
    """Executor return value for streamed results: rows are pulled chunk
    by chunk as the client advances tokens, so the coordinator never
    materializes the full result set (reference Query.java streams from
    the root-stage buffer via its ExchangeClient)."""
    columns: List[dict]
    row_iter: object            # iterator of JSON-ready row lists
    stats: object = None        # RuntimeStats-like (to_dict), read at drain


@dataclass
class ManagedQuery:
    query_id: str
    sql: str
    user: str
    source: str
    session: Dict[str, str]
    catalog: str
    schema: str
    resource_group: str = "global"
    # server-side prepared statements visible to this request
    # (X-Presto-Prepared-Statement headers, QueryPreparer analog)
    prepared: Dict[str, str] = field(default_factory=dict)
    added_prepare: Optional[tuple] = None       # (name, text) from PREPARE
    deallocated_prepare: Optional[str] = None   # name from DEALLOCATE
    slug: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    state: str = QUEUED
    error: Optional[str] = None
    columns: Optional[List[dict]] = None
    rows: Optional[list] = None
    runtime_stats: Optional[dict] = None
    # observability: the query's trace token (minted at submit or taken
    # from the client's X-Presto-Trace-Token) and the stage/task/operator
    # drill-down captured by the executor for /v1/query/{id}
    trace_token: str = ""
    query_info_extra: Optional[dict] = None
    peak_memory_bytes: int = 0
    # per-query device profiler capture dir (telemetry/profiler.py),
    # surfaced on /v1/query/{id} and the history record
    profile_trace_dir: Optional[str] = None
    created_at: float = field(default_factory=time.time)
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)
    _cancelled: bool = False
    _admitted: bool = False     # holds a resource-group running slot
    memory_estimate: Optional[int] = None   # admission claim, bytes
    _admitted_bytes: int = 0    # what admission actually reserved
    # streaming result state (StreamingResult executors)
    _row_iter: object = None
    _stats_src: object = None
    _iter_lock: threading.Lock = field(default_factory=threading.Lock)
    _chunks: dict = field(default_factory=dict)
    _max_token: int = -1
    _drained: bool = False
    rows_served: int = 0
    last_access: float = field(default_factory=time.time)

    def stats(self) -> dict:
        now = self.finished_at or time.time()
        return {
            "state": self.state,
            "queued": self.state == QUEUED,
            "scheduled": self.state not in (QUEUED,),
            "queuedTimeMillis": int(
                ((self.started_at or now) - self.created_at) * 1000),
            "elapsedTimeMillis": int((now - self.created_at) * 1000),
            "resourceGroup": self.resource_group,
        }


class DispatchManager:
    """Query registry + admission + async execution
    (DispatchManager.java:70, createQueryInternal :260)."""

    RESULT_CHUNK_ROWS = 4096
    MAX_QUERY_HISTORY = 200

    def __init__(self, executor: Callable[["ManagedQuery"], "object"],
                 resource_groups: Optional[ResourceGroupManager] = None,
                 events=None, history=None):
        """executor(query) runs the SQL and returns an exec.runner
        QueryResult (column_names / column_types / rows).  `events` is an
        EventListenerManager receiving created/completed events (the
        QueryMonitor analog, QueryMonitor.java:106).  `history` is an
        optional telemetry.history.QueryHistoryStore consulted at
        admission time (adaptive.history-sizing): a repeat of a recorded
        query seeds its memory claim from the observed peak instead of
        the flat default estimate."""
        from .events import EventListenerManager
        self._executor = executor
        self.resource_groups = resource_groups or ResourceGroupManager()
        self.events = events or EventListenerManager()
        self.history = history
        self._queries: Dict[str, ManagedQuery] = {}
        # rank 10: the outermost lock in the intake path — held only for
        # registry mutation, released before admission (12) or task work
        self._lock = OrderedLock("dispatch-manager", 10)  # lint: guarded-by(_lock)

    # -- intake -----------------------------------------------------------
    # a streaming query whose client stopped polling is canceled so its
    # resource-group slot frees (the reference's client abandonment
    # timeout, query.client.timeout)
    ABANDONED_AFTER_S = 300.0

    def _reap_abandoned(self) -> None:
        now = time.time()
        with self._lock:
            stale = [q for q in self._queries.values()
                     if q._row_iter is not None and not q.done.is_set()
                     and now - q.last_access > self.ABANDONED_AFTER_S]
        for q in stale:
            q._cancelled = True
            self._finish(q, CANCELED, "client abandoned the query")

    def submit(self, sql: str, user: str = "user", source: str = "",
               session: Optional[Dict[str, str]] = None,
               catalog: str = "tpch", schema: str = "sf0.01",
               prepared: Optional[Dict[str, str]] = None,
               trace_token: str = "") -> ManagedQuery:
        self._reap_abandoned()
        qid = f"{time.strftime('%Y%m%d_%H%M%S')}_{next(_query_ids):05d}"
        q = ManagedQuery(qid, sql, user, source, dict(session or {}),
                         catalog, schema, prepared=dict(prepared or {}))
        q.resource_group = self.resource_groups.select(user, source)
        # honor a client-supplied trace token (X-Presto-Trace-Token), else
        # mint one from the query id.  Kept OFF q.session: the executor's
        # runner cache is keyed by session items, and a per-query token
        # there would defeat plan/runner reuse.  The executor hands it to
        # the distributed runner out-of-band.
        q.trace_token = (trace_token or q.session.get("trace_token")
                         or f"trace-{qid}")
        est = (session or {}).get("query_memory_bytes")
        if est is not None:
            try:
                q.memory_estimate = max(0, int(est))
            except (TypeError, ValueError):
                pass
        if q.memory_estimate is None:
            self._seed_estimate_from_history(q)
        from .events import QueryCreatedEvent
        self.events.query_created(QueryCreatedEvent(
            query_id=qid, sql=sql, user=user, source=source,
            resource_group=q.resource_group, catalog=catalog,
            schema=schema, create_time=q.created_at))
        with self._lock:
            self._queries[qid] = q
            if len(self._queries) > self.MAX_QUERY_HISTORY:
                for k in list(self._queries)[:len(self._queries)
                                             - self.MAX_QUERY_HISTORY]:
                    old = self._queries[k]
                    if old.done.is_set():
                        del self._queries[k]
        try:
            if self.resource_groups.admit(q):
                q._admitted = True
                self._start(q)
        except (QueryQueueFullError, QueryMemoryLimitError) as e:
            # through _finish so the completed event fires (the reference
            # emits an immediate-failure event for queue rejection /
            # INSUFFICIENT_RESOURCES)
            self._finish(q, FAILED, str(e))
        return q

    def _seed_estimate_from_history(self, q: ManagedQuery) -> None:
        """adaptive.history-sizing at the admission gate: a repeat of a
        recorded query claims ~1.5x its last observed peak instead of the
        flat default estimate — small queries stop over-claiming headroom
        and large ones stop sneaking under the cap.  Opt-in per session
        (adaptive_history_sizing); text-keyed because admission runs
        before planning, so no plan template exists yet."""
        if self.history is None:
            return
        if str(q.session.get("adaptive_history_sizing", "")) \
                .strip().lower() not in ("true", "1"):
            return
        try:
            recs = self.history.list(state="FINISHED")
        except Exception:   # noqa: BLE001 — sizing is advisory
            return
        for rec in recs:
            peak = rec.get("peakMemoryBytes")
            if rec.get("query") == q.sql and peak:
                q.memory_estimate = max(1 << 20, int(int(peak) * 1.5))
                from ..exec.adaptive import ADAPTIVE_METRICS
                ADAPTIVE_METRICS.incr("history_sized_queries")
                return

    def _start(self, q: ManagedQuery) -> None:
        t = threading.Thread(target=self._run, args=(q,),
                             name=f"query-{q.query_id}", daemon=True)
        t.start()

    MAX_RETRIES = 2

    def _run(self, q: ManagedQuery) -> None:
        if q._cancelled:
            self._finish(q, CANCELED, None)
            return
        q.state = RUNNING
        q.started_at = time.time()
        attempt = 0
        while True:
            try:
                result = self._executor(q)
                if isinstance(result, StreamingResult):
                    # rows are pulled lazily by executing_response; the
                    # query finishes (and frees its resource-group slot)
                    # when the client drains the iterator
                    q.columns = result.columns
                    q._stats_src = result.stats
                    q._row_iter = iter(result.row_iter)
                    return
                q.columns = [{"name": n, "type": str(t)}
                             for n, t in zip(result.column_names,
                                             result.column_types)]
                q.rows = [[_json_value(v) for v in row]
                          for row in result.rows]
                q.runtime_stats = getattr(result, "runtime_stats", None)
                q.peak_memory_bytes = int(
                    getattr(result, "peak_memory_bytes", 0) or 0)
                q.profile_trace_dir = getattr(
                    result, "profile_trace_dir", None)
                q.added_prepare = getattr(result, "added_prepare", None)
                q.deallocated_prepare = getattr(
                    result, "deallocated_prepare", None)
                self._finish(q, CANCELED if q._cancelled else FINISHED,
                             None)
                return
            except Exception as e:  # noqa: BLE001 — becomes client error
                # transient infrastructure failures retry the whole query
                # (the ErrorClassifier analog, presto-spark-base
                # ErrorClassifier.java: worker death / connection loss is
                # retryable, user errors are not).  Writes never retry: a
                # partially-committed INSERT/CTAS re-executed would
                # duplicate data.
                word = q.sql.lstrip()[:6].lower()
                is_write = word.startswith(("create", "insert", "drop"))
                if _is_retryable(e) and not is_write \
                        and attempt < self.MAX_RETRIES \
                        and not q._cancelled:
                    attempt += 1
                    time.sleep(0.2 * attempt)
                    continue
                self._finish(q, FAILED, f"{type(e).__name__}: {e}")
                return

    def _finish(self, q: ManagedQuery, state: str, error: Optional[str]):
        if q.done.is_set():
            return
        q.state = state
        if state == CANCELED and error is None:
            error = "Query was canceled"   # clients must not see success
        q.error = error
        q.finished_at = time.time()
        q.done.set()
        from .events import QueryCompletedEvent
        now = q.finished_at
        self.events.query_completed(QueryCompletedEvent(
            query_id=q.query_id, sql=q.sql, user=q.user, state=state,
            create_time=q.created_at, end_time=now,
            wall_time_s=now - q.created_at,
            queued_time_s=(q.started_at or now) - q.created_at,
            rows=(q.rows_served if q._row_iter is not None
                  else len(q.rows or [])),
            error=error,
            runtime_stats=q.runtime_stats,
            peak_memory_bytes=q.peak_memory_bytes,
            trace_token=q.trace_token,
            resource_group=q.resource_group))
        # only a query that held a running slot frees one; cancelling a
        # QUEUED query must not over-admit past hardConcurrencyLimit
        if q._admitted:
            for nxt in self.resource_groups.release(q):
                nxt._admitted = True
                self._start(nxt)

    # -- lookup / cancel --------------------------------------------------
    def get(self, query_id: str) -> ManagedQuery:
        with self._lock:
            return self._queries[query_id]

    def cancel(self, query_id: str) -> None:
        q = self.get(query_id)
        q._cancelled = True
        if q.state == QUEUED:
            self.resource_groups.remove_queued(q)
            self._finish(q, CANCELED, None)

    def list_queries(self) -> List[dict]:
        with self._lock:
            qs = list(self._queries.values())
        return [{"queryId": q.query_id, "state": q.state,
                 "query": q.sql, "user": q.user,
                 "resourceGroup": q.resource_group,
                 **({"errorMessage": q.error} if q.error else {})}
                for q in qs]

    # -- protocol responses ----------------------------------------------
    def queued_response(self, q: ManagedQuery, token: int,
                        base_uri: str, wait_s: float = 0.1) -> dict:
        if q.state == QUEUED:
            q.done.wait(wait_s)
        resp = {"id": q.query_id,
                "infoUri": f"{base_uri}/v1/query/{q.query_id}",
                "stats": q.stats()}
        if q.state == QUEUED:
            resp["nextUri"] = (f"{base_uri}/v1/statement/queued/"
                               f"{q.query_id}/{q.slug}/{token + 1}")
        elif q.state in (FAILED, CANCELED) and q.rows is None:
            if q.error:
                resp["error"] = {
                    "message": q.error,
                    "errorName": ("USER_CANCELED" if q.state == CANCELED
                                  else "QUERY_FAILED")}
        else:
            resp["nextUri"] = (f"{base_uri}/v1/statement/executing/"
                               f"{q.query_id}/{q.slug}/0")
        return resp

    # chunks retained behind the client's token (re-GET of the current
    # token must work; anything older is gone, like the reference's
    # acknowledged pages)
    _CHUNK_KEEP = 2

    def _ensure_chunk(self, q: ManagedQuery, token: int) -> None:
        """Pull rows from the streaming iterator until chunk `token`
        exists or the stream is drained; forget acknowledged chunks."""
        while not q._drained and q._max_token < token:
            rows = list(itertools.islice(q._row_iter,
                                         self.RESULT_CHUNK_ROWS))
            if not rows:
                q._drained = True
                if q._stats_src is not None:
                    q.runtime_stats = q._stats_src.to_dict()
                break
            q._max_token += 1
            q._chunks[q._max_token] = rows
            q.rows_served += len(rows)
        for t in [t for t in q._chunks if t < token - self._CHUNK_KEEP + 1]:
            del q._chunks[t]

    def _executing_streaming(self, q: ManagedQuery, token: int,
                             base_uri: str) -> dict:
        resp = {"id": q.query_id,
                "infoUri": f"{base_uri}/v1/query/{q.query_id}",
                "stats": q.stats()}
        if q._cancelled and not q.done.is_set():
            self._finish(q, CANCELED, None)
        if not (q._cancelled or q.done.is_set()):
            with q._iter_lock:
                try:
                    self._ensure_chunk(q, token)
                except Exception as e:  # noqa: BLE001 — surfaces to client
                    self._finish(q, FAILED, f"{type(e).__name__}: {e}")
        if q.state in (FAILED, CANCELED):
            if q.error:
                resp["error"] = {
                    "message": q.error,
                    "errorName": ("USER_CANCELED" if q.state == CANCELED
                                  else "QUERY_FAILED")}
            return resp
        resp["columns"] = q.columns
        chunk = q._chunks.get(token)
        if chunk:
            resp["data"] = chunk
        if q._drained and token >= q._max_token:
            self._finish(q, FINISHED, None)
            resp["stats"] = q.stats()     # reflect the final state
        else:
            resp["nextUri"] = (f"{base_uri}/v1/statement/executing/"
                               f"{q.query_id}/{q.slug}/{token + 1}")
        return resp

    def executing_response(self, q: ManagedQuery, token: int,
                           base_uri: str, wait_s: float = 0.5) -> dict:
        q.last_access = time.time()
        if q._row_iter is not None:
            return self._executing_streaming(q, token, base_uri)
        if not q.done.is_set():
            q.done.wait(wait_s)
        resp = {"id": q.query_id,
                "infoUri": f"{base_uri}/v1/query/{q.query_id}",
                "stats": q.stats()}
        if not q.done.is_set():
            # still running: poll the same token
            resp["nextUri"] = (f"{base_uri}/v1/statement/executing/"
                               f"{q.query_id}/{q.slug}/{token}")
            return resp
        if q.state in (FAILED, CANCELED):
            if q.error:
                resp["error"] = {
                    "message": q.error,
                    "errorName": ("USER_CANCELED" if q.state == CANCELED
                                  else "QUERY_FAILED")}
            return resp
        lo = token * self.RESULT_CHUNK_ROWS
        hi = lo + self.RESULT_CHUNK_ROWS
        resp["columns"] = q.columns
        if lo < len(q.rows):
            resp["data"] = q.rows[lo:hi]
        if hi < len(q.rows):
            resp["nextUri"] = (f"{base_uri}/v1/statement/executing/"
                               f"{q.query_id}/{q.slug}/{token + 1}")
        return resp


def _is_retryable(e: Exception) -> bool:
    """Worker/connection failures are retryable; planning, semantic, and
    storage errors are the user's.  Delegates to the shared error
    classifier (common/errors.py, the ErrorClassifier.java analog) so the
    statement layer, the HTTP coordinator, and the batch scheduler agree
    on one taxonomy.  Planning errors raised coordinator-side (before any
    task ran) arrive untyped; the classifier's USER_ERROR shape check
    (ValueError/TypeError/KeyError/...) keeps them fail-fast, and query
    text that only references a dead cluster stays retryable."""
    from ..common.errors import INTERNAL_ERROR, classify_exception
    et = classify_exception(e)
    if et != INTERNAL_ERROR:
        from ..common.errors import is_retryable_type
        return is_retryable_type(et)
    # untagged INTERNAL_ERROR: an engine exception whose retryability the
    # type system cannot prove — retry only message shapes known to be
    # cluster-transient (the pre-classifier behavior)
    msg = str(e).lower()
    return any(s in msg for s in ("connection refused", "no live workers",
                                  "node is shutting down", "timed out",
                                  "remote task failed",
                                  "retry attempt", "unreachable"))


def _json_value(v):
    if isinstance(v, Decimal):
        return str(v)
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)
