"""SMILE binary JSON codec (decode + encode).

The coordinator's binary transport: HttpRemoteTask POSTs task updates
and reads TaskStatus/TaskInfo as `application/x-jackson-smile` when
binary transport is enabled (HttpRemoteTask.java:915-931 negotiation;
PrestoMediaTypes.APPLICATION_JACKSON_SMILE; airlift SmileCodec wraps
Jackson's SmileFactory).  This module implements the SMILE format
(https://github.com/FasterXML/smile-format-specification) for the JSON
value model the protocol uses: objects, arrays, strings, ints, doubles,
booleans, null — enough to decode every TaskUpdateRequest a coordinator
can send and encode every status/info response it reads back.

Layout essentials implemented here:
  header       ":)\\n" + options byte (bit0 shared keys, bit1 shared
               string values, bit2 raw binary)
  keys         0x20 empty; 0x30-0x33+byte long shared ref; 0x34 long
               unicode (0xFC-terminated); 0x40-0x7F short shared ref;
               0x80-0xBF short ASCII (len 1-64); 0xC0-0xF7 short Unicode
               (len 2-57); 0xFB END_OBJECT
  values       0x00-0x1F misc/shared-string refs; 0x20 ""; 0x21 null;
               0x22/0x23 false/true; 0x24/0x25 32/64-bit zigzag vints;
               0x26 BigInteger; 0x28/0x29 float/double (7-bit packed);
               0x2A BigDecimal; 0x40-0x5F tiny ASCII (1-32); 0x60-0x7F
               small ASCII (33-64); 0x80-0x9F tiny Unicode (2-33);
               0xA0-0xBF small Unicode (34-65); 0xC0-0xDF small ints
               (zigzag -16..15); 0xE0/0xE4 long ASCII/Unicode
               (0xFC-terminated); 0xE8 7-bit-packed binary; 0xF8/0xF9
               array start/end; 0xFA/0xFB object start/end
  vints        7 bits per byte; the FINAL byte has bit 7 set and carries
               the low 6 bits
Shared-name/value tables hold up to 1024 entries and reset on overflow,
matching Jackson's behavior.
"""
from __future__ import annotations

import struct
from typing import Any, List, Tuple

CONTENT_TYPE = "application/x-jackson-smile"

_HEADER = b":)\n"
_F_SHARED_NAMES = 0x01
_F_SHARED_VALUES = 0x02
_MAX_SHARED = 1024


class SmileError(ValueError):
    pass


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, buf: bytes):
        if not buf.startswith(_HEADER) or len(buf) < 4:
            raise SmileError("not a SMILE document (missing :)\\n header)")
        self.buf = buf
        self.pos = 4
        opts = buf[3]
        self.shared_names = bool(opts & _F_SHARED_NAMES)
        self.shared_values = bool(opts & _F_SHARED_VALUES)
        self.names: List[str] = []
        self.values: List[str] = []

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def take(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise SmileError("truncated SMILE document")
        self.pos += n
        return out

    def vint(self) -> int:
        """Unsigned vint: 7 bits/byte, final byte has bit 7 set and
        carries 6 bits."""
        v = 0
        while True:
            b = self.byte()
            if b & 0x80:
                return (v << 6) | (b & 0x3F)
            v = (v << 7) | b

    def zigzag_vint(self) -> int:
        v = self.vint()
        return (v >> 1) ^ -(v & 1)

    def until_fc(self) -> bytes:
        end = self.buf.index(0xFC, self.pos)
        out = self.buf[self.pos:end]
        self.pos = end + 1
        return out

    def packed7(self, nbytes: int) -> int:
        """Big-endian 7-bits-per-byte packing used for float/double."""
        v = 0
        for _ in range(nbytes):
            v = (v << 7) | (self.byte() & 0x7F)
        return v

    def _share_name(self, s: str) -> str:
        if self.shared_names and len(s.encode()) <= 64:
            if len(self.names) >= _MAX_SHARED:
                self.names = []
            self.names.append(s)
        return s

    def _share_value(self, s: str) -> str:
        if self.shared_values and len(s.encode()) <= 64:
            if len(self.values) >= _MAX_SHARED:
                self.values = []
            self.values.append(s)
        return s

    # -- tokens ----------------------------------------------------------
    def key(self):
        t = self.byte()
        if t == 0xFB:
            return None                       # END_OBJECT
        if t == 0x20:
            return ""
        if 0x30 <= t <= 0x33:                 # long shared ref
            return self.names[((t & 0x03) << 8) | self.byte()]
        if t == 0x34:                         # long unicode name
            return self._share_name(self.until_fc().decode("utf-8"))
        if 0x40 <= t <= 0x7F:                 # short shared ref
            return self.names[t - 0x40]
        if 0x80 <= t <= 0xBF:                 # short ASCII, len 1-64
            return self._share_name(self.take(t - 0x80 + 1).decode("ascii"))
        if 0xC0 <= t <= 0xF7:                 # short Unicode, len 2-57
            return self._share_name(self.take(t - 0xC0 + 2).decode("utf-8"))
        raise SmileError(f"unknown key token {t:#x}")

    def value(self, t: int) -> Any:
        if 0x01 <= t <= 0x1F:                 # short shared value ref
            return self.values[t - 1]
        if 0x2C <= t <= 0x2F:                 # long shared value ref
            return self.values[((t & 0x03) << 8) | self.byte()]
        if t == 0x20:
            return ""
        if t == 0x21:
            return None
        if t == 0x22:
            return False
        if t == 0x23:
            return True
        if t in (0x24, 0x25):                 # 32/64-bit zigzag vint
            return self.zigzag_vint()
        if t == 0x26:                         # BigInteger
            n = self.vint()                   # ORIGINAL byte count
            raw = self.take(_packed7_len(n))
            return int.from_bytes(_unpack7(raw)[:n], "big", signed=True)
        if t == 0x28:                         # float (5 x 7 bits)
            bits = self.packed7(5) & 0xFFFFFFFF
            return struct.unpack(">f", struct.pack(">I", bits))[0]
        if t == 0x29:                         # double (10 x 7 bits)
            bits = self.packed7(10) & 0xFFFFFFFFFFFFFFFF
            return struct.unpack(">d", struct.pack(">Q", bits))[0]
        if t == 0x2A:                         # BigDecimal: scale + magn.
            scale = self.zigzag_vint()
            n = self.vint()                   # ORIGINAL byte count
            raw = self.take(_packed7_len(n))
            unscaled = int.from_bytes(_unpack7(raw)[:n], "big",
                                      signed=True)
            from decimal import Decimal
            return Decimal(unscaled).scaleb(-scale)
        if 0x40 <= t <= 0x5F:                 # tiny ASCII 1-32
            return self._share_value(self.take(t - 0x40 + 1).decode("ascii"))
        if 0x60 <= t <= 0x7F:                 # small ASCII 33-64
            return self._share_value(self.take(t - 0x60 + 33).decode("ascii"))
        if 0x80 <= t <= 0x9F:                 # tiny Unicode 2-33
            return self._share_value(self.take(t - 0x80 + 2).decode("utf-8"))
        if 0xA0 <= t <= 0xBF:                 # small Unicode 34-65
            return self._share_value(self.take(t - 0xA0 + 34).decode("utf-8"))
        if 0xC0 <= t <= 0xDF:                 # small int zigzag -16..15
            v = t - 0xC0
            return (v >> 1) ^ -(v & 1)
        if t == 0xE0:                         # long ASCII
            return self.until_fc().decode("ascii")
        if t == 0xE4:                         # long Unicode
            return self.until_fc().decode("utf-8")
        if t == 0xE8:                         # 7-bit packed binary
            n = self.vint()
            return _unpack7(self.take(_packed7_len(n)))[:n]
        if t == 0xF8:                         # array
            out = []
            while True:
                vt = self.byte()
                if vt == 0xF9:
                    return out
                out.append(self.value(vt))
        if t == 0xFA:                         # object
            obj = {}
            while True:
                k = self.key()
                if k is None:
                    return obj
                obj[k] = self.value(self.byte())
        raise SmileError(f"unknown value token {t:#x}")


def _packed7_len(n: int) -> int:
    """Packed byte count for n source bytes under the 7-bit packing."""
    full, rem = divmod(n, 7)
    return full * 8 + (rem + 1 if rem else 0)


def _unpack7(raw: bytes) -> bytes:
    """Inverse of SMILE's 7-bit byte packing, Jackson convention: 7
    source bytes per 8 packed bytes; a trailing group of n source bytes
    packs into n+1 bytes with the LAST packed byte carrying the low n
    bits right-aligned (SmileParser._read7BitBinaryWithLength: one
    trailing byte b arrives as [b>>1, b&0x01])."""
    out = bytearray()
    i = 0
    while i + 8 <= len(raw):
        v = 0
        for b in raw[i:i + 8]:
            v = (v << 7) | (b & 0x7F)
        out.extend(v.to_bytes(7, "big"))
        i += 8
    rem = len(raw) - i
    if rem > 1:
        n = rem - 1                      # decoded byte count
        v = 0
        for b in raw[i:i + n]:
            v = (v << 7) | (b & 0x7F)
        v = (v << n) | (raw[-1] & ((1 << n) - 1))
        out.extend(v.to_bytes(n, "big"))
    return bytes(out)


def decode(buf: bytes) -> Any:
    r = _Reader(buf)
    t = r.byte()
    return r.value(t)


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

class _Writer:
    def __init__(self, shared_names: bool = True):
        self.out = bytearray(_HEADER)
        self.out.append(_F_SHARED_NAMES if shared_names else 0)
        self.shared_names = shared_names
        self.names: dict = {}

    def vint(self, v: int) -> None:
        """Unsigned vint (final byte: bit 7 set, low 6 bits)."""
        last = 0x80 | (v & 0x3F)
        v >>= 6
        rest = []
        while v:
            rest.append(v & 0x7F)
            v >>= 7
        self.out.extend(reversed(rest))
        self.out.append(last)

    def zigzag_vint(self, v: int) -> None:
        self.vint(v * 2 if v >= 0 else -v * 2 - 1)

    def packed7(self, v: int, nbytes: int) -> None:
        for i in reversed(range(nbytes)):
            self.out.append((v >> (7 * i)) & 0x7F)

    def key(self, k: str) -> None:
        if k == "":
            self.out.append(0x20)
            return
        if self.shared_names:
            ref = self.names.get(k)
            if ref is not None:
                if ref < 64:
                    self.out.append(0x40 + ref)
                else:
                    self.out.append(0x30 + (ref >> 8))
                    self.out.append(ref & 0xFF)
                return
        raw = k.encode("utf-8")
        if len(raw) <= 64 and raw.isascii():
            self.out.append(0x80 + len(raw) - 1)
            self.out.extend(raw)
        elif 2 <= len(raw) <= 57:
            self.out.append(0xC0 + len(raw) - 2)
            self.out.extend(raw)
        else:
            self.out.append(0x34)
            self.out.extend(raw)
            self.out.append(0xFC)
        if self.shared_names and len(raw) <= 64:
            if len(self.names) >= _MAX_SHARED:
                self.names = {}
            self.names[k] = len(self.names)

    def value(self, v: Any) -> None:
        if v is None:
            self.out.append(0x21)
        elif v is False:
            self.out.append(0x22)
        elif v is True:
            self.out.append(0x23)
        elif isinstance(v, int):
            if -16 <= v <= 15:
                self.out.append(0xC0 + (v * 2 if v >= 0 else -v * 2 - 1))
            elif -(1 << 63) <= v < (1 << 63):
                self.out.append(0x24 if -(1 << 31) <= v < (1 << 31)
                                else 0x25)
                self.zigzag_vint(v)
            else:
                mag = v.to_bytes((v.bit_length() + 8) // 8, "big",
                                 signed=True)
                self.out.append(0x26)
                self.vint(len(mag))          # ORIGINAL byte count
                self.out.extend(_pack7(mag))
        elif isinstance(v, float):
            bits = struct.unpack(">Q", struct.pack(">d", v))[0]
            self.out.append(0x29)
            self.packed7(bits, 10)
        elif isinstance(v, str):
            raw = v.encode("utf-8")
            if not raw:
                self.out.append(0x20)
            elif raw.isascii():
                if len(raw) <= 32:
                    self.out.append(0x40 + len(raw) - 1)
                    self.out.extend(raw)
                elif len(raw) <= 64:
                    self.out.append(0x60 + len(raw) - 33)
                    self.out.extend(raw)
                else:
                    self.out.append(0xE0)
                    self.out.extend(raw)
                    self.out.append(0xFC)
            else:
                if 2 <= len(raw) <= 33:
                    self.out.append(0x80 + len(raw) - 2)
                    self.out.extend(raw)
                elif 34 <= len(raw) <= 65:
                    self.out.append(0xA0 + len(raw) - 34)
                    self.out.extend(raw)
                else:
                    self.out.append(0xE4)
                    self.out.extend(raw)
                    self.out.append(0xFC)
        elif isinstance(v, (list, tuple)):
            self.out.append(0xF8)
            for item in v:
                self.value(item)
            self.out.append(0xF9)
        elif isinstance(v, dict):
            self.out.append(0xFA)
            for k, item in v.items():
                self.key(str(k))
                self.value(item)
            self.out.append(0xFB)
        else:
            from decimal import Decimal
            if isinstance(v, Decimal):
                sign, digits, exp = v.as_tuple()
                unscaled = int(v.scaleb(-exp)) if exp <= 0 else int(v)
                scale = max(-exp, 0)
                mag = unscaled.to_bytes(
                    (unscaled.bit_length() + 8) // 8, "big", signed=True)
                self.out.append(0x2A)
                self.zigzag_vint(scale)
                self.vint(len(mag))          # ORIGINAL byte count
                self.out.extend(_pack7(mag))
            else:
                raise SmileError(f"cannot encode {type(v).__name__}")


def _pack7(raw: bytes) -> bytes:
    """SMILE 7-bit byte packing, Jackson convention: 7 source bytes -> 8
    packed bytes; a trailing group of n bytes -> n+1 packed bytes with
    the last byte carrying the low n bits right-aligned
    (SmileGenerator._write7BitBinaryWithLength)."""
    out = bytearray()
    i = 0
    while i + 7 <= len(raw):
        v = int.from_bytes(raw[i:i + 7], "big")
        for j in reversed(range(8)):
            out.append((v >> (7 * j)) & 0x7F)
        i += 7
    rem = len(raw) - i
    if rem:
        v = int.from_bytes(raw[i:], "big")       # 8*rem bits
        for j in reversed(range(rem)):
            out.append((v >> (rem + 7 * j)) & 0x7F)
        out.append(v & ((1 << rem) - 1))
    return bytes(out)


def encode(obj: Any, shared_names: bool = True) -> bytes:
    w = _Writer(shared_names=shared_names)
    w.value(obj)
    return bytes(w.out)
