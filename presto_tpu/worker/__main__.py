"""Worker process entry point.

    python -m presto_tpu.worker --http-port 8080 \
        --discovery-uri http://coordinator:8080 [--coordinator]

The analog of the native worker main (presto_cpp/main/PrestoMain.cpp /
PrestoServer::run, presto_cpp/main/PrestoServer.cpp:197): start the HTTP
task server, announce to discovery, serve until interrupted.
"""
from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="presto-tpu-worker")
    # None defaults distinguish "not given" from "given at default value"
    # so explicit flags always beat etc-dir file keys
    parser.add_argument("--http-port", type=int, default=None)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--discovery-uri", default=None)
    parser.add_argument("--coordinator", action="store_const", const=True,
                        default=None,
                        help="also host the embedded discovery service")
    parser.add_argument("--environment", default=None)
    parser.add_argument("--hive-warehouse", default=None, metavar="DIR",
                        help="mount a Parquet warehouse directory as the "
                             "'hive' catalog (CREATE TABLE AS / INSERT)")
    parser.add_argument("--etc-dir", default=None, metavar="DIR",
                        help="boot from an etc/ directory of "
                             "config.properties / node.properties / "
                             "catalog/*.properties (the reference's file "
                             "configuration layout); command-line flags "
                             "override file keys")
    args = parser.parse_args(argv)

    if args.hive_warehouse:
        from ..connectors import catalog, hive
        catalog.register_connector(
            "hive", hive.HiveConnector(args.hive_warehouse))

    # baseline defaults <- etc-dir file keys <- explicitly-given flags
    kwargs = dict(port=0, node_id=None, coordinator=False,
                  discovery_uri=None, environment="production")
    if args.etc_dir:
        from .properties import (register_catalogs_from_etc,
                                 server_kwargs_from_etc)
        file_kwargs, _props = server_kwargs_from_etc(args.etc_dir)
        register_catalogs_from_etc(args.etc_dir)
        kwargs.update(file_kwargs)
    for k, v in (("port", args.http_port), ("node_id", args.node_id),
                 ("coordinator", args.coordinator),
                 ("discovery_uri", args.discovery_uri),
                 ("environment", args.environment)):
        if v is not None:
            kwargs[k] = v
    if args.etc_dir:
        import os
        listener_path = os.path.join(args.etc_dir,
                                     "event-listener.properties")
        if os.path.exists(listener_path):
            from .events import EventListenerManager, FileEventListener
            from .properties import load_properties
            lp = load_properties(listener_path)
            name = lp.get("event-listener.name")
            if name != "file":
                # refuse to boot with a silently-dead audit log
                raise SystemExit(
                    f"unknown event-listener.name {name!r} in "
                    f"{listener_path}; supported: file")
            mgr = EventListenerManager()
            mgr.register(FileEventListener(
                lp.get("event-listener.path",
                       os.path.join(args.etc_dir, "events.jsonl"))))
            kwargs["events"] = mgr

    from .server import WorkerServer
    server = WorkerServer(**kwargs)
    print(f"presto-tpu worker {server.node_id} listening on {server.uri}",
          flush=True)

    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
