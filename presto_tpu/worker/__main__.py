"""Worker process entry point.

    python -m presto_tpu.worker --http-port 8080 \
        --discovery-uri http://coordinator:8080 [--coordinator]

The analog of the native worker main (presto_cpp/main/PrestoMain.cpp /
PrestoServer::run, presto_cpp/main/PrestoServer.cpp:197): start the HTTP
task server, announce to discovery, serve until interrupted.
"""
from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="presto-tpu-worker")
    parser.add_argument("--http-port", type=int, default=0)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--discovery-uri", default=None)
    parser.add_argument("--coordinator", action="store_true",
                        help="also host the embedded discovery service")
    parser.add_argument("--environment", default="production")
    parser.add_argument("--hive-warehouse", default=None, metavar="DIR",
                        help="mount a Parquet warehouse directory as the "
                             "'hive' catalog (CREATE TABLE AS / INSERT)")
    args = parser.parse_args(argv)

    if args.hive_warehouse:
        from ..connectors import catalog, hive
        catalog.register_connector(
            "hive", hive.HiveConnector(args.hive_warehouse))

    from .server import WorkerServer
    server = WorkerServer(port=args.http_port, node_id=args.node_id,
                          coordinator=args.coordinator,
                          discovery_uri=args.discovery_uri,
                          environment=args.environment)
    print(f"presto-tpu worker {server.node_id} listening on {server.uri}",
          flush=True)

    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
