"""Query event pipeline: created/completed events fanned out to pluggable
listeners.

The analog of the reference's QueryMonitor publishing QueryCreatedEvent /
QueryCompletedEvent to every registered EventListener
(presto-main-base/.../event/QueryMonitor.java:106,queryCreatedEvent and
:138,queryCompletedEvent; listener SPI at
presto-spi/.../eventlistener/EventListener.java).  Listener failures are
isolated: one broken listener must not fail the query or starve the other
listeners, matching EventListenerManager's dispatch.
"""
from __future__ import annotations

import json
import threading
import time
import traceback
from dataclasses import asdict, dataclass, field
from typing import List, Optional


@dataclass
class QueryCreatedEvent:
    """Reference QueryCreatedEvent: identity + context at intake."""
    query_id: str
    sql: str
    user: str
    source: str
    resource_group: str
    catalog: str
    schema: str
    create_time: float = field(default_factory=time.time)


@dataclass
class QueryCompletedEvent:
    """Reference QueryCompletedEvent: outcome + statistics at finish."""
    query_id: str
    sql: str
    user: str
    state: str                      # FINISHED | FAILED | CANCELED
    create_time: float
    end_time: float
    wall_time_s: float
    queued_time_s: float
    rows: int
    error: Optional[str] = None
    # rolled-up execution-wide RuntimeStats ({name: {sum, count, min, max}},
    # the reference QueryCompletedEvent's queryStats.runtimeStats) and the
    # query's peak MemoryPool reservation — both observability satellites;
    # defaulted so pre-existing listeners/tests keep constructing the event
    runtime_stats: Optional[dict] = None
    peak_memory_bytes: int = 0
    # identity context for downstream consumers (the telemetry history
    # store keys its durable records on these; the reference event carries
    # traceToken/resourceGroupId on QueryMetadata/QueryContext)
    trace_token: str = ""
    resource_group: str = ""


@dataclass
class TaskCompletedEvent:
    """Per-task terminal event from the WORKER execution path — the stats
    QueryMonitor.java:106 aggregates per task (splitCompletedEvent /
    TaskInfo final stats): identity, outcome, and the task-level counters
    the coordinator's UI drill-down reads."""
    task_id: str
    state: str                      # FINISHED | FAILED | CANCELED
    create_time: float
    end_time: float
    wall_time_s: float
    output_rows: int
    output_pages: int
    output_bytes: int
    peak_memory_bytes: int
    error: Optional[str] = None


class EventListener:
    """Listener SPI (EventListener.java): override any subset."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def task_completed(self, event: TaskCompletedEvent) -> None:
        pass


class FileEventListener(EventListener):
    """Append events as JSON lines — the simplest useful listener (audit
    log / test fixture), analogous to the file-based event-listener
    plugins shipped around the reference."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def _write(self, kind: str, event) -> None:
        line = json.dumps({"event": kind, **asdict(event)})
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._write("query_created", event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._write("query_completed", event)

    def task_completed(self, event: TaskCompletedEvent) -> None:
        self._write("task_completed", event)


class EventListenerManager:
    """Fan events out to every registered listener, isolating failures
    (EventListenerManager.java: a throwing listener is logged and
    skipped)."""

    def __init__(self):
        self._listeners: List[EventListener] = []
        self.dispatch_errors = 0

    def register(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def unregister(self, listener: EventListener) -> None:
        """Detach a listener (server shutdown detaches its history
        bridge so a closed store never sees another event)."""
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def _fire(self, method: str, event) -> None:
        for listener in self._listeners:
            try:
                getattr(listener, method)(event)
            except Exception:   # noqa: BLE001 — listener isolation
                self.dispatch_errors += 1
                traceback.print_exc()

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._fire("query_created", event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._fire("query_completed", event)

    def task_completed(self, event: TaskCompletedEvent) -> None:
        self._fire("task_completed", event)
