"""Durable spooled exchange storage for fault-tolerant execution
(retry-policy=task).

The analog of the reference's fault-tolerant execution exchange
(presto-main/.../exchange/LocalFileSystemExchangeStorage and the
spooling OutputBuffer written for retry-policy=TASK): every page a
stage produces is staged DURABLY before the producer acknowledges it,
and the spool outlives the producing task — a consumer (or a retried
consumer attempt) replays any token range long after the producer
finished, and a failed task can be retried ALONE because its inputs
still exist.

Built as a composition over the PR 15 two-tier spill design rather
than a new storage engine:

- tier 1 is host RAM: pages are LZ4-compressed on append and staged in
  memory, charged REVOCABLE to the owning task's MemoryContext, so the
  PR 15 arbitrator sees them and can reclaim them under pool pressure
  through the registered revoke callback;
- tier 2 is an append-only LZ4 block file under `spool.path` (falling
  back to `spill.path`, then the system temp dir) using the same
  length-prefixed record framing as the retained-buffer spill: staged
  pages overflow to it when the staging budget fills, when the
  arbitrator revokes, or when the worker begins a graceful drain
  (`flush()` — the block file survives the process exit).

Reads are token-indexed and tier-transparent: a record is decompressed
from RAM if still staged, else pread back from the block file, so the
exchange client's existing token-resume protocol needs no new wire
surface.
"""
from __future__ import annotations

import os
import re
import struct
import tempfile
import time
from typing import Dict, List, Optional

from ..common.compression import compress, decompress
from ..common.locks import OrderedLock

DEFAULT_STAGING_BUDGET_BYTES = 16 << 20

_SAFE_ID = re.compile(r"[^A-Za-z0-9._-]+")


class SpoolMetrics:
    """Process-wide spool counters (the /v1/metrics presto_tpu_spool_*
    section, same singleton shape as ExchangeMetrics/MemoryMetrics)."""

    _COUNTERS = ("spooled_pages", "spooled_bytes", "spooled_raw_bytes",
                 "disk_bytes", "read_pages", "read_bytes", "flushes",
                 "spools_opened", "spools_released", "spool_wall_s")
    _GAUGES = ("staged_bytes",)

    def __init__(self):
        # rank 100: metrics registries are leaf locks
        self._lock = OrderedLock("metrics:spool", 100)  # lint: guarded-by(_lock)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            for name in self._COUNTERS + self._GAUGES:
                setattr(self, name, 0)

    def incr(self, name: str, delta=1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + delta)

    def snapshot(self) -> dict:
        with self._lock:
            return {name: getattr(self, name)
                    for name in self._COUNTERS + self._GAUGES}


SPOOL_METRICS = SpoolMetrics()


class TaskSpool:
    """Durable page store for ONE task's output buffers.

    `append` returns only once the page is durably staged (compressed in
    host RAM charged revocable, or already on disk) — that return is the
    producer's acknowledgement point under retry-policy=task.  Records
    are retained past task completion until `close()` (query release or
    task eviction); `flush()` forces every staged record to the block
    file so a draining worker's spool survives its exit."""

    def __init__(self, task_id: str, n_buffers: int,
                 spool_dir: Optional[str] = None, memory=None,
                 staging_budget_bytes: int = DEFAULT_STAGING_BUDGET_BYTES):
        self.task_id = task_id
        self._dir = spool_dir or tempfile.gettempdir()
        self._memory = memory
        self._budget = max(0, int(staging_budget_bytes))
        # reentrant: append -> _charge_locked -> _flush_locked re-enters;
        # rank 32 sits between the output buffer (30) and the pool (40)
        self._lock = OrderedLock(
            "task-spool", 32, reentrant=True)  # lint: guarded-by(_lock)
        # token t of buffer b -> [raw_len, compressed_len, ram|None, offset]
        self._records: Dict[int, List[list]] = \
            {b: [] for b in range(max(1, n_buffers))}
        self._staged_bytes = 0            # compressed bytes resident in RAM
        self._spooled_bytes = 0           # cumulative raw bytes appended
        self._holder = None               # lazy revocable registration
        self._fd: Optional[int] = None
        self._path: Optional[str] = None
        self._end = 0                     # block-file append offset
        self._closed = False
        SPOOL_METRICS.incr("spools_opened")

    # -- producer side ----------------------------------------------------
    def append(self, buffer_id: int, data: bytes) -> int:
        """Durably stage one serialized page; returns its token."""
        t0 = time.perf_counter()
        cp = compress("LZ4", data)
        with self._lock:
            if self._closed:
                raise BufferError(f"spool for task {self.task_id} released")
            rec = [len(data), len(cp), cp, -1]
            self._records[buffer_id].append(rec)
            token = len(self._records[buffer_id]) - 1
            self._staged_bytes += len(cp)
            self._spooled_bytes += len(data)
            self._charge_locked(len(cp))
            if self._budget and self._staged_bytes > self._budget:
                self._flush_locked()
        SPOOL_METRICS.incr("spooled_pages")
        SPOOL_METRICS.incr("spooled_bytes", len(cp))
        SPOOL_METRICS.incr("spooled_raw_bytes", len(data))
        SPOOL_METRICS.incr("staged_bytes", len(cp))
        SPOOL_METRICS.incr("spool_wall_s", time.perf_counter() - t0)
        return token

    def _charge_locked(self, nb: int) -> None:
        if self._memory is None or nb <= 0:
            return
        if self._holder is None:
            self._holder = self._memory.register_revocable(
                "spool", self._revoke)
        if not self._holder.try_reserve(nb, arbitrate=False):
            # no revocable headroom: give the staged prefix to disk now
            # (self-spill, same discipline as the retained output buffer)
            self._flush_locked()

    def _revoke(self) -> int:
        """Arbitrator callback: flush every staged record to the block
        file.  Never blocks — a contended spool declines this pass."""
        if not self._lock.acquire(timeout=0.05):
            return 0
        try:
            return self._flush_locked()
        finally:
            self._lock.release()

    def _open_disk_locked(self) -> int:
        if self._fd is None:
            os.makedirs(self._dir, exist_ok=True)
            safe = _SAFE_ID.sub("_", self.task_id)[:80]
            self._fd, self._path = tempfile.mkstemp(
                prefix=f"presto-spool-{safe}-", suffix=".spool",
                dir=self._dir)
        return self._fd

    def _flush_locked(self) -> int:
        """Move every RAM-staged record to the block file (length-prefixed
        LZ4 records, append order) and free the revocable charge."""
        if self._closed:
            return 0
        chunks, freed = [], 0
        base = None
        for recs in self._records.values():
            for rec in recs:
                if rec[2] is None:
                    continue
                if base is None:
                    base = self._end
                rec[3] = self._end + 4
                chunks.append(struct.pack("<i", rec[1]) + rec[2])
                self._end += 4 + rec[1]
                freed += rec[1]
                rec[2] = None
        if not chunks:
            return 0
        os.pwrite(self._open_disk_locked(), b"".join(chunks), base)
        self._staged_bytes -= freed
        if self._holder is not None:
            self._holder.free(freed)
        from ..exec.memory import MEMORY_METRICS
        MEMORY_METRICS.incr("spilled_bytes", freed)
        MEMORY_METRICS.incr("disk_spilled_bytes", freed)
        if self._memory is not None:
            self._memory.note_spill(freed)
            self._memory.note_disk_spill(freed)
        SPOOL_METRICS.incr("flushes")
        SPOOL_METRICS.incr("disk_bytes", freed)
        SPOOL_METRICS.incr("staged_bytes", -freed)
        return freed

    def flush(self) -> int:
        """Force-stage everything to the block file (graceful drain: the
        spool must survive the process exit).  Returns bytes flushed."""
        with self._lock:
            return self._flush_locked()

    # -- consumer side ----------------------------------------------------
    def page_count(self, buffer_id: int) -> int:
        with self._lock:
            return len(self._records.get(buffer_id, ()))

    def read(self, buffer_id: int, token: int) -> bytes:
        """One page back, tier-transparently (RAM decompress or disk
        pread).  IndexError past the appended range."""
        with self._lock:
            rec = self._records[buffer_id][token]
            raw_len, clen, ram, offset = rec
            payload = ram if ram is not None \
                else os.pread(self._fd, clen, offset)
        data = decompress("LZ4", payload, raw_len)
        SPOOL_METRICS.incr("read_pages")
        SPOOL_METRICS.incr("read_bytes", raw_len)
        if ram is None:
            from ..exec.memory import MEMORY_METRICS
            MEMORY_METRICS.incr("unspilled_bytes", raw_len)
            if self._memory is not None:
                self._memory.note_unspill(raw_len)
        return data

    # -- accounting / lifecycle -------------------------------------------
    @property
    def spooled_bytes(self) -> int:
        """Cumulative raw page bytes appended (TaskInfo spooledBytes)."""
        with self._lock:
            return self._spooled_bytes

    @property
    def staged_bytes(self) -> int:
        with self._lock:
            return self._staged_bytes

    @property
    def disk_path(self) -> Optional[str]:
        with self._lock:
            return self._path

    def close(self) -> None:
        """Release everything (query done / task evicted): free the
        revocable charge, drop staged pages, unlink the block file."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._staged_bytes:
                SPOOL_METRICS.incr("staged_bytes", -self._staged_bytes)
            self._staged_bytes = 0
            self._records = {}
            if self._holder is not None:
                self._holder.close()
                self._holder = None
            if self._fd is not None:
                try:
                    os.close(self._fd)
                    os.unlink(self._path)
                except OSError:
                    pass
                self._fd = None
        SPOOL_METRICS.incr("spools_released")
