"""Coordinator<->worker protocol DTOs (JSON).

Mirrors the reference task protocol surface (presto-main-base/.../server/
TaskUpdateRequest.java:37, TaskStatus/TaskInfo; native codegen mirror
presto-native-execution/presto_cpp/presto_protocol/) scoped to the fields the
TPU worker consumes: the plan fragment rides base64-encoded inside the update
request exactly like HttpRemoteTask.sendUpdate builds it
(presto-main/.../server/remotetask/HttpRemoteTask.java:883-889).
"""
from __future__ import annotations

import base64
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..spi import plan as P

# Task states (reference TaskState.java)
PLANNED = "PLANNED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
CANCELED = "CANCELED"
ABORTED = "ABORTED"
FAILED = "FAILED"

DONE_STATES = {FINISHED, CANCELED, ABORTED, FAILED}


@dataclass
class TaskSource:
    """Splits for one plan node (reference TaskSource.java).  A split is
    either a connector split dict or a remote-location dict
    ({"remote": true, "location": ".../results/<buffer>"}) feeding a
    RemoteSourceNode, matching how the reference ships remote splits to the
    ExchangeOperator."""
    plan_node_id: str
    splits: List[dict] = field(default_factory=list)
    no_more_splits: bool = True

    def to_dict(self):
        return {"planNodeId": self.plan_node_id, "splits": self.splits,
                "noMoreSplits": self.no_more_splits}

    @staticmethod
    def from_dict(d):
        return TaskSource(d["planNodeId"], d.get("splits", []),
                          d.get("noMoreSplits", True))


@dataclass
class OutputBuffersSpec:
    """Which output buffers a task must expose (reference OutputBuffers):
    PARTITIONED -> buffer i holds hash partition i; BROADCAST -> every buffer
    holds the full output; one buffer per consumer task either way."""
    type: str                      # "PARTITIONED" | "BROADCAST"
    n_buffers: int = 1
    partition_keys: List[str] = field(default_factory=list)

    def to_dict(self):
        return {"type": self.type, "nBuffers": self.n_buffers,
                "partitionKeys": self.partition_keys}

    @staticmethod
    def from_dict(d):
        return OutputBuffersSpec(d["type"], d.get("nBuffers", 1),
                                 d.get("partitionKeys", []))


@dataclass
class TaskUpdateRequest:
    task_id: str
    task_index: int
    fragment_b64: Optional[str]    # base64(json(PlanFragment))
    sources: List[TaskSource]
    output_buffers: OutputBuffersSpec
    session: Dict[str, str] = field(default_factory=dict)
    # reference TaskUpdateRequest.tableWriteInfo (presto_protocol_core.h:726):
    # the writer target a TableWriterNode in the fragment commits into
    table_write_info: Optional[dict] = None
    # runtime dynamic-filter summaries pushed by the coordinator once the
    # build-side stage completes (filter id -> DynamicFilterSummary wire
    # dict, exec/adaptive.py) — the analog of the reference coordinator's
    # DynamicFilterService fan-out to waiting scan tasks
    dynamic_filters: Optional[Dict[str, dict]] = None

    @staticmethod
    def make(task_id: str, task_index: int, fragment: P.PlanFragment,
             sources: List[TaskSource], output_buffers: OutputBuffersSpec,
             session: Optional[Dict[str, str]] = None) -> "TaskUpdateRequest":
        raw = json.dumps(fragment.to_dict()).encode()
        return TaskUpdateRequest(task_id, task_index,
                                 base64.b64encode(raw).decode(),
                                 sources, output_buffers, session or {})

    def fragment(self) -> P.PlanFragment:
        raw = base64.b64decode(self.fragment_b64)
        d = json.loads(raw)
        from .plan_translation import is_reference_fragment, translate_fragment
        if is_reference_fragment(d):
            # a Java-coordinator-shaped fragment (PrestoToVeloxQueryPlan
            # seam): translate the reference plan-node/RowExpression JSON
            return translate_fragment(d, self.table_write_info)
        return P.PlanFragment.from_dict(d)

    def to_dict(self):
        out = {"taskId": self.task_id, "taskIndex": self.task_index,
               "fragment": self.fragment_b64,
               "sources": [s.to_dict() for s in self.sources],
               "outputBuffers": self.output_buffers.to_dict(),
               "session": self.session}
        if self.table_write_info is not None:
            out["tableWriteInfo"] = self.table_write_info
        if self.dynamic_filters is not None:
            out["dynamicFilters"] = self.dynamic_filters
        return out

    @staticmethod
    def from_dict(d):
        return TaskUpdateRequest(
            d["taskId"], d.get("taskIndex", 0), d.get("fragment"),
            [TaskSource.from_dict(s) for s in d.get("sources", [])],
            OutputBuffersSpec.from_dict(d["outputBuffers"]),
            d.get("session", {}), d.get("tableWriteInfo"),
            d.get("dynamicFilters"))


def from_reference_update(task_id: str, d: dict) -> "TaskUpdateRequest":
    """Accept an HttpRemoteTask-shaped TaskUpdateRequest
    (presto_protocol_core.h:807: session/extraCredentials/fragment/
    sources/outputIds/tableWriteInfo) and map it onto the worker's compact
    internal request.  Output partitioning keys are not carried by the
    reference OutputBuffers — the task derives them from the fragment's
    partitioning scheme (same seam as PrestoToVeloxQueryPlan).  The task
    index (AssignUniqueId namespacing) comes from the reference taskId's
    partition component (queryId.stageId.stageExecutionId.partition.attempt,
    TaskId.java)."""
    from .presto_protocol import TaskUpdateRequest as RefUpdate
    ref = RefUpdate.from_json(d)
    parts = task_id.split(".")
    try:
        task_index = int(parts[3]) if len(parts) >= 4 else 0
    except ValueError:
        task_index = 0
    sources = []
    for ts in ref.sources:
        # raw reference split dicts; Task.start translates them inside its
        # fail-the-task guard (a malformed split must FAIL the task, not
        # 404/500 the update request)
        splits = [s.split or {} for s in ts.splits]
        sources.append(TaskSource(ts.planNodeId, splits, ts.noMoreSplits))
    bufs = ref.outputIds.buffers
    # buffers maps bufferId -> partition; BROADCAST repeats partition 0 for
    # every consumer, so the buffer COUNT comes from the ids
    n_buffers = (max(int(k) for k in bufs.keys()) + 1) if bufs else 1
    ob = OutputBuffersSpec(
        "BROADCAST" if ref.outputIds.type == "BROADCAST"
        else "PARTITIONED", n_buffers, [])
    session = dict(ref.session.systemProperties)
    return TaskUpdateRequest(task_id, task_index, ref.fragment, sources,
                             ob, session, ref.tableWriteInfo)


@dataclass
class TaskStatus:
    task_id: str
    state: str
    version: int
    self_uri: str
    failures: List[str] = field(default_factory=list)
    memory_reservation: int = 0
    completed_drivers: int = 0
    # reference ErrorType.java classification of the FIRST failure
    # (ExecutionFailureInfo.errorCode.type): the coordinator's retry
    # decision — USER_ERROR never retries, infra errors may
    error_type: str = ""

    def to_dict(self):
        # reference-shaped TaskStatus fields (presto_protocol_core.h:2358:
        # failures are ExecutionFailureInfo-shaped dicts) merged with the
        # compact extra fields in-repo clients read
        from ..common.errors import is_retryable_type
        from .presto_protocol import TaskStatus as RefStatus
        et = self.error_type or "INTERNAL_ERROR"
        ref = RefStatus(
            version=self.version, state=self.state, self_uri=self.self_uri,
            failures=[{"message": f, "type": "TASK_FAILURE",
                       "errorCode": {"name": "GENERIC_" + et, "code": 0,
                                     "type": et,
                                     "retriable": is_retryable_type(et)}}
                      for f in self.failures],
            memoryReservationInBytes=self.memory_reservation).to_json()
        ref.update({"taskId": self.task_id,
                    "completedDrivers": self.completed_drivers})
        return ref

    @staticmethod
    def from_dict(d):
        failures = [f["message"] if isinstance(f, dict) else f
                    for f in d.get("failures", [])]
        error_type = ""
        for f in d.get("failures", []):
            if isinstance(f, dict):
                error_type = (f.get("errorCode") or {}).get("type", "")
                break
        return TaskStatus(d["taskId"], d["state"], d["version"], d["self"],
                          failures,
                          d.get("memoryReservationInBytes", 0),
                          d.get("completedDrivers", 0),
                          error_type=error_type)


def make_announcement(node_id: str, uri: str, environment: str = "test",
                      pool_type: str = "TPU") -> dict:
    """Worker service announcement body (reference
    presto_cpp/main/Announcer.cpp:26-57)."""
    return {
        "environment": environment,
        "pool": "general",
        "location": f"/{node_id}",
        "services": [{
            "id": node_id,
            "type": "presto",
            "properties": {
                "node_version": "presto-tpu-0.1",
                "coordinator": "false",
                "pool_type": pool_type,
                "connectorIds": "tpch,tpcds",
                "http": uri,
            },
        }],
        "announced_at": time.time(),
    }


_SIZE_UNITS = {"B": 1, "kB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30,
               "TB": 1 << 40}


def parse_data_size(s) -> int:
    """'512MB' / '1GB' / plain int -> bytes (reference DataSize parsing)."""
    if isinstance(s, int):
        return s
    s = str(s).strip()
    for unit, mult in sorted(_SIZE_UNITS.items(), key=lambda x: -len(x[0])):
        if s.endswith(unit):
            return int(float(s[:-len(unit)]) * mult)
    return int(s)


_DURATION_UNITS = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0,
                   "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration(s) -> float:
    """'1m' / '10s' / '500ms' / plain number -> seconds (reference
    io.airlift.units.Duration parsing)."""
    if isinstance(s, (int, float)):
        return float(s)
    s = str(s).strip()
    for unit, mult in sorted(_DURATION_UNITS.items(),
                             key=lambda x: -len(x[0])):
        if s.endswith(unit):
            return float(s[:-len(unit)]) * mult
    return float(s)


def apply_session_properties(config, session: Dict[str, str]):
    """Session overrides -> a task-local ExecutionConfig (the analog of
    presto_cpp QueryContextManager::toVeloxConfigs mapping Presto session
    properties onto the execution engine's config,
    QueryContextManager.cpp:224).  Unknown keys are ignored, like the
    reference does for properties a worker does not understand."""
    import dataclasses
    if not session:
        return config
    kw = {}
    if "query_max_memory_per_node" in session:
        kw["memory_budget_bytes"] = parse_data_size(
            session["query_max_memory_per_node"])
    if "query_max_memory" in session:
        kw["memory_max_query_bytes"] = parse_data_size(
            session["query_max_memory"])
    if "spill_enabled" in session:
        kw["spill_enabled"] = str(session["spill_enabled"]).lower() == "true"
    if "spill_partitions" in session:
        kw["spill_partitions"] = int(session["spill_partitions"])
    if "spill_path" in session:
        kw["spill_path"] = session["spill_path"] or None
    if "spill_host_budget_bytes" in session:
        kw["spill_budget_bytes"] = int(session["spill_host_budget_bytes"])
    if "spill_async_staging" in session:
        kw["spill_async_staging"] = (
            str(session["spill_async_staging"]).lower() == "true")
    if "task_batch_rows" in session:
        kw["batch_rows"] = int(session["task_batch_rows"])
    if "exchange_compression" in session:
        kw["exchange_compression"] = (
            str(session["exchange_compression"]).lower() == "true")
    if "exchange_compression_codec" in session:
        codec = str(session["exchange_compression_codec"]).upper()
        from ..common.compression import supported_codecs
        if codec not in supported_codecs():
            # reject at task creation (fails the task with a clear error)
            # rather than KeyError deep inside the output loop
            raise ValueError(
                f"unsupported exchange_compression_codec {codec!r}; "
                f"supported: {', '.join(supported_codecs())}")
        kw["exchange_compression_codec"] = codec
    # grouped (lifespan) execution knobs (reference grouped_execution /
    # concurrent_lifespans_per_task session properties)
    if "grouped_lifespans" in session:
        kw["grouped_lifespans"] = int(session["grouped_lifespans"])
    if "grouped_prefetch_depth" in session:
        kw["grouped_prefetch_depth"] = int(
            session["grouped_prefetch_depth"])
    if "grouped_lifespan_sharding" in session:
        kw["grouped_lifespan_sharding"] = (
            str(session["grouped_lifespan_sharding"]).lower() == "true")
    # fault-tolerance knobs (coordinator propagates its retry mode so
    # workers enable replayable output buffers; reference
    # exchange.max-error-duration / presto-spark retry budget)
    if "remote_task_retry_attempts" in session:
        kw["remote_task_retry_attempts"] = int(
            session["remote_task_retry_attempts"])
    if "exchange_max_error_duration" in session:
        kw["exchange_max_error_duration_s"] = parse_duration(
            session["exchange_max_error_duration"])
    if "retry_policy" in session:
        mode = str(session["retry_policy"]).strip().lower()
        from ..exec.pipeline import RETRY_POLICY_MODES
        if mode not in RETRY_POLICY_MODES:
            raise ValueError(
                f"retry_policy must be one of {RETRY_POLICY_MODES}, "
                f"got {mode!r}")
        kw["retry_policy"] = mode
    if "query_max_execution_time" in session:
        kw["query_max_execution_time_s"] = parse_duration(
            session["query_max_execution_time"])
    # durable-spool knobs (retry-policy=task; fall back to spill.path)
    if "spool_path" in session:
        kw["spool_path"] = session["spool_path"] or None
    if "spool_staging_budget_bytes" in session:
        kw["spool_staging_budget_bytes"] = parse_data_size(
            session["spool_staging_budget_bytes"])
    # concurrent exchange client knobs (reference exchange.client-threads /
    # exchange.max-buffer-size / exchange.max-response-size)
    if "exchange_client_threads" in session:
        n = int(session["exchange_client_threads"])
        if n < 1:
            raise ValueError(
                f"exchange_client_threads must be >= 1, got {n}")
        kw["exchange_client_threads"] = n
    if "exchange_max_buffer_size" in session:
        kw["exchange_max_buffer_bytes"] = int(parse_data_size(
            session["exchange_max_buffer_size"]))
    if "exchange_max_response_size" in session:
        kw["exchange_max_response_bytes"] = int(parse_data_size(
            session["exchange_max_response_size"]))
    if "fault_injection_probability" in session:
        p = float(session["fault_injection_probability"])
        if not 0.0 <= p <= 1.0:
            raise ValueError(
                f"fault_injection_probability must be in [0, 1], got {p}")
        kw["fault_injection_probability"] = p
    if "analyze_unfused" in session:
        # EXPLAIN ANALYZE compatibility knob: disable scan-chain fusion so
        # per-operator stats come from the interpreted streaming path
        kw["analyze_unfused"] = (
            str(session["analyze_unfused"]).lower() == "true")
    if "plan_validation" in session:
        mode = str(session["plan_validation"]).strip().lower()
        from ..analysis import VALIDATION_MODES
        if mode not in VALIDATION_MODES:
            # reject at task creation like a bad codec: a clear USER_ERROR
            # beats a silent fall-through to the default mode
            raise ValueError(
                f"plan_validation must be one of {VALIDATION_MODES}, "
                f"got {mode!r}")
        kw["plan_validation"] = mode
    if "lock_validation" in session:
        mode = str(session["lock_validation"]).strip().lower()
        if mode not in ("on", "off", "true", "false"):
            raise ValueError(
                "lock_validation must be one of on/off/true/false, "
                f"got {mode!r}")
        kw["lock_validation"] = mode in ("on", "true")
    if "scan_kernel" in session:
        mode = str(session["scan_kernel"]).strip().lower()
        from ..exec.pipeline import SCAN_KERNEL_MODES
        if mode not in SCAN_KERNEL_MODES:
            raise ValueError(
                f"scan_kernel must be one of {SCAN_KERNEL_MODES}, "
                f"got {mode!r}")
        kw["scan_kernel"] = mode
    if "profile" in session:
        # per-query device profiler capture (telemetry/profiler.py):
        # wraps execution in jax.profiler.trace() under profile_dir
        kw["profile"] = str(session["profile"]).lower() == "true"
    # adaptive execution knobs (reference enable_dynamic_filtering /
    # dynamic-filtering.* session properties)
    if "dynamic_filtering" in session:
        kw["dynamic_filtering"] = (
            str(session["dynamic_filtering"]).lower() == "true")
    if "dynamic_filtering_wait_timeout" in session:
        kw["dynamic_filtering_wait_timeout_s"] = parse_duration(
            session["dynamic_filtering_wait_timeout"])
    if "dynamic_filtering_max_distinct_values" in session:
        kw["dynamic_filtering_max_distinct"] = int(
            session["dynamic_filtering_max_distinct_values"])
    if "adaptive_exchange" in session:
        kw["adaptive_exchange"] = (
            str(session["adaptive_exchange"]).lower() == "true")
    if "adaptive_history_sizing" in session:
        kw["adaptive_history_sizing"] = (
            str(session["adaptive_history_sizing"]).lower() == "true")
    if "storage_zone_rows" in session:
        # zone-map granularity: dynamic-filter pruning needs zones finer
        # than the scanned table to discriminate chunks at small scale
        n = int(session["storage_zone_rows"])
        if n < 1:
            raise ValueError(f"storage_zone_rows must be >= 1, got {n}")
        kw["storage_zone_rows"] = n
    return dataclasses.replace(config, **kw) if kw else config
