"""Coordinator-side distributed execution over the HTTP task protocol.

The analog of the reference coordinator's scheduling + remote-task stack
(SqlQueryScheduler.java:114 stage scheduling, SqlStageExecution.scheduleTask
:513, HttpRemoteTask.java:883-936 update POSTs) and of the result pump
(server/protocol/Query.java:116 holding an ExchangeClient on the root
stage): fragments are assigned round-robin to discovered workers, each task
gets its splits + upstream buffer locations in a TaskUpdateRequest, and the
coordinator pulls the root stage's buffers over the same results protocol.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..connectors import catalog, tpch
from ..exec.pipeline import ExecutionConfig
from ..exec.runner import LocalQueryRunner, QueryResult, pages_to_result
from ..spi import plan as P
from .exchange import pull_pages
from .protocol import (DONE_STATES, FAILED, OutputBuffersSpec, TaskSource,
                       TaskStatus, TaskUpdateRequest)

_query_counter = itertools.count()


class HeartbeatFailureDetector:
    """Coordinator-side liveness probing (reference
    presto-main/.../failureDetector/HeartbeatFailureDetector.java:77 +
    DiscoveryNodeManager.refreshNodesInternal): each worker's
    /v1/info/state is polled on an interval; a node failing `threshold`
    consecutive probes — or reporting SHUTTING_DOWN — is dropped from
    scheduling until it responds ACTIVE again."""

    def __init__(self, worker_uris: List[str], interval_s: float = 0.5,
                 threshold: int = 3):
        self.worker_uris = list(worker_uris)
        self.threshold = threshold
        self._streak = {u: 0 for u in self.worker_uris}
        self._draining = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # one prober per worker: a hung node must not delay detection of
        # the others (the reference probes asynchronously per service)
        self._threads = [
            threading.Thread(target=self._loop, args=(uri, interval_s),
                             name=f"failure-detector-{i}", daemon=True)
            for i, uri in enumerate(self.worker_uris)]
        for t in self._threads:
            t.start()

    def _probe(self, uri: str):
        from .auth import outbound_headers, urlopen_internal
        try:
            req = urllib.request.Request(uri + "/v1/info/state",
                                         headers=outbound_headers())
            with urlopen_internal(req, timeout=2.0) as resp:
                return json.loads(resp.read())
        except (OSError, ValueError):
            return None

    def _loop(self, uri: str, interval_s: float) -> None:
        while not self._stop.is_set():
            state = self._probe(uri)
            with self._lock:
                if state is None:
                    self._streak[uri] += 1
                else:
                    self._streak[uri] = 0
                    if state == "SHUTTING_DOWN":
                        self._draining.add(uri)
                    else:
                        self._draining.discard(uri)
            self._stop.wait(interval_s)

    def alive(self) -> List[str]:
        with self._lock:
            return [u for u in self.worker_uris
                    if self._streak[u] < self.threshold
                    and u not in self._draining]

    def failed(self) -> List[str]:
        with self._lock:
            return [u for u in self.worker_uris
                    if self._streak[u] >= self.threshold]

    def close(self) -> None:
        self._stop.set()


class RemoteTask:
    """Client-side handle for one worker task (reference HttpRemoteTask)."""

    def __init__(self, worker_uri: str, task_id: str):
        self.worker_uri = worker_uri
        self.task_id = task_id
        self.task_uri = f"{worker_uri}/v1/task/{task_id}"

    def update(self, request: TaskUpdateRequest) -> TaskStatus:
        from .auth import outbound_headers
        body = json.dumps(request.to_dict()).encode()
        req = urllib.request.Request(
            self.task_uri, data=body, method="POST",
            headers={"Content-Type": "application/json",
                     **outbound_headers()})
        from .auth import urlopen_internal
        with urlopen_internal(req, timeout=30) as resp:
            return TaskStatus.from_dict(json.loads(resp.read()))

    def status(self, current_state: Optional[str] = None,
               max_wait_ms: int = 1000) -> TaskStatus:
        from .auth import outbound_headers
        url = f"{self.task_uri}/status?maxWaitMs={max_wait_ms}"
        req = urllib.request.Request(url, headers=outbound_headers())
        if current_state:
            req.add_header("X-Presto-Current-State", current_state)
        from .auth import urlopen_internal
        with urlopen_internal(req, timeout=60) as resp:
            return TaskStatus.from_dict(json.loads(resp.read()))

    def cancel(self) -> None:
        from .auth import outbound_headers
        req = urllib.request.Request(self.task_uri, method="DELETE",
                                     headers=outbound_headers())
        try:
            from .auth import urlopen_internal
            urlopen_internal(req, timeout=10).close()
        except OSError:
            pass

    def result_location(self, buffer_id: int) -> str:
        return f"{self.task_uri}/results/{buffer_id}"


class _Stage:
    def __init__(self, fragment: P.PlanFragment, children: List["_Stage"],
                 n_tasks: int):
        self.fragment = fragment
        self.children = children
        self.n_tasks = n_tasks
        self.tasks: List[RemoteTask] = []


class HttpQueryRunner(LocalQueryRunner):
    """Schedules fragment DAGs over real HTTP workers — the external-worker
    integration point the reference reaches through
    DistributedQueryRunner.setExternalWorkerLauncher
    (presto-tests/.../DistributedQueryRunner.java:190-215)."""

    def __init__(self, worker_uris: List[str], schema: str = "sf0.01",
                 failure_detector: Optional[HeartbeatFailureDetector] = None,
                 config: Optional[ExecutionConfig] = None,
                 n_tasks: int = 2, broadcast_threshold: int = 600_000,
                 session: Optional[Dict[str, str]] = None,
                 catalog: str = "tpch"):
        super().__init__(schema, config, catalog)
        self.worker_uris = worker_uris
        self.failure_detector = failure_detector
        self.n_tasks = n_tasks
        self.broadcast_threshold = broadcast_threshold
        self.session = session or {}
        self._rr = itertools.count()

    def _live_uris(self) -> List[str]:
        """Schedulable workers (reference NodeScheduler.createNodeSelector
        consuming the failure detector's view)."""
        if self.failure_detector is None:
            return self.worker_uris
        live = self.failure_detector.alive()
        if not live:
            raise RuntimeError("no live workers")
        return live

    # -- planning ---------------------------------------------------------
    def plan_subplan(self, sql: str):
        from ..sql.fragmenter import FragmenterConfig, plan_distributed
        output = self.plan(sql)
        names = output.column_names
        types = [v.type for v in output.outputs]
        cfg = FragmenterConfig(broadcast_threshold=self.broadcast_threshold)
        return plan_distributed(output, cfg), names, types

    def _build_stages(self, subplan: P.SubPlan) -> _Stage:
        children = [self._build_stages(c) for c in subplan.children]
        frag = subplan.fragment
        if frag.partitioning in (P.SOURCE_DISTRIBUTION,
                                 P.FIXED_HASH_DISTRIBUTION):
            n_tasks = self.n_tasks
        else:
            n_tasks = 1
        return _Stage(frag, children, n_tasks)

    # -- execution --------------------------------------------------------
    def execute(self, sql: str) -> QueryResult:
        subplan, names, types = self.plan_subplan(sql)
        root = self._build_stages(subplan)
        qid = f"q{next(_query_counter)}_{int(time.time() * 1000) % 100000}"
        all_tasks: List[RemoteTask] = []
        try:
            self._schedule(root, qid, consumer_tasks=1, all_tasks=all_tasks)
            # decode with the session codec, else the coordinator's own
            # configured codec — workers compress every output buffer,
            # including the root stage this pull reads, with the same
            # cluster config (reference: one PagesSerdeFactory per cluster)
            codec = str(self.session.get(
                "exchange_compression_codec",
                self.config.exchange_compression_codec)).upper()
            pages = []
            for task in root.tasks:
                pages.extend(pull_pages(task.result_location(0),
                                        codec=codec))
            self._check_failures(all_tasks)
            return pages_to_result(iter(pages), names, types)
        finally:
            for t in all_tasks:
                t.cancel()

    def _schedule(self, stage: _Stage, qid: str, consumer_tasks: int,
                  all_tasks: List[RemoteTask], stage_path: str = "0") -> None:
        # children first: their task locations feed this stage's sources
        for i, child in enumerate(stage.children):
            self._schedule(child, qid, stage.n_tasks, all_tasks,
                           f"{stage_path}.{i}")

        frag = stage.fragment
        scheme = frag.output_partitioning_scheme
        if scheme.handle == P.FIXED_HASH_DISTRIBUTION:
            spec = OutputBuffersSpec(
                "PARTITIONED", consumer_tasks,
                [a.name for a in scheme.arguments])
        elif scheme.handle == P.FIXED_BROADCAST_DISTRIBUTION:
            spec = OutputBuffersSpec("BROADCAST", consumer_tasks)
        else:  # SINGLE: one buffer, one consumer
            spec = OutputBuffersSpec("PARTITIONED", 1)

        # split assignment (reference SourcePartitionedScheduler)
        scan_splits: Dict[str, List[catalog.TableSplit]] = {}
        for node in P.walk_plan(frag.root):
            if isinstance(node, P.TableScanNode):
                th = node.table
                sf = dict(th.extra).get("scaleFactor", 0.01)
                n_splits = max(stage.n_tasks, self.config.splits_per_scan)
                scan_splits[node.id] = catalog.make_splits(
                    th.table_name, sf, n_splits, th.connector_id)
        remote_nodes = [n for n in P.walk_plan(frag.root)
                        if isinstance(n, P.RemoteSourceNode)]
        child_by_fid = {c.fragment.fragment_id: c for c in stage.children}

        live = self._live_uris()
        for ti in range(stage.n_tasks):
            worker = live[next(self._rr) % len(live)]
            task_id = f"{qid}.{stage_path.replace('.', '_')}.{ti}"
            sources = []
            for node_id, splits in scan_splits.items():
                own = [s.to_dict() for s in splits[ti::stage.n_tasks]]
                sources.append(TaskSource(node_id, own))
            for rnode in remote_nodes:
                locations = []
                for fid in rnode.source_fragment_ids:
                    child = child_by_fid[fid]
                    child_scheme = \
                        child.fragment.output_partitioning_scheme.handle
                    buffer_id = 0 if child_scheme == P.SINGLE_DISTRIBUTION \
                        else ti
                    for ct in child.tasks:
                        locations.append(
                            {"remote": True,
                             "location": ct.result_location(buffer_id)})
                sources.append(TaskSource(rnode.id, locations))
            req = TaskUpdateRequest.make(task_id, ti, frag, sources,
                                         spec, session=self.session)
            # a draining worker answers 503 (server.py do_task_update):
            # reroute the task to the next live worker (reference
            # SqlStageExecution retrying placement on node refusal)
            candidates = [worker] + [u for u in live if u != worker]
            task = None
            last_err = None
            for cand in candidates:
                task = RemoteTask(cand, task_id)
                try:
                    task.update(req)
                    break
                except urllib.error.HTTPError as e:
                    if e.code != 503:
                        raise
                    last_err = e
                    task = None
            if task is None:
                raise RuntimeError(
                    f"no worker accepted task {task_id}: {last_err}")
            stage.tasks.append(task)
            all_tasks.append(task)

    def _check_failures(self, tasks: List[RemoteTask]) -> None:
        for t in tasks:
            st = t.status(max_wait_ms=0)
            if st.state == FAILED:
                raise RuntimeError(
                    f"task {t.task_id} failed: {st.failures[:1]}")
