"""Coordinator-side distributed execution over the HTTP task protocol.

The analog of the reference coordinator's scheduling + remote-task stack
(SqlQueryScheduler.java:114 stage scheduling, SqlStageExecution.scheduleTask
:513, HttpRemoteTask.java:883-936 update POSTs) and of the result pump
(server/protocol/Query.java:116 holding an ExchangeClient on the root
stage): fragments are assigned round-robin to discovered workers, each task
gets its splits + upstream buffer locations in a TaskUpdateRequest, and the
coordinator pulls the root stage's buffers over the same results protocol.

Fault tolerance (reference HttpRemoteTask error budgets + presto-spark's
ErrorClassifier-driven task retry): every failure observed at the
coordinator — a FAILED task status, a 404 on a task the coordinator
created, a worker dropping off the failure detector, an exchange source
exhausting its error budget — is classified by error type.  USER_ERROR
fails the query fast with no retry; everything infrastructure-shaped
restarts the failed task under a per-task attempt budget
(remote_task_retry_attempts), on a surviving worker, with the SAME task-id
lineage and the SAME splits.  Because consumer TaskSources bake in producer
locations, restarting a producer restarts every ancestor stage up to the
root; the root's restart resets the coordinator's collected pages, and
retained producer buffers replay from token 0, so output stays
exactly-once.
"""
from __future__ import annotations

import itertools
import json
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from ..common.locks import OrderedLock
from ..common.errors import (INTERNAL_ERROR, PrestoQueryError,
                             PrestoUserError, ExchangeLostError,
                             PoisonSplitError, QueryDeadlineExceededError,
                             RemoteTaskError, WorkerLostError,
                             is_retryable_type, parse_error_type)
from ..connectors import catalog, tpch
from ..exec.adaptive import DynamicFilterCollector, DynamicFilterSummary
from ..exec.pipeline import ExecutionConfig
from ..exec.runner import LocalQueryRunner, QueryResult, pages_to_result
from ..spi import plan as P
from ..utils.runtime_stats import RuntimeStats
from .exchange import ExchangeClient
from .protocol import (DONE_STATES, FAILED, OutputBuffersSpec, TaskSource,
                       TaskStatus, TaskUpdateRequest, parse_data_size,
                       parse_duration)

_query_counter = itertools.count()

_RETRY_SUFFIX = re.compile(r"\.r\d+$")
_RESULT_LOCATIONS = re.compile(r"/v1/task/([^/\s]+)/results/")
_SOURCE_LOCATIONS = re.compile(r"(https?://[^/\s\"\\]+)/v1/task/([^/\s\"\\]+)/results/")
_SIG_JUNK_LINE = re.compile(r"[\"'}\\\s]+")


def _failure_signature(message: str) -> str:
    """Canonical signature for an INTERNAL failure.  The same root cause
    can be observed directly (the failed task's own traceback in a status
    event) or through any number of consumer exchange wrappers, each of
    which JSON-escapes the quoted producer error one level deeper.
    Collapse the escape layers, then take the deepest meaningful line —
    the root exception — with digits masked so ports, attempt counters
    and line numbers don't fragment the signature."""
    text = message or ""
    for _ in range(8):  # escape depth doubles per wrapper; 8 is plenty
        collapsed = text.replace("\\\\", "\\")
        if collapsed == text:
            break
        text = collapsed
    text = text.replace("\\r", "").replace("\\n", "\n").replace('\\"', '"')
    lines = [ln.strip() for ln in text.splitlines()]
    lines = [ln for ln in lines if ln and not _SIG_JUNK_LINE.fullmatch(ln)]
    last = lines[-1] if lines else ""
    return re.sub(r"\d+", "#", last)[:200]


class HeartbeatFailureDetector:
    """Coordinator-side liveness probing (reference
    presto-main/.../failureDetector/HeartbeatFailureDetector.java:77 +
    DiscoveryNodeManager.refreshNodesInternal): each worker's
    /v1/info/state is polled on an interval; a node failing `threshold`
    consecutive probes — or reporting SHUTTING_DOWN — is dropped from
    scheduling until it responds ACTIVE again.

    `heartbeat_timeout_s` adds an absolute-age trigger on top of the
    consecutive-miss streak (failure-detector.heartbeat-timeout): a
    worker whose last successful heartbeat is older than the timeout is
    failed even if individual probes are still timing out slowly enough
    to not build a streak."""

    def __init__(self, worker_uris: List[str], interval_s: float = 0.5,
                 threshold: int = 3,
                 heartbeat_timeout_s: Optional[float] = None):
        self.worker_uris = list(worker_uris)
        self.threshold = threshold
        self.heartbeat_timeout_s = heartbeat_timeout_s or None
        self._streak = {u: 0 for u in self.worker_uris}
        # last SUCCESSFUL probe per worker (monotonic); seeded now so a
        # worker that never answers still ages out of scheduling
        now = time.monotonic()
        self._last_seen = {u: now for u in self.worker_uris}
        self._draining = set()
        # rank 80: prober bookkeeping only — never nests into engine locks
        self._lock = OrderedLock("failure-detector", 80)  # lint: guarded-by(_lock)
        self._stop = threading.Event()
        # one prober per worker: a hung node must not delay detection of
        # the others (the reference probes asynchronously per service)
        self._threads = [
            threading.Thread(target=self._loop, args=(uri, interval_s),
                             name=f"failure-detector-{i}", daemon=True)
            for i, uri in enumerate(self.worker_uris)]
        for t in self._threads:
            t.start()

    def _probe(self, uri: str):
        from .auth import outbound_headers, urlopen_internal
        try:
            req = urllib.request.Request(uri + "/v1/info/state",
                                         headers=outbound_headers())
            with urlopen_internal(req, timeout=2.0) as resp:
                return json.loads(resp.read())
        except (OSError, ValueError):
            return None

    def _loop(self, uri: str, interval_s: float) -> None:
        while not self._stop.is_set():
            state = self._probe(uri)
            with self._lock:
                if state is None:
                    self._streak[uri] += 1
                else:
                    self._streak[uri] = 0
                    self._last_seen[uri] = time.monotonic()
                    if state == "SHUTTING_DOWN":
                        self._draining.add(uri)
                    else:
                        self._draining.discard(uri)
            self._stop.wait(interval_s)

    def heartbeat_age_s(self, uri: str) -> float:
        """Seconds since the worker last answered a probe."""
        with self._lock:
            return time.monotonic() - self._last_seen.get(
                uri, time.monotonic())

    def _failed_locked(self, uri: str) -> bool:
        if self._streak[uri] >= self.threshold:
            return True
        return (self.heartbeat_timeout_s is not None
                and time.monotonic() - self._last_seen[uri]
                > self.heartbeat_timeout_s)

    def alive(self) -> List[str]:
        with self._lock:
            return [u for u in self.worker_uris
                    if not self._failed_locked(u)
                    and u not in self._draining]

    def failed(self) -> List[str]:
        with self._lock:
            return [u for u in self.worker_uris
                    if self._failed_locked(u)]

    def snapshot(self) -> Dict[str, dict]:
        """Per-worker probe state for /v1/status and /v1/metrics."""
        with self._lock:
            now = time.monotonic()
            return {u: {"streak": self._streak[u],
                        "draining": u in self._draining,
                        "heartbeatAgeSeconds": round(
                            now - self._last_seen[u], 3),
                        "alive": (not self._failed_locked(u)
                                  and u not in self._draining)}
                    for u in self.worker_uris}

    def close(self) -> None:
        self._stop.set()


class RemoteTask:
    """Client-side handle for one worker task (reference HttpRemoteTask)."""

    def __init__(self, worker_uri: str, task_id: str,
                 trace_token: str = ""):
        self.worker_uri = worker_uri
        self.task_id = task_id
        self.task_uri = f"{worker_uri}/v1/task/{task_id}"
        # X-Presto-Trace-Token rides on EVERY coordinator->worker request
        # for this task (the reference's trace-token propagation on the
        # task protocol), so worker access logs join to the query trace
        self.trace_token = trace_token

    def _headers(self) -> dict:
        from .auth import outbound_headers
        headers = outbound_headers()
        if self.trace_token:
            headers["X-Presto-Trace-Token"] = self.trace_token
        return headers

    def update(self, request: TaskUpdateRequest,
               deadline_ms: Optional[float] = None) -> TaskStatus:
        body = json.dumps(request.to_dict()).encode()
        headers = {"Content-Type": "application/json", **self._headers()}
        if deadline_ms is not None:
            # the query's REMAINING wall budget at dispatch (relative ms,
            # so no coordinator<->worker clock agreement is needed): the
            # worker arms a local monotonic deadline from it
            headers["X-Presto-Task-Deadline"] = str(int(deadline_ms))
        req = urllib.request.Request(
            self.task_uri, data=body, method="POST", headers=headers)
        from .auth import urlopen_internal
        with urlopen_internal(req, timeout=30) as resp:
            return TaskStatus.from_dict(json.loads(resp.read()))

    def status(self, current_state: Optional[str] = None,
               max_wait_ms: int = 1000,
               timeout_s: float = 60.0) -> TaskStatus:
        url = f"{self.task_uri}/status?maxWaitMs={max_wait_ms}"
        req = urllib.request.Request(url, headers=self._headers())
        if current_state:
            req.add_header("X-Presto-Current-State", current_state)
        from .auth import urlopen_internal
        with urlopen_internal(req, timeout=timeout_s) as resp:
            return TaskStatus.from_dict(json.loads(resp.read()))

    def info(self, timeout_s: float = 10.0) -> dict:
        """Full TaskInfo (GET /v1/task/{id}): per-task stats + the plan-node
        inventory with per-operator stats when the worker collected them."""
        req = urllib.request.Request(self.task_uri, headers=self._headers())
        from .auth import urlopen_internal
        with urlopen_internal(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())

    def cancel(self) -> None:
        req = urllib.request.Request(self.task_uri, method="DELETE",
                                     headers=self._headers())
        try:
            from .auth import urlopen_internal
            urlopen_internal(req, timeout=10).close()
        except OSError:
            pass

    def result_location(self, buffer_id: int) -> str:
        return f"{self.task_uri}/results/{buffer_id}"


class _Stage:
    def __init__(self, fragment: P.PlanFragment, children: List["_Stage"],
                 n_tasks: int, stage_path: str = "0"):
        self.fragment = fragment
        self.children = children
        self.n_tasks = n_tasks
        self.stage_path = stage_path
        self.parent: Optional["_Stage"] = None
        for c in children:
            c.parent = self
        # filled by _QueryExecution._prepare: immutable per query, reused
        # verbatim on task restart (same splits, same buffer spec)
        self.spec: Optional[OutputBuffersSpec] = None
        self.scan_splits: Dict[str, List[catalog.TableSplit]] = {}
        self.remote_nodes: List[P.RemoteSourceNode] = []
        self.tasks: List[Optional[RemoteTask]] = [None] * n_tasks

    def postorder(self) -> List["_Stage"]:
        out: List[_Stage] = []
        for c in self.children:
            out.extend(c.postorder())
        out.append(self)
        return out


class _FailureSignal(Exception):
    """Internal control flow: the status watcher observed task failures;
    unwind the root pull and let the retry loop classify them."""

    def __init__(self, events: List[dict]):
        super().__init__(f"{len(events)} task failure(s) observed")
        self.events = events


class _StatusWatcher:
    """Background poller over every live task's /status (the coordinator
    side of the reference's continuous task-status long-poll in
    HttpRemoteTask).  Feeds failures to the query's retry loop the moment
    they happen, so the root pull aborts early instead of draining all
    pages first.  Transport errors build a per-worker streak; two straight
    misses — or the failure detector dropping the worker — count every
    unfinished task there as lost."""

    TRANSPORT_STREAK = 2

    def __init__(self, execution: "_QueryExecution",
                 interval_s: float = 0.15):
        self._exec = execution
        self._stop = threading.Event()
        # rank 82: event-list lock, leaf-like (only above the registries)
        self._lock = OrderedLock("status-watcher", 82)  # lint: guarded-by(_lock)
        self._events: List[dict] = []
        self._streaks: Dict[str, int] = {}
        self._done: Set[str] = set()
        self._thread = threading.Thread(target=self._loop,
                                        args=(interval_s,),
                                        name="status-watcher", daemon=True)
        self._thread.start()

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def _emit(self, **event) -> None:
        with self._lock:
            self._events.append(event)

    def _loop(self, interval_s: float) -> None:
        while not self._stop.is_set():
            dead_workers = set()
            det = self._exec.runner.failure_detector
            if det is not None:
                dead_workers.update(det.failed())
            for task in self._exec.current_tasks():
                if self._stop.is_set():
                    return
                if task.task_id in self._done:
                    continue
                if task.worker_uri in dead_workers:
                    self._emit(kind="worker_lost", task_id=task.task_id,
                               worker_uri=task.worker_uri,
                               message=f"worker {task.worker_uri} dropped "
                                       "by failure detector")
                    continue
                try:
                    st = task.status(max_wait_ms=0, timeout_s=2.0)
                except urllib.error.HTTPError as e:
                    if e.code in (404, 410):
                        # the worker restarted and lost its task registry:
                        # the task is gone, not the query (TaskLostError)
                        self._emit(kind="task_lost", task_id=task.task_id,
                                   worker_uri=task.worker_uri,
                                   message=f"task {task.task_id} not found "
                                           f"on {task.worker_uri} "
                                           f"({e.code})")
                    else:
                        self._bump_streak(task)
                except (urllib.error.URLError, TimeoutError, OSError,
                        ValueError):
                    self._bump_streak(task)
                else:
                    with self._lock:
                        self._streaks[task.worker_uri] = 0
                    if st.state == FAILED:
                        msg = st.failures[0] if st.failures else "unknown"
                        self._emit(kind="failed", task_id=task.task_id,
                                   worker_uri=task.worker_uri,
                                   error_type=st.error_type, message=msg)
                    elif st.state in DONE_STATES:
                        self._done.add(task.task_id)
            self._stop.wait(interval_s)

    def _bump_streak(self, task: RemoteTask) -> None:
        with self._lock:
            n = self._streaks.get(task.worker_uri, 0) + 1
            self._streaks[task.worker_uri] = n
        if n >= self.TRANSPORT_STREAK:
            self._emit(kind="worker_lost", task_id=task.task_id,
                       worker_uri=task.worker_uri,
                       message=f"worker {task.worker_uri} unreachable "
                               f"({n} consecutive status probes failed)")

    def close(self) -> None:
        self._stop.set()


class _DynamicFilterPump:
    """Coordinator-side dynamic-filter distribution (the analog of the
    reference DynamicFilterService): build-stage tasks summarize their
    dynamic-filter key domains into TaskInfo ("dynamicFilterSummaries");
    this pump polls those infos, merges the per-task partials per filter
    id once EVERY task of every producing stage has reported, and pushes
    the merged domains to the downstream scan tasks via fragment-less
    task updates.  Consumer tasks wait a bounded
    dynamic-filtering.wait-timeout then proceed unfiltered, so a slow or
    dead producer degrades to the unfiltered plan instead of stalling —
    a late delivery after the wait is ignored (and metered) worker-side."""

    def __init__(self, execution: "_QueryExecution",
                 interval_s: float = 0.1):
        self._exec = execution
        cfg = execution.runner.config
        max_distinct = int(execution.session.get(
            "dynamic_filtering_max_distinct_values",
            cfg.dynamic_filtering_max_distinct))
        self._collector = DynamicFilterCollector(max_distinct)
        # fid -> producing stages (several source fragments can feed the
        # same filter id); a filter is ready only when ALL have reported
        self._producers: Dict[str, List[_Stage]] = {}
        # consumer stages paired with the filter ids their scans await
        self._consumers: List[Tuple[_Stage, Set[str]]] = []
        for stage in execution.stages:
            for fid in stage.fragment.dynamic_filter_sources.values():
                self._producers.setdefault(fid, []).append(stage)
            fids = {e["id"] for node in P.walk_plan(stage.fragment.root)
                    if isinstance(node, P.TableScanNode)
                    for e in getattr(node, "runtime_filters", None) or []}
            if fids:
                self._consumers.append((stage, fids))
        self._stage_done: Set[int] = set()
        self._ready: Dict[str, dict] = {}    # fid -> merged wire dict
        self._pushed: Set[Tuple[str, frozenset]] = set()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        args=(interval_s,),
                                        name="dynamic-filter-pump",
                                        daemon=True)
        if self._producers and self._consumers:
            self._thread.start()

    def _collect(self) -> None:
        """Merge summaries from producer stages whose tasks ALL report."""
        for stages in self._producers.values():
            for stage in stages:
                if id(stage) in self._stage_done:
                    continue
                want = set(stage.fragment.dynamic_filter_sources.values())
                partials: List[Dict[str, dict]] = []
                for task in stage.tasks:
                    if task is None:
                        break
                    try:
                        info = task.info(timeout_s=2.0)
                    except (OSError, ValueError):
                        break
                    sums = info.get("dynamicFilterSummaries") or {}
                    if not want <= set(sums):
                        break  # task still running (or retried attempt)
                    partials.append(sums)
                else:
                    for sums in partials:
                        for fid in want:
                            self._collector.publish(
                                DynamicFilterSummary.from_dict(sums[fid]))
                    self._stage_done.add(id(stage))
        for fid, stages in self._producers.items():
            if fid not in self._ready and all(
                    id(s) in self._stage_done for s in stages):
                self._ready[fid] = self._collector.get(fid).to_dict()
                self._exec.stats.add("dynamicFiltersCollected", 1)

    def _push(self) -> None:
        """Deliver ready filters to every live consumer task exactly once
        per (task attempt, filter set); a restarted attempt has a new task
        id, so it is re-delivered automatically."""
        for stage, fids in self._consumers:
            have = {f: self._ready[f] for f in fids if f in self._ready}
            if not have:
                continue
            for ti, task in enumerate(stage.tasks):
                if task is None:
                    continue
                key = (task.task_id, frozenset(have))
                if key in self._pushed:
                    continue
                req = TaskUpdateRequest(
                    task.task_id, ti, None, [], stage.spec,
                    session=self._exec.session, dynamic_filters=have)
                try:
                    task.update(req,
                                deadline_ms=self._exec._deadline_ms())
                except (urllib.error.URLError, urllib.error.HTTPError,
                        TimeoutError, OSError):
                    pass  # consumer proceeds unfiltered after its wait
                else:
                    self._pushed.add(key)

    def _loop(self, interval_s: float) -> None:
        while not self._stop.is_set():
            self._collect()
            self._push()
            if len(self._ready) == len(self._producers):
                # everything collected; keep pushing only for restarts
                if all((t.task_id, frozenset(
                        {f: self._ready[f] for f in fids
                         if f in self._ready})) in self._pushed
                       for stage, fids in self._consumers
                       for t in stage.tasks if t is not None):
                    return
            self._stop.wait(interval_s)

    def close(self) -> None:
        self._stop.set()


class _QueryExecution:
    """One query's distributed run: scheduling, the failure watcher, and
    the classify-restart loop (the coordinator analog of presto-spark's
    per-task retry over durable shuffle — here over retained buffers)."""

    def __init__(self, runner: "HttpQueryRunner", root: _Stage, qid: str,
                 trace_token: str = ""):
        self.runner = runner
        self.root = root
        self.qid = qid
        self.stages = root.postorder()
        cfg = runner.config
        self.max_attempts = int(runner.session.get(
            "remote_task_retry_attempts", cfg.remote_task_retry_attempts))
        self.max_error_s = parse_duration(runner.session.get(
            "exchange_max_error_duration",
            cfg.exchange_max_error_duration_s))
        self.session = dict(runner.session)
        if self.max_attempts > 0:
            # workers must retain acknowledged buffer pages so a restarted
            # consumer can replay its inputs from token 0
            self.session.setdefault("remote_task_retry_attempts",
                                    str(self.max_attempts))
        # retry-policy=task (fault-tolerant execution): workers spool every
        # stage's output durably and a failed task restarts ALONE — the
        # policy rides to workers in the session so their tasks build
        # TaskSpools and their exchange consumers park on producer loss
        self.retry_policy = str(runner.session.get(
            "retry_policy",
            getattr(cfg, "retry_policy", "query"))).strip().lower()
        self.session.setdefault("retry_policy", self.retry_policy)
        # query.max-execution-time -> a coordinator-local monotonic
        # deadline; 0 disables.  Minted HERE as the typed non-retryable
        # EXCEEDED_TIME_LIMIT user error; the remaining budget is also
        # forwarded per task via X-Presto-Task-Deadline
        self.deadline_limit_s = parse_duration(self.session.get(
            "query_max_execution_time",
            getattr(cfg, "query_max_execution_time_s", 0.0)))
        self.started_at = time.monotonic()
        self.deadline = (self.started_at + self.deadline_limit_s
                         if self.deadline_limit_s > 0 else None)
        # poison-split quarantine: (lineage, normalized INTERNAL error
        # signature) -> distinct workers it failed on
        self.failure_workers: Dict[Tuple[str, str], Set[str]] = {}
        self.codec = str(self.session.get(
            "exchange_compression_codec",
            cfg.exchange_compression_codec)).upper()
        # concurrent root-pull client knobs (exchange.client-threads /
        # .max-buffer-size / .max-response-size and session equivalents)
        self.client_threads = int(self.session.get(
            "exchange_client_threads", cfg.exchange_client_threads))
        self.max_buffer_bytes = parse_data_size(self.session.get(
            "exchange_max_buffer_size", cfg.exchange_max_buffer_bytes))
        self.max_response_bytes = parse_data_size(self.session.get(
            "exchange_max_response_size", cfg.exchange_max_response_bytes))
        self.stats = RuntimeStats()             # root-pull exchange stats
        # trace token: honor one handed down by the statement layer (it
        # minted per-query), else mint from the query id; propagated to
        # every task via session + X-Presto-Trace-Token headers
        self.trace_token = str(
            trace_token or runner.session.get("trace_token")
            or f"trace-{qid}")
        self.session.setdefault("trace_token", self.trace_token)
        # per-operator stats collection is always on for distributed
        # executions: TaskInfo carries the per-node breakdown that
        # /v1/query/{id} rolls up (a per-batch dict update on the worker —
        # the device-side fused counters make it cheap even on hot paths)
        self.session.setdefault("collect_operator_stats", "true")
        # shuffle fabric: session override > config.  The HTTP coordinator
        # only drives the page wire, so a requested "ici" is honored
        # inside each worker's local scheduler (if it has a mesh) while
        # every CROSS-process edge here stays http — tag the stats so
        # fabric comparisons see which wire this run used
        self.fabric = str(runner.session.get(
            "exchange_fabric", cfg.exchange_fabric)).strip().lower()
        self.stats.add("exchangeFabricHttpQueries", 1)
        self.id_attempt: Dict[str, int] = {}    # lineage -> id generation
        self.budget_used: Dict[str, int] = {}   # lineage -> retries charged
        self.suspects: Set[str] = set()         # workers seen failing
        self.retries = 0
        self.all_tasks: List[RemoteTask] = []   # every attempt, for cleanup
        self.lineage_index: Dict[str, Tuple[_Stage, int]] = {}
        self._watcher: Optional[_StatusWatcher] = None
        self._df_pump: Optional[_DynamicFilterPump] = None
        self.dynamic_filtering = str(self.session.get(
            "dynamic_filtering",
            getattr(cfg, "dynamic_filtering", True))).strip().lower() \
            in ("true", "1")

    # -- identity ---------------------------------------------------------
    def lineage(self, stage: _Stage, ti: int) -> str:
        return f"{self.qid}.{stage.stage_path.replace('.', '_')}.{ti}"

    def task_id_for(self, lineage: str) -> str:
        """Retry attempts keep the base lineage and add `.rN` (same task,
        attempt N — the worker counts these in tasks_retried)."""
        attempt = self.id_attempt.get(lineage, 0)
        return lineage if attempt == 0 else f"{lineage}.r{attempt}"

    def current_tasks(self) -> List[RemoteTask]:
        return [t for s in self.stages for t in s.tasks if t is not None]

    # -- scheduling -------------------------------------------------------
    def _prepare(self, stage: _Stage, consumer_tasks: int) -> None:
        """Fix a stage's buffer spec, split assignment, and remote-source
        set once; restarts reuse them verbatim."""
        frag = stage.fragment
        scheme = frag.output_partitioning_scheme
        if scheme.handle == P.FIXED_HASH_DISTRIBUTION:
            stage.spec = OutputBuffersSpec(
                "PARTITIONED", consumer_tasks,
                [a.name for a in scheme.arguments])
        elif scheme.handle == P.FIXED_BROADCAST_DISTRIBUTION:
            stage.spec = OutputBuffersSpec("BROADCAST", consumer_tasks)
        else:  # SINGLE: one buffer, one consumer
            stage.spec = OutputBuffersSpec("PARTITIONED", 1)
        # split assignment (reference SourcePartitionedScheduler)
        for node in P.walk_plan(frag.root):
            if isinstance(node, P.TableScanNode):
                th = node.table
                sf = dict(th.extra).get("scaleFactor", 0.01)
                n_splits = max(stage.n_tasks,
                               self.runner.config.splits_per_scan)
                stage.scan_splits[node.id] = catalog.make_splits(
                    th.table_name, sf, n_splits, th.connector_id)
        stage.remote_nodes = [n for n in P.walk_plan(frag.root)
                              if isinstance(n, P.RemoteSourceNode)]
        for ti in range(stage.n_tasks):
            self.lineage_index[self.lineage(stage, ti)] = (stage, ti)

    def _make_sources(self, stage: _Stage, ti: int) -> List[TaskSource]:
        sources = []
        for node_id, splits in stage.scan_splits.items():
            own = [s.to_dict() for s in splits[ti::stage.n_tasks]]
            sources.append(TaskSource(node_id, own))
        child_by_fid = {c.fragment.fragment_id: c for c in stage.children}
        for rnode in stage.remote_nodes:
            locations = []
            for fid in rnode.source_fragment_ids:
                child = child_by_fid[fid]
                child_scheme = \
                    child.fragment.output_partitioning_scheme.handle
                buffer_id = 0 if child_scheme == P.SINGLE_DISTRIBUTION \
                    else ti
                for ct in child.tasks:
                    locations.append(
                        {"remote": True,
                         "location": ct.result_location(buffer_id)})
            sources.append(TaskSource(rnode.id, locations))
        return sources

    def _place_task(self, stage: _Stage, ti: int) -> RemoteTask:
        """Create one task attempt on a live, non-suspect worker.  A 503
        (draining) or a transport error reroutes to the next candidate
        (reference SqlStageExecution retrying placement on node refusal)."""
        lineage = self.lineage(stage, ti)
        task_id = self.task_id_for(lineage)
        req = TaskUpdateRequest.make(task_id, ti, stage.fragment,
                                     self._make_sources(stage, ti),
                                     stage.spec, session=self.session)
        live = self.runner._live_uris()
        preferred = [u for u in live if u not in self.suspects] or live
        worker = preferred[next(self.runner._rr) % len(preferred)]
        candidates = [worker] + [u for u in preferred if u != worker] \
            + [u for u in live if u not in preferred]
        last_err: Optional[Exception] = None
        for cand in candidates:
            task = RemoteTask(cand, task_id, trace_token=self.trace_token)
            try:
                task.update(req, deadline_ms=self._deadline_ms())
            except urllib.error.HTTPError as e:
                if e.code != 503:
                    raise
                last_err = e
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                # the worker died between discovery and placement
                self.suspects.add(cand)
                last_err = e
            else:
                stage.tasks[ti] = task
                self.all_tasks.append(task)
                return task
        raise WorkerLostError(
            worker, f"no worker accepted task {task_id}: {last_err}")

    def schedule_all(self) -> None:
        for stage in self.stages:
            consumer = stage.parent.n_tasks if stage.parent else 1
            self._prepare(stage, consumer)
        for stage in self.stages:  # postorder: producers before consumers
            for ti in range(stage.n_tasks):
                self._place_task(stage, ti)

    # -- the retry loop ---------------------------------------------------
    def run(self) -> List:
        self.schedule_all()
        if self.dynamic_filtering and self._df_pump is None:
            self._df_pump = _DynamicFilterPump(self)
        while True:
            self._watcher = _StatusWatcher(self)
            # one concurrent client over every root-task buffer (reference
            # Query.java holding an ExchangeClient on the root stage): a
            # restart discards this client and builds a fresh one, and the
            # producers' retained buffers replay from token 0 — so a
            # half-drained attempt stays exactly-once
            client = ExchangeClient(
                [task.result_location(0) for task in self.root.tasks],
                codec=self.codec, max_error_duration_s=self.max_error_s,
                should_abort=self._raise_pending_failures,
                client_threads=self.client_threads,
                max_buffer_bytes=self.max_buffer_bytes,
                max_response_bytes=self.max_response_bytes,
                stats=self.stats)
            try:
                pages = list(client.pages())
                self._raise_pending_failures()
                return pages
            except (ExchangeLostError, RemoteTaskError,
                    _FailureSignal) as e:
                failed = self._classify_failure(e)
                self._restart(failed, cause=e)
            finally:
                client.close()
                self._watcher.close()

    def _deadline_ms(self) -> Optional[float]:
        """Remaining wall budget in ms for X-Presto-Task-Deadline."""
        if self.deadline is None:
            return None
        return max(0.0, (self.deadline - time.monotonic()) * 1000.0)

    def _check_deadline(self) -> None:
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise QueryDeadlineExceededError(
                time.monotonic() - self.started_at, self.deadline_limit_s,
                context=f"query {self.qid}")

    def _raise_pending_failures(self) -> None:
        """should_abort hook for the root pull: unwind as soon as the
        watcher has seen ANY task fail, instead of discovering it after
        all pages are drained.  Also where the query deadline is minted —
        the hook runs every root pull round, so EXCEEDED_TIME_LIMIT
        surfaces within one round of the budget elapsing (and, being a
        typed USER_ERROR, is never retried)."""
        self._check_deadline()
        events = self._watcher.events() if self._watcher else []
        if events:
            raise _FailureSignal(events)

    def _lineage_of_task(self, task_id: str) -> Optional[str]:
        base = _RETRY_SUFFIX.sub("", task_id)
        return base if base in self.lineage_index else None

    def _culprit_lineage(self, text: str, fallback_task_id: str
                         ) -> Optional[str]:
        """Failure text may embed producer buffer locations (a consumer
        failing on its exchange pull quotes the source).  The DEEPEST
        mentioned task is the true culprit; its restart set covers every
        ancestor including the quoting consumer."""
        for tid in reversed(_RESULT_LOCATIONS.findall(text or "")):
            lin = self._lineage_of_task(tid)
            if lin is not None:
                return lin
        return self._lineage_of_task(fallback_task_id)

    def _classify_failure(self, exc: Exception) -> Set[str]:
        """Failure -> set of lineages to charge and restart.  Raises a
        typed query error for anything non-retryable."""
        failed: Set[str] = set()
        if isinstance(exc, RemoteTaskError):
            if not is_retryable_type(exc.error_type):
                # only USER_ERROR is non-retryable: surface the typed
                # user error so upper layers also skip query-level retry
                raise PrestoUserError(
                    f"query failed [{exc.error_type}]: {exc}") from exc
            self._add_culprit(failed, str(exc), exc.location)
            if exc.error_type == INTERNAL_ERROR:
                worker = exc.location.split("/v1/task/", 1)[0]
                for lin in failed:
                    self._note_internal_failure(lin, worker, str(exc))
        elif isinstance(exc, ExchangeLostError):
            worker = exc.location.split("/v1/task/", 1)[0]
            self.suspects.add(worker)
            self._add_culprit(failed, str(exc), exc.location)
        else:
            assert isinstance(exc, _FailureSignal)
            for ev in exc.events:
                kind = ev["kind"]
                if kind == "failed":
                    et = ev.get("error_type") or parse_error_type(
                        ev.get("message", ""))
                    if not is_retryable_type(et):
                        raise PrestoUserError(
                            f"task {ev['task_id']} failed [{et}]: "
                            f"{ev['message']}") from exc
                    self._add_culprit(failed, ev.get("message", ""),
                                      ev["task_id"])
                    if et == INTERNAL_ERROR:
                        self._note_internal_failure(
                            self._lineage_of_task(ev["task_id"]),
                            ev.get("worker_uri", ""),
                            ev.get("message", ""))
                else:  # task_lost / worker_lost
                    self.suspects.add(ev["worker_uri"])
                    lin = self._lineage_of_task(ev["task_id"])
                    if lin is not None:
                        failed.add(lin)
        if not failed:
            raise PrestoQueryError(
                f"query failed (unattributable): {exc}") from exc
        return failed

    def _add_culprit(self, failed: Set[str], text: str,
                     fallback: str) -> None:
        # fallback may be a buffer location or a bare task id
        tid = fallback.rsplit("/v1/task/", 1)[-1].split("/", 1)[0]
        lin = self._culprit_lineage(text, tid)
        if lin is not None:
            failed.add(lin)

    def _note_internal_failure(self, lineage: Optional[str], worker: str,
                               message: str) -> None:
        """Poison-split quarantine bookkeeping: the same INTERNAL error
        signature for the same task lineage on >= 2 DISTINCT workers is
        deterministic, not infrastructure — fail fast with the split
        identity instead of burning the remaining attempt budget."""
        # A consumer observing its producer's failure quotes the producer's
        # buffer location; the DEEPEST quoted location names the true
        # culprit AND the worker that hosted it (the caller only knows the
        # outermost wrapper's worker, which is the wrong attribution).
        for wkr, tid in reversed(_SOURCE_LOCATIONS.findall(message or "")):
            lin = self._lineage_of_task(tid)
            if lin is not None:
                lineage, worker = lin, wkr
                break
        if not lineage or not worker:
            return
        sig = _failure_signature(message)
        key = (lineage, sig)
        workers = self.failure_workers.setdefault(key, set())
        workers.add(worker)
        if len(workers) >= 2:
            raise PoisonSplitError(lineage, workers, sig)

    def _restart(self, lineages: Set[str], cause: Exception) -> None:
        """Restart every failed lineage.  Under retry-policy=query the
        restart set also covers ALL tasks of every ancestor stage
        (consumer locations are baked into TaskSources, so a new producer
        attempt invalidates its consumers; the root's restart resets the
        collected output — exactly-once).  Under retry-policy=task the
        failed lineage restarts ALONE: its output replays from the durable
        spool and surviving consumers get their source locations refreshed
        in place, so no ancestor stage re-runs.  Only the originally
        failed lineages are charged against the attempt budget."""
        if self.max_attempts <= 0:
            raise PrestoQueryError(
                f"query failed (task retry disabled): {cause}") from cause
        for lin in sorted(lineages):
            used = self.budget_used.get(lin, 0) + 1
            if used > self.max_attempts:
                raise PrestoQueryError(
                    f"task {lin} failed after {self.max_attempts} retry "
                    f"attempt(s): {cause}") from cause
            self.budget_used[lin] = used
        self.retries += len(lineages)
        restart: Dict[int, Set[int]] = {}  # id(stage) -> task indices
        stage_by_id = {id(s): s for s in self.stages}
        for lin in lineages:
            stage, ti = self.lineage_index[lin]
            restart.setdefault(id(stage), set()).add(ti)
            if self.retry_policy == "task":
                continue  # spooled output: no ancestor cascade
            anc = stage.parent
            while anc is not None:
                restart[id(anc)] = set(range(anc.n_tasks))
                anc = anc.parent
        # cancel superseded attempts first so workers stop computing and
        # release buffer memory (retained buffers only die on teardown)
        for sid, indices in restart.items():
            stage = stage_by_id[sid]
            for ti in indices:
                old = stage.tasks[ti]
                if old is not None:
                    threading.Thread(target=old.cancel, daemon=True).start()
                stage.tasks[ti] = None
                self.id_attempt[self.lineage(stage, ti)] = \
                    self.id_attempt.get(self.lineage(stage, ti), 0) + 1
        for stage in self.stages:  # postorder: new producers first
            if id(stage) not in restart:
                continue
            for ti in sorted(restart[id(stage)]):
                self._place_task(stage, ti)
        if self.retry_policy == "task":
            self._refresh_consumers(restart, stage_by_id)

    def _refresh_consumers(self, restarted: Dict[int, Set[int]],
                           stage_by_id: Dict[int, _Stage]) -> None:
        """retry-policy=task: each SURVIVING consumer of a restarted
        producer gets a fragment-less task update carrying refreshed
        source locations, so its live exchange pulls redirect to the
        replacement attempt's buffers mid-stream (consumers that were
        themselves restarted already baked in the new locations)."""
        parents: Dict[int, _Stage] = {}
        for sid in restarted:
            parent = stage_by_id[sid].parent
            if parent is not None:
                parents[id(parent)] = parent
        for pid, parent in parents.items():
            replaced = restarted.get(pid, set())
            for ti, task in enumerate(parent.tasks):
                if task is None or ti in replaced:
                    continue
                req = TaskUpdateRequest(
                    task.task_id, ti, None,
                    self._make_sources(parent, ti), parent.spec,
                    session=self.session)
                try:
                    task.update(req, deadline_ms=self._deadline_ms())
                except (urllib.error.URLError, urllib.error.HTTPError,
                        TimeoutError, OSError):
                    pass  # the watcher surfaces a truly dead consumer

    def query_info_snapshot(self) -> dict:
        """Stage/task/operator breakdown for /v1/query/{id} (the reference
        QueryInfo.outputStage drill-down): one TaskInfo fetch per current
        task plus the cross-task per-plan-node operator rollup, keyed the
        same way the EXPLAIN ANALYZE annotator reads it.  Unreachable
        workers degrade to a stub entry instead of failing the snapshot."""
        from ..exec.scheduler import merge_node_stats
        merged: Dict[str, dict] = {}
        stages = []
        for stage in self.stages:
            tasks = []
            stage_cpu = 0
            stage_wall = 0
            stage_peak = 0
            for task in stage.tasks:
                if task is None:
                    continue
                try:
                    info = task.info()
                except (OSError, ValueError):
                    info = {"taskId": task.task_id, "unreachable": True}
                for pipe in info.get("pipelines", []):
                    for op in pipe.get("operators", []):
                        if op.get("stats"):
                            merge_node_stats(
                                merged, {op["planNodeId"]: op["stats"]})
                tstats = info.get("stats", {})
                stage_cpu += int(tstats.get("totalCpuTimeInNanos", 0))
                stage_wall += int(tstats.get("driverWallTimeInNanos", 0))
                stage_peak += int(
                    tstats.get("peakTotalMemoryInBytes", 0) or 0)
                tasks.append({"worker": task.worker_uri, **info})
            stages.append({"stageId": f"{self.qid}.{stage.stage_path}",
                           "fragmentId": stage.fragment.fragment_id,
                           "partitioning": stage.fragment.partitioning,
                           "nTasks": stage.n_tasks,
                           # cumulative driver thread-time vs wall across
                           # the stage's tasks (the reference StageStats
                           # totalCpuTime/totalScheduledTime pair): the
                           # gap is scheduling + device + exchange waits
                           "cpuTimeInNanos": stage_cpu,
                           "wallTimeInNanos": stage_wall,
                           "peakMemoryBytes": stage_peak,
                           "tasks": tasks})
        return {"traceToken": self.trace_token, "stages": stages,
                "peakMemoryBytes": sum(st.get("peakMemoryBytes", 0)
                                       for st in stages),
                "operatorStats": merged}

    def peak_memory_bytes(self) -> int:
        """Cluster-wide peak: the sum of per-task memory-pool peaks
        (reference peakTotalMemoryReservation).  Fetched task-by-task
        AFTER the drain, so admission history seeding records what the
        distributed run actually reserved instead of 0."""
        total = 0
        for t in self.all_tasks:
            if t is None:
                continue
            try:
                stats = t.info(timeout_s=5).get("stats") or {}
                total += int(stats.get("peakTotalMemoryInBytes", 0) or 0)
            except (OSError, ValueError):
                continue
        return total

    def close(self) -> None:
        if self._watcher is not None:
            self._watcher.close()
        if self._df_pump is not None:
            self._df_pump.close()
        for t in self.all_tasks:
            t.cancel()


class HttpQueryRunner(LocalQueryRunner):
    """Schedules fragment DAGs over real HTTP workers — the external-worker
    integration point the reference reaches through
    DistributedQueryRunner.setExternalWorkerLauncher
    (presto-tests/.../DistributedQueryRunner.java:190-215)."""

    def __init__(self, worker_uris: List[str], schema: str = "sf0.01",
                 failure_detector: Optional[HeartbeatFailureDetector] = None,
                 config: Optional[ExecutionConfig] = None,
                 n_tasks: int = 2, broadcast_threshold: int = 600_000,
                 session: Optional[Dict[str, str]] = None,
                 catalog: str = "tpch"):
        super().__init__(schema, config, catalog)
        self.worker_uris = worker_uris
        self.failure_detector = failure_detector
        self.n_tasks = n_tasks
        self.broadcast_threshold = broadcast_threshold
        self.session = session or {}
        self._rr = itertools.count()
        # lifetime counters across queries (surfaced via /v1/metrics when
        # this runner backs a coordinator's statement endpoint)
        self.tasks_retried = 0
        self.queries_failed = 0
        # observability side channels: the most recent _QueryExecution
        # (QueryInfo drill-down) and ANALYZE rollup / snapshot
        self.last_execution: Optional[_QueryExecution] = None
        self.last_query_info: Optional[dict] = None

    def _live_uris(self) -> List[str]:
        """Schedulable workers (reference NodeScheduler.createNodeSelector
        consuming the failure detector's view)."""
        if self.failure_detector is None:
            return self.worker_uris
        live = self.failure_detector.alive()
        if not live:
            raise RuntimeError("no live workers")
        return live

    # -- planning ---------------------------------------------------------
    def plan_subplan(self, sql: str):
        from ..sql.fragmenter import FragmenterConfig, plan_distributed
        output = self.plan(sql)
        names = output.column_names
        types = [v.type for v in output.outputs]
        cfg = FragmenterConfig(broadcast_threshold=self.broadcast_threshold)
        with self._validation():
            sub = plan_distributed(output, cfg, exec_config=self.config)
        return sub, names, types

    def _build_stages(self, subplan: P.SubPlan,
                      stage_path: str = "0") -> _Stage:
        children = [self._build_stages(c, f"{stage_path}.{i}")
                    for i, c in enumerate(subplan.children)]
        frag = subplan.fragment
        if frag.partitioning in (P.SOURCE_DISTRIBUTION,
                                 P.FIXED_HASH_DISTRIBUTION):
            n_tasks = self.n_tasks
        else:
            n_tasks = 1
        return _Stage(frag, children, n_tasks, stage_path)

    def _explain_http(self, ast, trace_token: str = "") -> QueryResult:
        """EXPLAIN over the HTTP-distributed plan.  ANALYZE executes the
        fragment DAG on the real workers with per-operator stats collection
        enabled in every task's session, then annotates each fragment from
        the TaskInfo rollup (the coordinator side of the task -> stage ->
        coordinator merge)."""
        from ..common.types import VarcharType
        from ..sql.explain import format_analyze_footer, format_subplan
        from ..sql.fragmenter import FragmenterConfig, plan_distributed
        from ..sql.planner import Planner
        if ast.explain_type == "VALIDATE":
            return self._explain_validate(ast)
        with self._validation():
            output = Planner(default_schema=self.schema,
                             default_catalog=self.catalog) \
                .plan_query_to_output(ast.query)
            subplan = plan_distributed(
                output,
                FragmenterConfig(
                    broadcast_threshold=self.broadcast_threshold),
                exec_config=self.config)
        stats = None
        footer = ""
        if ast.analyze:
            from ..telemetry import profile_capture
            root = self._build_stages(subplan)
            qid = (f"q{next(_query_counter)}_"
                   f"{int(time.time() * 1000) % 100000}")
            saved = self.session
            self.session = {**self.session,
                            "collect_operator_stats": "true"}
            try:
                execution = _QueryExecution(self, root, qid,
                                            trace_token=trace_token)
                self.last_execution = execution
                try:
                    # device capture covers only the coordinator's slice
                    # (root pull + in-process loopback workers); remote
                    # workers profile their own processes
                    with profile_capture(self.config.profile_dir, qid,
                                         enabled=self.config.profile) \
                            as trace_dir:
                        execution.run()
                    snapshot = execution.query_info_snapshot()
                finally:
                    self.tasks_retried += execution.retries
                    execution.close()
            finally:
                self.session = saved
            stats = snapshot["operatorStats"]
            self.last_operator_stats = stats
            self.last_query_info = snapshot
            # footer counters (fusionDeclined*/fusedProgramWallNanos) are
            # recorded in each TASK's RuntimeStats on its worker: merge
            # them across tasks, on top of the coordinator's own root-pull
            # stats
            merged_rs = execution.stats.to_dict()
            for st in snapshot["stages"]:
                for t in st["tasks"]:
                    src = (t.get("stats") or {}).get("runtimeStats") or {}
                    for k, v in src.items():
                        e = merged_rs.get(k)
                        if e is None:
                            merged_rs[k] = dict(v)
                        else:
                            e["sum"] += v["sum"]
                            e["count"] += v["count"]
                            e["min"] = min(e["min"], v["min"])
                            e["max"] = max(e["max"], v["max"])
            footer = format_analyze_footer(merged_rs,
                                           profile_dir=trace_dir)
        text = format_subplan(subplan, stats)
        if footer:
            text += "\n\n" + footer
        return QueryResult(["Query Plan"],
                           [VarcharType(max(1, len(text)))], [[text]])

    # -- execution --------------------------------------------------------
    def execute(self, sql: str, trace_token: str = "") -> QueryResult:
        from ..sql import parser as A
        try:
            ast = A.parse_sql(sql)
        except Exception:
            ast = None
        if ast is not None and isinstance(ast, A.Explain):
            return self._explain_http(ast, trace_token=trace_token)
        subplan, names, types = self.plan_subplan(sql)
        root = self._build_stages(subplan)
        qid = f"q{next(_query_counter)}_{int(time.time() * 1000) % 100000}"
        execution = _QueryExecution(self, root, qid,
                                    trace_token=trace_token)
        self.last_execution = execution
        try:
            pages = execution.run()
            result = pages_to_result(iter(pages), names, types)
            result.runtime_stats = execution.stats.to_dict()
            try:
                # per-task memory-pool peaks roll into the result so the
                # QueryCompletedEvent / history record carries a real
                # peak for adaptive admission seeding (was always 0)
                result.peak_memory_bytes = execution.peak_memory_bytes()
            except Exception:   # noqa: BLE001 — stats are best-effort
                pass
            return result
        except Exception:
            self.queries_failed += 1
            raise
        finally:
            self.tasks_retried += execution.retries
            execution.close()
