"""Stateless query router across coordinators/clusters.

The analog of presto-router (RouterResource + pluggable scheduler,
presto-router/.../router/) combined with the plan-checker router plugin
(presto-plan-checker-router-plugin: send a query to the TPU-native cluster
only if its planner accepts it, else fall back to another cluster —
`javaClusterFallbackEnabled`, PlanCheckerRouterPluginConfig.java:36).

The router serves the same `POST /v1/statement` surface clients already
speak and answers with an HTTP 307 redirect to the chosen cluster's
statement endpoint — the reference router does exactly this (clients
follow the redirect and then poll `nextUri` on the target coordinator
directly, so the router stays stateless and off the data path).

Schedulers: round_robin (RandomChoice/RoundRobin analogs) and
plan_check — validate the SQL against the native planner first and route
unplannable queries to the configured fallback cluster (the sidecar plan
validation seam, presto-native-sidecar-plugin/.../nativechecker/)."""
from __future__ import annotations

import itertools
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional


def plan_checks(sql: str, schema: str = "sf0.01",
                catalog: str = "tpch") -> Optional[str]:
    """None when the native planner accepts the statement, else the
    planning error (the /v1/plan-check validation used by the router and
    exposed by the coordinator as a sidecar endpoint)."""
    from ..sql.planner import Planner, PlanningError
    from ..sql import parser as A
    try:
        ast = A.parse_sql(sql)
        if isinstance(ast, (A.CreateTableAs, A.InsertInto)):
            Planner(schema, catalog).plan_write(ast)
        elif isinstance(ast, A.DropTable):
            pass
        else:
            q = ast.query if isinstance(ast, A.Explain) else ast
            Planner(schema, catalog).plan_query_to_output(q)
        return None
    except Exception as e:  # noqa: BLE001 — any failure = not plannable
        return f"{type(e).__name__}: {e}"


class QueryRouter:
    """HTTP router process: POST /v1/statement -> 307 to a cluster."""

    def __init__(self, clusters: List[str], port: int = 0,
                 scheduler: str = "round_robin",
                 fallback: Optional[str] = None):
        """clusters: coordinator base URIs the router balances over.
        scheduler 'plan_check': route to clusters[...] only when the native
        planner accepts the query, else to `fallback`."""
        self.clusters = list(clusters)
        self.scheduler = scheduler
        self.fallback = fallback
        self._rr = itertools.count()
        self._lock = threading.Lock()

        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def do_POST(self):
                if not re.match(r"^/v1/statement/?$", self.path):
                    self._reply(404, b'{"error": "not found"}')
                    return
                length = int(self.headers.get("Content-Length", 0))
                sql = self.rfile.read(length).decode()
                target = router.route(
                    sql,
                    schema=self.headers.get("X-Presto-Schema", "sf0.01"),
                    catalog=self.headers.get("X-Presto-Catalog", "tpch"))
                if target is None:
                    self._reply(503, b'{"error": "no cluster available"}')
                    return
                self.send_response(307)
                self.send_header("Location", f"{target}/v1/statement")
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                if self.path == "/v1/router/clusters":
                    import json
                    body = json.dumps({
                        "clusters": router.clusters,
                        "scheduler": router.scheduler,
                        "fallback": router.fallback}).encode()
                    self._reply(200, body)
                    return
                self._reply(404, b'{"error": "not found"}')

            def _reply(self, code: int, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_port
        self.uri = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name=f"router-{self.port}",
                                        daemon=True)
        self._thread.start()

    def route(self, sql: str, schema: str = "sf0.01",
              catalog: str = "tpch") -> Optional[str]:
        if self.scheduler == "plan_check":
            if plan_checks(sql, schema, catalog) is None:
                return self._next()
            return self.fallback
        return self._next()

    def _next(self) -> Optional[str]:
        if not self.clusters:
            return self.fallback
        with self._lock:
            return self.clusters[next(self._rr) % len(self.clusters)]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
