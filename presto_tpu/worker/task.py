"""Worker-side task manager: TaskUpdateRequest -> running pipeline.

The analog of the reference SqlTaskManager/SqlTaskExecution
(presto-main-base/.../execution/SqlTaskManager.java:103,
SqlTaskExecution.java:83) and the native TaskManager
(presto_cpp/main/TaskManager.cpp:493): decode the base64 plan fragment,
build a TaskContext from the shipped splits and remote-source locations,
run the compiled pipeline on an executor thread, and stream output pages
into token-acknowledged output buffers, hash-partitioned per the fragment's
output partitioning scheme.
"""
from __future__ import annotations

import threading
import traceback
from typing import Callable, Dict, List, Optional

from ..common.errors import (INTERNAL_ERROR, USER_ERROR, InjectedTaskFailure,
                             QueryDeadlineExceededError, classify_exception)
from ..common.locks import OrderedCondition, OrderedLock, validation_scope
from ..common.serde import serialize_page
from ..connectors import catalog, tpch
from ..exec.pipeline import (ExecutionConfig, PlanCompiler, TaskContext,
                             tuned_config)
from ..exec.scheduler import partition_targets, split_page
from ..spi import plan as P
from .buffers import OutputBufferManager
from .exchange import remote_page_reader
from .protocol import (DONE_STATES, FAILED, FINISHED, PLANNED, RUNNING,
                       CANCELED, TaskStatus, TaskUpdateRequest)


class TpuTask:
    """One task: state machine + executor thread + output buffers."""

    def __init__(self, task_id: str, self_uri: str, config: ExecutionConfig,
                 events=None, manager=None):
        self.task_id = task_id
        self.self_uri = self_uri
        self.config = config
        self.events = events
        self.manager = manager
        self.state = PLANNED              # lint: guarded-by(_cond)
        self.version = 0                  # lint: guarded-by(_cond)
        self.failures: List[str] = []     # lint: guarded-by(_cond)
        self.error_type = ""              # lint: guarded-by(_cond)
        self.buffers: Optional[OutputBufferManager] = None
        self.done_at: Optional[float] = None  # lint: guarded-by(_cond)
        self.memory_peak = 0
        self.memory_ctx = None            # task MemoryContext (set by start)
        # TaskInfo stats surface (reference TaskInfo/TaskStats): the
        # coordinator-side aggregation and UI drill-down consume these
        import time as _t
        self.created_at = _t.time()
        self.output_rows = 0
        self.output_pages = 0
        self.output_bytes = 0
        self.plan_nodes: List[dict] = []
        from ..utils.runtime_stats import RuntimeStats
        self.stats = RuntimeStats()       # exchange-client walls/bytes etc.
        # X-Presto-Trace-Token propagated by the coordinator (session key
        # "trace_token"); echoed back in TaskInfo so a trace id observed at
        # the coordinator can be joined against worker-side task records
        self.trace_token = ""
        # X-Presto-Task-Deadline: the query's remaining wall budget at
        # dispatch time, converted to a worker-local monotonic deadline
        # (relative ms avoids any coordinator<->worker clock agreement);
        # enforced by the _run page loop and the TaskManager reaper
        self._deadline: Optional[float] = None
        self._deadline_budget_s = 0.0
        # remote-source locations by plan node, shared BY REFERENCE with
        # this task's exchange readers so a coordinator task-retry can
        # redirect live pulls to the replacement attempt's buffers
        self._remote_locations: Dict[str, List[str]] = {}
        self._remote_clients: Dict[str, list] = {}
        # runtime dynamic filters (exec/adaptive.py): summaries RECEIVED
        # from the coordinator (filter id -> wire dict, shared by
        # reference with the TaskContext so late deliveries still prune
        # splits not yet drained) and summaries PRODUCED by this task's
        # own output (published through TaskInfo for collection)
        self.dynamic_filters: Dict[str, dict] = {}  # lint: guarded-by(_cond)
        self.dynamic_filter_summaries: Dict[str, dict] = {}
        self._df_wait_done = False        # lint: guarded-by(_cond)
        # rank 16: above the task manager (14), below every data-plane
        # lock; _set_state never nests (events and the manager counter
        # fire after release)
        self._cond = OrderedCondition("task-state", 16)
        self._thread: Optional[threading.Thread] = None

    def info(self) -> dict:
        """TaskInfo payload (reference TaskInfo.java shape, scoped to the
        fields our coordinator consumes: status + task-level stats + the
        fragment's plan-node inventory)."""
        import time as _t
        status = self.status()
        return {
            "taskId": self.task_id,
            "taskStatus": status.to_dict(),
            "traceToken": self.trace_token,
            "noMoreSplits": True,
            # build-side dynamic-filter summaries this task produced
            # (fragment.dynamic_filter_sources); the coordinator merges
            # them across the stage's tasks and pushes the result to the
            # downstream scan tasks (worker/coordinator.py)
            "dynamicFilterSummaries": dict(self.dynamic_filter_summaries),
            "stats": {
                "createTime": self.created_at,
                # drain-pipeline wall when task_concurrency > 1: serialize
                # wall overlapping it is (elapsed - drain) — the local-
                # exchange overlap surface (TaskStats per-pipeline walls)
                "drainPipelineWallS": round(
                    getattr(self, "_drain_wall", [0.0])[0], 4),
                "elapsedTimeInNanos": int(
                    (_t.time() - self.created_at) * 1e9),
                # driver thread-time vs driver wall (sampled at the _run
                # boundaries): the per-stage CPU/wall attribution in
                # /v1/query/{id} sums these across the stage's tasks
                "totalCpuTimeInNanos": getattr(
                    self, "_driver_cpu_nanos", 0),
                "driverWallTimeInNanos": getattr(
                    self, "_driver_wall_nanos", 0),
                "outputPositions": self.output_rows,
                "outputDataSizeInBytes": self.output_bytes,
                "bufferedPages": self.output_pages,
                "peakTotalMemoryInBytes": self.memory_peak,
                # arbitrated-pool surface: revocation is observable per
                # task (spilledBytes > 0 after a revoke/self-spill), and
                # retained output pages appear as revocable bytes
                "spilledBytes": (
                    0 if self.memory_ctx is None
                    else self.memory_ctx.pool.spilled_bytes),
                # fault-tolerant mode: raw bytes durably staged through the
                # task's output spool (0 under retry-policy=query)
                "spooledBytes": (
                    0 if self.buffers is None
                    else self.buffers.spooled_bytes),
                "memoryReservedBytes": (
                    0 if self.memory_ctx is None
                    else self.memory_ctx.pool.reserved),
                "memoryRevocableBytes": (
                    0 if self.memory_ctx is None
                    else self.memory_ctx.pool.revocable),
                "memoryOverFree": (
                    0 if self.memory_ctx is None
                    else self.memory_ctx.pool.over_free_count),
                "state": self.state,
                # the wire this task's remote-source inputs rode: the
                # worker protocol pulls pages over HTTP regardless of the
                # configured preference (ICI engages only inside a
                # mesh-pinned in-process stage, exec/scheduler.py)
                "exchangeFabric": "http",
                "exchangeFabricRequested": getattr(
                    self.config, "exchange_fabric", "auto"),
                # which fused-scan implementation this task's config
                # requested (exec/kernels Pallas vs XLA chain); actual
                # engagement is per-scan via the kernelScanPrograms /
                # kernelDeclined{reason} runtime-stats counters
                "scanKernel": getattr(
                    self.config, "scan_kernel", "auto"),
                "runtimeStats": self.stats.to_dict(),
            },
            "pipelines": [{
                "operators": self.plan_nodes,
            }],
        }

    # -- state ------------------------------------------------------------
    def _set_state(self, state: str, failure: Optional[str] = None,
                   error_type: str = "") -> None:
        import time
        with self._cond:
            if self.state in DONE_STATES:
                return
            self.state = state
            self.version += 1
            if failure:
                self.failures.append(failure)
                if not self.error_type:
                    self.error_type = error_type or INTERNAL_ERROR
            if state in DONE_STATES:
                self.done_at = time.monotonic()
            self._cond.notify_all()
        if state == FAILED and self.manager is not None:
            # lifetime counter: incremented under the MANAGER's lock (this
            # used to be a bare cross-object `+= 1` racing every executor
            # thread), and only after _cond is released — task-state (16)
            # never nests into task-manager (14)
            self.manager.note_task_failed()
        if state in DONE_STATES and self.events is not None:
            # task-level terminal event from the WORKER path (reference
            # QueryMonitor per-task stats; listener isolation inside the
            # manager keeps a broken listener from failing the task)
            from .events import TaskCompletedEvent
            now = time.time()
            self.events.task_completed(TaskCompletedEvent(
                task_id=self.task_id, state=state,
                create_time=self.created_at, end_time=now,
                wall_time_s=now - self.created_at,
                output_rows=self.output_rows,
                output_pages=self.output_pages,
                output_bytes=self.output_bytes,
                peak_memory_bytes=self.memory_peak,
                error=failure.splitlines()[-1] if failure else None))

    def status(self) -> TaskStatus:
        with self._cond:
            return TaskStatus(self.task_id, self.state, self.version,
                              self.self_uri, list(self.failures),
                              memory_reservation=self.memory_peak,
                              error_type=self.error_type)

    def wait_status(self, current_state: Optional[str],
                    max_wait_s: float) -> TaskStatus:
        """Long-poll: return when state differs from current_state or the
        wait expires (reference TaskResource.getTaskStatus :189)."""
        import time
        deadline = time.monotonic() + max_wait_s
        with self._cond:
            while (current_state is not None
                   and self.state == current_state
                   and self.state not in DONE_STATES):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
        return self.status()

    def cancel(self) -> None:
        self._set_state(CANCELED)
        if self.buffers:
            # drop undelivered pages and unblock a backpressured producer
            self.buffers.destroy_all()

    def fail(self, message: str, error_type: str = INTERNAL_ERROR) -> None:
        """Force-fail a RUNNING task (TaskManager.abort chaos hook): the
        executor thread observes the terminal state at its next page and
        stops; consumers see the tagged error on their next pull."""
        if self.buffers:
            self.buffers.set_error(
                f"task {self.task_id} failed [{error_type}]: {message}")
        self._set_state(FAILED, message, error_type)

    # -- deadline (X-Presto-Task-Deadline) --------------------------------
    def set_deadline(self, remaining_ms: float) -> None:
        """Arm the task's wall deadline from the header's REMAINING budget
        (the coordinator forwards what's left of query.max-execution-time
        at dispatch; monotonic-local, no clock sync needed)."""
        import time
        self._deadline = time.monotonic() + max(0.0, remaining_ms) / 1000.0
        self._deadline_budget_s = max(0.0, remaining_ms) / 1000.0

    def deadline_exceeded(self) -> bool:
        import time
        return (self._deadline is not None
                and time.monotonic() > self._deadline
                and self.state not in DONE_STATES)

    def _check_deadline(self) -> None:
        """Raise the typed non-retryable time-limit error past deadline
        (called from the _run page loop so device work stops promptly)."""
        import time
        if self._deadline is not None and time.monotonic() > self._deadline:
            over = time.monotonic() - self._deadline
            raise QueryDeadlineExceededError(
                self._deadline_budget_s + over, self._deadline_budget_s,
                context=f"task {self.task_id}")

    def fail_deadline(self) -> None:
        """Reaper-side enforcement: a stuck (or executor-less) task past
        its deadline fails with the same typed user error."""
        import time
        over = (time.monotonic() - self._deadline
                if self._deadline is not None else 0.0)
        err = QueryDeadlineExceededError(
            self._deadline_budget_s + max(0.0, over),
            self._deadline_budget_s, context=f"task {self.task_id}")
        self.fail(str(err), USER_ERROR)

    def _exchange_abort(self) -> None:
        """should_abort hook for this task's exchange clients: once the
        task is terminal (FAILED sibling propagated, canceled, finished)
        every remote-source pull stops promptly instead of draining."""
        if self.state in DONE_STATES:
            from .exchange import ExchangeAbortedError
            raise ExchangeAbortedError(
                f"task {self.task_id} is {self.state}; aborting exchange "
                f"pull")

    def deliver_dynamic_filters(self, filters: Dict[str, dict]) -> None:
        """Coordinator push of collected build-side summaries.  The dict
        handed to this task's TaskContext is SHARED and updated in place,
        so a summary landing while the task runs still prunes splits not
        yet drained (late binding, no recompile).  One arriving after the
        bounded pre-start wait already expired is metered as a late
        arrival — never an error (the scan simply ran unfiltered)."""
        from ..exec.adaptive import ADAPTIVE_METRICS
        with self._cond:
            self.dynamic_filters.update(filters)
            late = self._df_wait_done
            self._cond.notify_all()
        if late:
            ADAPTIVE_METRICS.incr("filter_late_arrivals", len(filters))

    def _await_dynamic_filters(self, fragment: P.PlanFragment,
                               ctx: TaskContext) -> None:
        """Bounded pre-execution wait for the dynamic filters this
        fragment's scans are annotated to consume
        (dynamic-filtering.wait-timeout; reference
        DynamicFilterService#blockUntilDynamicFilter).  On timeout the
        scan proceeds unfiltered — pruning is advisory, so waiting
        forever for a filter that may never arrive (killed build worker)
        would trade availability for nothing."""
        import time
        from ..exec.adaptive import ADAPTIVE_METRICS
        expected = set()
        if ctx.config.dynamic_filtering:
            for n in P.walk_plan(fragment.root):
                if isinstance(n, P.TableScanNode):
                    for e in getattr(n, "runtime_filters", ()) or ():
                        expected.add(e["id"])
        timed_out = False
        deadline = time.monotonic() + max(
            0.0, ctx.config.dynamic_filtering_wait_timeout_s)
        with self._cond:
            while expected - set(self.dynamic_filters) \
                    and self.state not in DONE_STATES:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    timed_out = True
                    break
                self._cond.wait(remaining)
            self._df_wait_done = True
        if timed_out:
            ADAPTIVE_METRICS.incr("filter_wait_timeouts")

    def update_remote_sources(self, sources) -> None:
        """Fragment-less task update (coordinator task-retry under
        retry-policy=task): a failed PRODUCER was replaced by a new
        attempt, so this consumer's exchange pulls must redirect to the
        replacement's buffer locations.  The stored location lists are
        mutated IN PLACE (fresh clients pick them up) and every live
        client is told to relocate, resuming each stream at its delivered
        token — exactly-once because the spool replays deterministically
        from 0."""
        from .plan_translation import translate_split
        for source in sources:
            old = self._remote_locations.get(source.plan_node_id)
            if old is None:
                continue
            splits = [translate_split(s) for s in source.splits]
            new_locs = [s["location"] for s in splits if s.get("remote")]
            if not new_locs:
                continue
            old[:] = new_locs
            for client in self._remote_clients.get(source.plan_node_id, []):
                try:
                    client.update_locations(new_locs)
                except Exception:
                    pass  # a closed client has nothing to redirect

    # -- execution ----------------------------------------------------------
    def start(self, update: TaskUpdateRequest) -> None:
        try:
            fragment = update.fragment()
            spec = update.output_buffers
            from ..exec.memory import MemoryContext, MemoryPool
            from .protocol import apply_session_properties
            cfg = apply_session_properties(self.config, update.session)
            # the task's node of the query->task->operator context tree:
            # the arbitrated pool below it serves both the executor's
            # operators and the output buffers' retained-page charge, and
            # a query.max-memory ceiling rides in as max_bytes
            self.memory_ctx = MemoryContext(
                MemoryPool(cfg.memory_budget_bytes),
                f"task/{self.task_id}",
                max_bytes=cfg.memory_max_query_bytes)
            # retry mode makes buffers replayable: a retried consumer
            # re-reads from token 0, so acknowledged pages must survive —
            # charged to this task's context as revocable bytes (spilled
            # to disk by the arbitrator under pressure).  retry-policy=task
            # goes further: output pages are DURABLY spooled (host-RAM
            # staging -> LZ4 block file) and retained past task completion,
            # so a failed task retries alone — no ancestor restart — and a
            # draining worker's output survives its exit
            spool = None
            if getattr(cfg, "retry_policy", "query") == "task":
                from .spooling import TaskSpool
                spool = TaskSpool(
                    self.task_id, spec.n_buffers,
                    spool_dir=cfg.spool_path or cfg.spill_path,
                    memory=self.memory_ctx,
                    staging_budget_bytes=cfg.spool_staging_budget_bytes)
            self.buffers = OutputBufferManager(
                spec.type, spec.n_buffers,
                retain=spool is None and cfg.remote_task_retry_attempts > 0,
                coalesce_target_bytes=cfg.exchange_max_response_bytes,
                memory=self.memory_ctx, spill_dir=cfg.spill_path,
                spool=spool)
            if update.dynamic_filters:
                # summaries known at dispatch time (build stage already
                # finished) ride the create request — no wait needed
                self.dynamic_filters.update(update.dynamic_filters)
            ctx = TaskContext(config=cfg, task_index=update.task_index,
                              memory=self.memory_ctx,
                              runtime_stats=self.stats,
                              dynamic_filters=self.dynamic_filters)
            self.trace_token = update.session.get("trace_token", "")
            if self.trace_token:
                print(f"[trace {self.trace_token}] task {self.task_id} "
                      f"starting")
            if str(update.session.get(
                    "collect_operator_stats", "")).lower() == "true":
                # coordinator-requested per-node operator stats (EXPLAIN
                # ANALYZE / QueryInfo drill-down): enable the same node-id
                # keyed stats dict the local ANALYZE path uses; merged into
                # the TaskInfo plan-node inventory when the task finishes
                ctx.stats = {}
            from .plan_translation import translate_split
            for source in update.sources:
                splits = [translate_split(s) for s in source.splits]
                remote = [s["location"] for s in splits if s.get("remote")]
                conn = [s for s in splits if not s.get("remote")]
                if remote:
                    # should_abort: a sibling failure (or cancel) puts this
                    # task in a terminal state, and the exchange pull must
                    # stop with it instead of draining a doomed query.
                    # The location list is kept (by reference) and every
                    # client created is registered, so a coordinator task
                    # retry can redirect live pulls mid-stream
                    # (update_remote_sources).
                    self._remote_locations[source.plan_node_id] = remote
                    nid = source.plan_node_id
                    ctx.remote_pages[nid] = \
                        remote_page_reader(
                            remote, codec=cfg.exchange_compression_codec,
                            max_error_duration_s=
                            cfg.exchange_max_error_duration_s,
                            should_abort=self._exchange_abort,
                            client_threads=cfg.exchange_client_threads,
                            max_buffer_bytes=cfg.exchange_max_buffer_bytes,
                            max_response_bytes=
                            cfg.exchange_max_response_bytes,
                            stats=self.stats,
                            park_on_failure=(
                                getattr(cfg, "retry_policy", "query")
                                == "task"),
                            on_client=lambda c, n=nid: (
                                self._remote_clients.setdefault(
                                    n, []).append(c)))
                if conn:
                    ctx.splits[source.plan_node_id] = [
                        catalog.TableSplit.from_dict(s) for s in conn]
        except Exception as e:
            # a malformed update (bad fragment, bad session property) must
            # fail the task, not strand it in PLANNED (the coordinator
            # sees FAILED on its next status poll, TaskResource.cpp:242-255)
            error_type = classify_exception(e)
            message = traceback.format_exc()
            if self.buffers is None:
                self.buffers = OutputBufferManager("PARTITIONED", 1)
            self.buffers.set_error(
                f"task {self.task_id} failed to start "
                f"[{error_type}]:\n{message}")
            self._set_state(FAILED, message, error_type)
            return

        self._set_state(RUNNING)
        self._thread = threading.Thread(
            target=self._run, args=(fragment, spec, ctx),
            name=f"task-{self.task_id}", daemon=True)
        self._thread.start()

    def _inject_fault(self, ctx: TaskContext) -> None:
        """Chaos hooks (the HTTP-worker mirror of the batch scheduler's
        SchedulerConfig.fault_injector): a manager-level injector callable
        and a config/session probability.  The probabilistic roll is a
        DETERMINISTIC hash of the task id, so a given chaos run replays
        exactly and a retry (new attempt id) rolls independently."""
        if self.manager is not None and self.manager.fault_injector:
            self.manager.fault_injector(self.task_id)
        p = ctx.config.fault_injection_probability
        if p > 0.0:
            import hashlib
            h = int.from_bytes(hashlib.sha256(
                self.task_id.encode()).digest()[:8], "big")
            if h % 1_000_000 < p * 1_000_000:
                raise InjectedTaskFailure(
                    f"injected task failure (p={p}, task {self.task_id})")

    def _run(self, fragment: P.PlanFragment, spec, ctx: TaskContext) -> None:
        # debug.lock-validation=on (worker property or lock_validation
        # session override): every OrderedLock acquisition made while this
        # task executes — by ANY thread, the flag is process-global and
        # counting so concurrent scoped tasks compose — is checked against
        # the declared rank order and metered into presto_tpu_lock_*
        if getattr(ctx.config, "lock_validation", False):
            with validation_scope():
                return self._run_impl(fragment, spec, ctx)
        return self._run_impl(fragment, spec, ctx)

    def _run_impl(self, fragment: P.PlanFragment, spec,
                  ctx: TaskContext) -> None:
        # driver-boundary CPU vs wall: _run IS the task's driver thread,
        # so thread_time measures its compute and the wall-minus-CPU gap
        # is time spent waiting (device syncs, buffer backpressure,
        # exchange pulls) — surfaced as totalCpuTimeInNanos in TaskInfo
        # and rolled up per stage by the coordinator
        import time as _t
        t0 = _t.perf_counter()  # lint: allow-wall-clock
        c0 = _t.thread_time()
        try:
            self.plan_nodes = [
                {"planNodeId": n.id, "operatorType": type(n).__name__}
                for n in P.walk_plan(fragment.root)]
            self._inject_fault(ctx)
            out_vars = fragment.root.output_variables
            out_types = [v.type for v in out_vars]
            out_names = [v.name for v in out_vars]
            keys = spec.partition_keys
            if keys:
                # explicit keys: a name the fragment doesn't output is a
                # malformed update and must fail loudly
                key_indices = [out_names.index(k) for k in keys]
            else:
                # reference-shaped updates carry no keys in OutputBuffers:
                # the fragment's own partitioning scheme defines them
                scheme = getattr(fragment, "output_partitioning_scheme",
                                 None)
                key_indices = [out_names.index(a.name)
                               for a in (scheme.arguments if scheme
                                         else [])
                               if a.name in out_names]
            n_parts = len(self.buffers.buffers)
            partitioned = (spec.type == "PARTITIONED" and n_parts > 1
                           and key_indices)
            # bounded wait for runtime dynamic filters BEFORE the drain
            # starts, so the scan's first split resolution already sees
            # them; producer-side summarization setup mirrors the
            # in-process scheduler (exec/scheduler._summarize_page_block)
            self._await_dynamic_filters(fragment, ctx)
            from ..exec.scheduler import _summarize_page_block
            dyn_max = ctx.config.dynamic_filtering_max_distinct
            dyn_idx = ([(out_names.index(c), fid)
                        for c, fid in fragment.dynamic_filter_sources.items()
                        if c in out_names]
                       if ctx.config.dynamic_filtering else [])
            task_sums: Dict[str, object] = {}
            compiler = PlanCompiler(ctx)
            pages = compiler.run_to_pages(fragment.root)
            if ctx.config.task_concurrency > 1:
                # overlap pipeline drain (device dispatch + page decode)
                # with serialization + buffering — the two-pipeline shape
                # the reference gets from separate drivers connected by a
                # local exchange.  background_drain owns the thread
                # lifecycle: cancelling the task closes the generator,
                # which stops and unblocks the producer.
                from ..exec.local_exchange import background_drain
                drain_wall = [0.0]
                pages = background_drain(pages, wall_out=drain_wall)
                self._drain_wall = drain_wall
            for page in pages:
                self.memory_peak = ctx.memory.peak
                self._check_deadline()
                if self.state in DONE_STATES:
                    # deterministic shutdown of the drain pipeline (the
                    # generator's close() stops background producers)
                    if hasattr(pages, "close"):
                        pages.close()
                    return
                self.output_rows += page.position_count
                for j, fid in dyn_idx:
                    s = _summarize_page_block(fid, page.blocks[j], dyn_max)
                    prev = task_sums.get(fid)
                    task_sums[fid] = s if prev is None \
                        else prev.merge(s, dyn_max)
                compress = ctx.config.exchange_compression
                codec = ctx.config.exchange_compression_codec
                if partitioned:
                    targets = partition_targets(page, out_types, key_indices,
                                                n_parts)
                    for p, sub in enumerate(
                            split_page(page, targets, n_parts)):
                        if sub is not None:
                            data = serialize_page(sub, compress=compress,
                                                  codec=codec)
                            self.output_pages += 1
                            self.output_bytes += len(data)
                            self.buffers.add(p, data)
                else:
                    data = serialize_page(page, compress=compress,
                                          codec=codec)
                    self.output_pages += 1
                    self.output_bytes += len(data)
                    self.buffers.add(0, data)
            self.memory_peak = ctx.memory.peak
            if dyn_idx:
                # a task with no output still publishes EMPTY summaries:
                # a zero-row build side legitimately prunes every
                # downstream chunk, unlike an absent summary (unknown)
                from ..exec.adaptive import DynamicFilterSummary
                for _j, fid in dyn_idx:
                    if fid not in task_sums:
                        task_sums[fid] = DynamicFilterSummary(
                            fid, row_count=0)
                self.dynamic_filter_summaries = {
                    fid: s.to_dict() for fid, s in task_sums.items()}
            if ctx.stats:
                # attach the collected per-node operator stats to the plan-
                # node inventory (TaskInfo pipelines[].operators[].stats) so
                # the coordinator can roll them up across tasks; everything
                # in the stats dicts is already JSON-safe
                for op in self.plan_nodes:
                    s = ctx.stats.get(op["planNodeId"])
                    if s is not None:
                        op["stats"] = s
            self.buffers.set_complete()
            if self.buffers.spooled_bytes:
                # EXPLAIN ANALYZE footer + coordinator roll-up surface
                self.stats.add("spoolBytes", self.buffers.spooled_bytes,
                               "BYTE")
            self._set_state(FINISHED)
        except Exception as e:
            # tag the failure with its reference error type so consumers
            # (and the coordinator behind them) can decide retryability —
            # a propagated USER_ERROR stays non-retryable end to end
            error_type = classify_exception(e)
            message = traceback.format_exc()
            self.buffers.set_error(
                f"task {self.task_id} failed [{error_type}]:\n{message}")
            self._set_state(FAILED, message, error_type)
        finally:
            wall = _t.perf_counter() - t0  # lint: allow-wall-clock
            self._driver_cpu_nanos = int((_t.thread_time() - c0) * 1e9)
            self._driver_wall_nanos = int(wall * 1e9)
            self.stats.add("driverCpuNanos", self._driver_cpu_nanos,
                           "NANO")
            self.stats.add("driverWallNanos", self._driver_wall_nanos,
                           "NANO")
            try:
                self._export_spans(fragment)
            except Exception:
                pass  # telemetry must never fail a task

    def _export_spans(self, fragment: P.PlanFragment) -> None:
        """Export this task's span subtree into the process telemetry
        exporter.  Span names embed the task id and parent the owning
        fragment's span by NAME — span ids are derived from
        (trace token, name) on both sides (telemetry/otlp.py), so the
        coordinator's `fragment {id}` span and this worker's
        `task {id}` span stitch into one distributed trace without any
        coordinator↔worker handshake."""
        if not self.trace_token:
            return
        from ..telemetry import get_process_exporter
        exp = get_process_exporter()
        if exp is None:
            return
        import time as _t
        from ..utils.runtime_stats import Span
        end = _t.time()
        task_name = f"task {self.task_id}"
        spans = [Span(
            name=task_name,
            parent=f"fragment {fragment.fragment_id}",
            start=self.created_at, end=end,
            attributes={
                "presto.task_id": self.task_id,
                "presto.state": self.state,
                "presto.rows": self.output_rows,
                "presto.pages": self.output_pages,
                "presto.bytes": self.output_bytes,
                "presto.cpu_nanos": getattr(self, "_driver_cpu_nanos", 0),
                "presto.peak_memory_bytes": self.memory_peak,
            })]
        for op in self.plan_nodes:
            attrs = {"presto.operator": op.get("operatorType", ""),
                     "presto.plan_node_id": op.get("planNodeId", "")}
            for k, v in (op.get("stats") or {}).items():
                if isinstance(v, (bool, int, float, str)):
                    attrs[k] = v
            spans.append(Span(
                name=f"operator {self.task_id}.{op.get('planNodeId', '')}",
                parent=task_name,
                start=self.created_at, end=end, attributes=attrs))
        exp.export_spans(self.trace_token, spans,
                         resource={"presto.role": "worker",
                                   "presto.task_uri": self.self_uri})


class TaskManager:
    """Task registry (reference SqlTaskManager.java:103).  Terminal tasks
    are evicted after a grace period — both inline on task creation and by
    a periodic reaper thread (the reference's PeriodicTaskManager task
    cleanup), so a worker that stops receiving new tasks still frees
    terminal tasks and their retained buffers."""

    TASK_TTL_S = 300.0
    REAPER_INTERVAL_S = 15.0

    def __init__(self, base_uri: str = "",
                 config: Optional[ExecutionConfig] = None, events=None):
        self.base_uri = base_uri
        self.config = config or tuned_config()
        self.events = events
        # rank 14: held across _evict_locked -> buffers.destroy_all, which
        # takes buffer conditions (30) and the spool (32) underneath
        self._lock = OrderedLock("task-manager", 14)
        self.tasks: Dict[str, TpuTask] = {}       # lint: guarded-by(_lock)
        self.tasks_created = 0                    # lint: guarded-by(_lock)
        self.tasks_failed = 0                     # lint: guarded-by(_lock)
        self.tasks_retried = 0                    # lint: guarded-by(_lock)
        # chaos hook: fault_injector(task_id) raises to fail the task at
        # start (the worker mirror of SchedulerConfig.fault_injector)
        self.fault_injector: Optional[Callable[[str], None]] = None
        self._reaper_stop: Optional[threading.Event] = None

    def counts(self) -> Dict[str, int]:
        """Live task-state counts + lifetime counters (metrics/status)."""
        with self._lock:
            by_state: Dict[str, int] = {}
            mem_peak = 0
            for t in self.tasks.values():
                by_state[t.state] = by_state.get(t.state, 0) + 1
                mem_peak = max(mem_peak, t.memory_peak)
            return {"created": self.tasks_created, "by_state": by_state,
                    "memory_peak": mem_peak,
                    "failed": self.tasks_failed,
                    "retried": self.tasks_retried}

    def note_task_failed(self) -> None:
        """Lifetime failure counter, bumped by tasks entering FAILED.
        Taken under the manager lock: executor threads from many tasks
        race on it, and a bare `+= 1` loses increments."""
        with self._lock:
            self.tasks_failed += 1

    def _evict_locked(self) -> None:
        import time
        now = time.monotonic()
        dead = [tid for tid, t in self.tasks.items()
                if t.done_at is not None and now - t.done_at > self.TASK_TTL_S]
        for tid in dead:
            if self.tasks[tid].buffers is not None:
                self.tasks[tid].buffers.destroy_all()
            del self.tasks[tid]

    def evict_terminal(self) -> None:
        with self._lock:
            self._evict_locked()
            overdue = [t for t in self.tasks.values()
                       if t.deadline_exceeded()]
        for t in overdue:
            # reaper-side deadline enforcement: even a task whose executor
            # is stuck (device sync, backpressure) fails its deadline
            t.fail_deadline()

    def flush_spools(self) -> int:
        """Graceful drain: force every task's spool staging to disk so
        spooled output survives this worker's exit."""
        with self._lock:
            tasks = list(self.tasks.values())
        return sum(t.buffers.flush_spool() for t in tasks
                   if t.buffers is not None)

    def all_output_consumed(self) -> bool:
        """Drain gate: every COMPLETE task output stream has been acked or
        released by its consumer (tasks still running don't count yet)."""
        with self._lock:
            tasks = list(self.tasks.values())
        return all(t.buffers.all_consumed() for t in tasks
                   if t.buffers is not None)

    def start_reaper(self, interval_s: Optional[float] = None) -> None:
        """Periodic terminal-task eviction (reference PeriodicTaskManager):
        without it a worker that stops receiving create_or_update calls
        never evicts done tasks or frees their buffers."""
        if self._reaper_stop is not None:
            return
        stop = threading.Event()
        self._reaper_stop = stop
        interval = interval_s or self.REAPER_INTERVAL_S

        def loop():
            while not stop.wait(interval):
                self.evict_terminal()
        threading.Thread(target=loop, name="task-reaper",
                         daemon=True).start()

    def stop_reaper(self) -> None:
        if self._reaper_stop is not None:
            self._reaper_stop.set()
            self._reaper_stop = None

    def create_or_update(self, update: TaskUpdateRequest,
                         deadline_ms: Optional[float] = None) -> TaskStatus:
        import re
        with self._lock:
            self._evict_locked()
            task = self.tasks.get(update.task_id)
            if task is None:
                if not update.fragment_b64 and update.sources:
                    # source-refresh for a task we don't know (it already
                    # finished and was evicted): answer with a terminal
                    # stub instead of stranding a PLANNED zombie in the
                    # registry
                    return TaskStatus(update.task_id, CANCELED, 0,
                                      f"{self.base_uri}/v1/task/"
                                      f"{update.task_id}", [])
                self.tasks_created += 1
                if re.search(r"\.r\d+$", update.task_id):
                    # coordinator retry lineage suffix (taskId.rATTEMPT)
                    self.tasks_retried += 1
                task = TpuTask(update.task_id,
                               f"{self.base_uri}/v1/task/{update.task_id}",
                               self.config, events=self.events, manager=self)
                self.tasks[update.task_id] = task
                fresh = True
            else:
                fresh = False
        if deadline_ms is not None:
            task.set_deadline(deadline_ms)
        if fresh and update.fragment_b64:
            task.start(update)
        elif not fresh:
            if update.sources:
                # coordinator task-retry: redirect this consumer's
                # exchange pulls to the replacement attempt's locations
                task.update_remote_sources(update.sources)
            if update.dynamic_filters:
                # coordinator push of collected build-side summaries to
                # a task created before they existed (it may be waiting
                # on them, running unfiltered, or already done)
                task.deliver_dynamic_filters(update.dynamic_filters)
        return task.status()

    def get(self, task_id: str) -> TpuTask:
        task = self.tasks.get(task_id)
        if task is None:
            raise KeyError(task_id)
        return task

    def abort(self, task_id: str,
              message: str = "aborted by chaos hook") -> None:
        """Force-fail one running task (chaos testing: the deterministic
        'kill this task mid-query' lever next to the probabilistic
        injection)."""
        self.get(task_id).fail(message)

    def cancel_all(self) -> None:
        self.stop_reaper()
        for t in list(self.tasks.values()):
            t.cancel()
