"""Worker HTTP server: the task REST protocol + node info + discovery.

The analog of the native worker shell's HTTP surface
(presto_cpp/main/TaskResource.cpp:59-129 registerUris, PrestoServer.cpp:327-390
endpoint setup) on Python's stdlib threading HTTP server:

  POST   /v1/task/{taskId}                      create/update task
  GET    /v1/task/{taskId}                      task info
  GET    /v1/task/{taskId}/status               long-poll task status
  DELETE /v1/task/{taskId}                      cancel
  GET    /v1/task/{taskId}/results/{b}/{token}  pull pages (SerializedPage)
  GET    /v1/task/{taskId}/results/{b}/{token}/acknowledge
  DELETE /v1/task/{taskId}/results/{b}
  GET    /v1/info, /v1/info/state
  PUT    /v1/info/state                         graceful shutdown (drain)
  GET    /v1/status                             node status (NodeStatus.java)
  GET    /v1/metrics                            Prometheus text exposition
  PUT    /v1/announcement/{nodeId}              (coordinator role: discovery)
  GET    /v1/service                            (coordinator role: node list)
"""
from __future__ import annotations

import json
import re
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from ..exec.pipeline import ExecutionConfig, tuned_config
from .protocol import TaskUpdateRequest, make_announcement
from .task import TaskManager

# routes subject to the internal JWT filter (worker-to-worker and
# coordinator-to-worker surfaces; client-facing statement/query/UI
# endpoints authenticate separately in the reference, so enabling the
# internal filter must not lock clients out)
_INTERNAL = {"task_update", "task_status", "task_info", "task_delete",
             "results", "results_ack", "results_destroy", "announce",
             "service", "info_state_put"}

_ROUTES = [
    ("POST", re.compile(r"^/v1/statement$"), "statement_post"),
    ("GET", re.compile(
        r"^/v1/statement/queued/(?P<qid>[^/]+)/(?P<slug>[^/]+)"
        r"/(?P<token>\d+)$"), "statement_queued"),
    ("GET", re.compile(
        r"^/v1/statement/executing/(?P<qid>[^/]+)/(?P<slug>[^/]+)"
        r"/(?P<token>\d+)$"), "statement_executing"),
    ("DELETE", re.compile(
        r"^/v1/statement/(?:queued/|executing/)?(?P<qid>[^/]+)"
        r"/(?P<slug>[^/]+)/\d+$"), "statement_cancel"),
    ("GET", re.compile(r"^/v1/query$"), "query_list"),
    ("GET", re.compile(r"^/v1/query/(?P<qid>[^/]+)$"), "query_info"),
    ("GET", re.compile(r"^/v1/cluster$"), "cluster"),
    ("POST", re.compile(r"^/v1/plan-check$"), "plan_check"),
    ("GET", re.compile(r"^/ui/?$"), "ui"),
    ("GET", re.compile(r"^/v1/info/state$"), "info_state"),
    ("PUT", re.compile(r"^/v1/info/state$"), "info_state_put"),
    ("GET", re.compile(r"^/v1/status$"), "status"),
    ("GET", re.compile(r"^/v1/metrics$"), "metrics"),
    ("GET", re.compile(r"^/v1/info$"), "info"),
    ("GET", re.compile(r"^/v1/service$"), "service"),
    ("PUT", re.compile(r"^/v1/announcement/(?P<node>[^/]+)$"), "announce"),
    ("POST", re.compile(r"^/v1/task/(?P<task>[^/]+)$"), "task_update"),
    ("GET", re.compile(r"^/v1/task/(?P<task>[^/]+)/status$"), "task_status"),
    ("GET", re.compile(
        r"^/v1/task/(?P<task>[^/]+)/results/(?P<buffer>\d+)/(?P<token>\d+)"
        r"/acknowledge$"), "results_ack"),
    ("GET", re.compile(
        r"^/v1/task/(?P<task>[^/]+)/results/(?P<buffer>\d+)/(?P<token>\d+)$"),
     "results"),
    ("DELETE", re.compile(
        r"^/v1/task/(?P<task>[^/]+)/results/(?P<buffer>\d+)$"),
     "results_destroy"),
    ("GET", re.compile(r"^/v1/task/(?P<task>[^/]+)$"), "task_info"),
    ("DELETE", re.compile(r"^/v1/task/(?P<task>[^/]+)$"), "task_delete"),
]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_ref: "WorkerServer" = None  # set by subclassing in WorkerServer

    # quiet request logging
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _dispatch(self, method: str):
        parsed = urlparse(self.path)
        # internal JWT filter (InternalAuthenticationFilter.cpp decision
        # table) runs before routing, like the reference's proxygen
        # filter chain
        for m, rx, name in _ROUTES:
            if m != method:
                continue
            match = rx.match(parsed.path)
            if match:
                if name in _INTERNAL:
                    # internal JWT filter (InternalAuthenticationFilter
                    # decision table) guards the internal surfaces only
                    err = self.server_ref.auth.check_inbound(
                        self.headers.get("X-Presto-Internal-Bearer"))
                    if err is not None:
                        self._send(401, {"error": err})
                        return
                try:
                    getattr(self, "do_" + name)(
                        match.groupdict(), parse_qs(parsed.query))
                except KeyError:
                    self._send(404, {"error": "unknown task"})
                except BufferError as e:
                    self._send(500, {"error": str(e)})
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except Exception:  # noqa: BLE001 — surface, don't drop conn
                    import traceback
                    self._send(500, {"error": traceback.format_exc()})
                return
        self._send(404, {"error": f"no route {method} {parsed.path}"})

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PUT(self):
        self._dispatch("PUT")

    def do_DELETE(self):
        self._dispatch("DELETE")

    # -- helpers ----------------------------------------------------------
    def _send(self, code: int, obj=None, body: bytes = b"",
              headers: Optional[Dict[str, str]] = None):
        if obj is not None:
            body = json.dumps(obj).encode()
        self.send_response(code)
        hdrs = dict(headers or {})
        if "Content-Type" not in hdrs:
            self.send_header("Content-Type",
                             "application/json" if obj is not None
                             else "application/x-presto-pages")
        self.send_header("Content-Length", str(len(body)))
        for k, v in hdrs.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length)

    def _body_json(self):
        """Request body as a JSON value, honoring the binary transport:
        Content-Type application/x-jackson-smile bodies (the
        coordinator's HttpRemoteTask.java:915-931 negotiation) decode
        through worker/smile.py; everything else parses as JSON text."""
        raw = self._body()
        ctype = (self.headers.get("Content-Type") or "").lower()
        from . import smile
        if smile.CONTENT_TYPE in ctype or raw[:3] == b":)\n":
            return smile.decode(raw)
        return json.loads(raw)

    def _accepts_smile(self) -> bool:
        from . import smile
        return smile.CONTENT_TYPE in (self.headers.get("Accept")
                                      or "").lower()

    def _accepts_thrift(self) -> bool:
        from . import thrift
        return thrift.CONTENT_TYPE in (self.headers.get("Accept")
                                       or "").lower()

    def _send_negotiated(self, code: int, obj,
                         thrift_encoder=None) -> None:
        """JSON by default; SMILE or Thrift when the client's Accept asks
        for it (the TaskStatus/TaskInfo hot path the reference serves over
        a negotiated binary transport — HttpRemoteTask.java:915-931 /
        TaskResource.cpp:218-224).  Thrift needs a typed schema, so only
        endpoints passing a thrift_encoder serve it."""
        if thrift_encoder is not None and self._accepts_thrift():
            from . import thrift
            self._send(code, None, thrift_encoder(obj),
                       headers={"Content-Type": thrift.CONTENT_TYPE})
        elif self._accepts_smile():
            from . import smile
            self._send(code, None, smile.encode(obj),
                       headers={"Content-Type": smile.CONTENT_TYPE})
        else:
            self._send(code, obj)

    # -- endpoints --------------------------------------------------------
    def do_info(self, groups, query):
        s = self.server_ref
        self._send(200, {"nodeVersion": {"version": "presto-tpu-0.1"},
                         "environment": s.environment,
                         "coordinator": s.coordinator,
                         "uptime": f"{time.time() - s.started_at:.0f}s"})

    def do_info_state(self, groups, query):
        self._send(200, self.server_ref.state)

    def do_info_state_put(self, groups, query):
        """Graceful shutdown (reference GracefulShutdownHandler /
        presto_cpp PrestoServer.cpp:648-688): stop accepting tasks, drain
        running ones, then report SHUTTING_DOWN until the process exits."""
        body = json.loads(self._body())
        if body != "SHUTTING_DOWN":
            self._send(400, {"error": f"unsupported state {body!r}"})
            return
        self.server_ref.begin_shutdown()
        self._send(200, "SHUTTING_DOWN")

    def do_status(self, groups, query):
        """Node status (reference server/NodeStatus.java: the payload the
        coordinator's memory manager and UI poll)."""
        s = self.server_ref
        c = s.task_manager.counts()
        det = s.failure_detector
        self._send(200, {
            "nodeId": s.node_id,
            "nodeVersion": {"version": "presto-tpu-0.1"},
            "environment": s.environment,
            "coordinator": s.coordinator,
            "state": s.state,
            "uptime": f"{time.time() - s.started_at:.0f}s",
            "tasks": c["by_state"],
            "totalTasks": c["created"],
            "tasksFailed": c["failed"],
            "tasksRetried": c["retried"],
            "heapUsed": c["memory_peak"],   # HBM peak, heap-shaped field
            **({"failureDetector": det.snapshot()} if det else {}),
            **self._serving_status(),
        })

    def _serving_status(self) -> dict:
        """Serving-tier section of /v1/status (coordinator role): plan /
        executable cache counters, prepared-statement registry, per-group
        admission state."""
        s = self.server_ref
        if s.dispatch is None:
            return {}
        from ..serving import (GLOBAL_PLAN_CACHE, PREPARED_REGISTRY,
                               SERVING_METRICS)
        return {"serving": {
            "planCache": GLOBAL_PLAN_CACHE.info(),
            "preparedStatements": PREPARED_REGISTRY.info(),
            "metrics": SERVING_METRICS.snapshot(),
            "resourceGroups": s.dispatch.resource_groups.info(),
        }}

    def do_metrics(self, groups, query):
        """Prometheus text exposition (reference
        presto_cpp/main/runtime-metrics/PrometheusStatsReporter.h:40)."""
        s = self.server_ref
        c = s.task_manager.counts()
        lines = [
            "# TYPE presto_tpu_uptime_seconds gauge",
            f"presto_tpu_uptime_seconds {time.time() - s.started_at:.1f}",
            "# TYPE presto_tpu_tasks_created_total counter",
            f"presto_tpu_tasks_created_total {c['created']}",
            "# TYPE presto_tpu_tasks_failed_total counter",
            f"presto_tpu_tasks_failed_total {c['failed']}",
            "# TYPE presto_tpu_task_retries_total counter",
            f"presto_tpu_task_retries_total {c['retried']}",
            "# TYPE presto_tpu_task_memory_peak_bytes gauge",
            f"presto_tpu_task_memory_peak_bytes {c['memory_peak']}",
            "# TYPE presto_tpu_tasks gauge",
        ]
        for state, n in sorted(c["by_state"].items()):
            lines.append(
                'presto_tpu_tasks{state="%s"} %d' % (state.lower(), n))
        det = s.failure_detector
        if det is not None:
            lines.append("# TYPE presto_tpu_worker_probe_failures gauge")
            lines.append("# TYPE presto_tpu_worker_alive gauge")
            for uri, w in sorted(det.snapshot().items()):
                lines.append(
                    'presto_tpu_worker_probe_failures{worker="%s"} %d'
                    % (uri, w["streak"]))
                lines.append(
                    'presto_tpu_worker_alive{worker="%s",draining="%s"} %d'
                    % (uri, str(w["draining"]).lower(),
                       1 if w["alive"] else 0))
        # durable spooled-exchange section (worker/spooling.py): bytes
        # staged/flushed by fault-tolerant (retry-policy=task) executions
        from .spooling import SPOOL_METRICS
        sp = SPOOL_METRICS.snapshot()
        for k in sorted(sp):
            if k == "staged_bytes":
                lines.append(f"# TYPE presto_tpu_spool_{k} gauge")
                lines.append(f"presto_tpu_spool_{k} {sp[k]}")
            else:
                lines.append(f"# TYPE presto_tpu_spool_{k}_total counter")
                lines.append(f"presto_tpu_spool_{k}_total {sp[k]}")
        # exchange-client section: process-wide (one worker per process in
        # a real deployment; in-process test clusters aggregate, so tests
        # reset() the singleton before asserting)
        from .exchange import EXCHANGE_METRICS
        x = EXCHANGE_METRICS.snapshot()
        lines += [
            "# TYPE presto_tpu_exchange_pages_total counter",
            f"presto_tpu_exchange_pages_total {x['pages']}",
            "# TYPE presto_tpu_exchange_bytes_total counter",
            f"presto_tpu_exchange_bytes_total {x['bytes']}",
            "# TYPE presto_tpu_exchange_uncompressed_bytes_total counter",
            "presto_tpu_exchange_uncompressed_bytes_total "
            f"{x['uncompressed_bytes']}",
            "# TYPE presto_tpu_exchange_responses_total counter",
            f"presto_tpu_exchange_responses_total {x['responses']}",
            "# TYPE presto_tpu_exchange_clients_total counter",
            f"presto_tpu_exchange_clients_total {x['clients']}",
            "# TYPE presto_tpu_exchange_pull_wall_seconds_total counter",
            f"presto_tpu_exchange_pull_wall_seconds_total "
            f"{x['pull_wall_s']:.6f}",
            "# TYPE presto_tpu_exchange_decode_wall_seconds_total counter",
            f"presto_tpu_exchange_decode_wall_seconds_total "
            f"{x['decode_wall_s']:.6f}",
            "# TYPE presto_tpu_exchange_wait_wall_seconds_total counter",
            f"presto_tpu_exchange_wait_wall_seconds_total "
            f"{x['wait_wall_s']:.6f}",
            "# TYPE presto_tpu_exchange_buffered_bytes gauge",
            f"presto_tpu_exchange_buffered_bytes {x['buffered_bytes']}",
            "# TYPE presto_tpu_exchange_buffered_bytes_peak gauge",
            "presto_tpu_exchange_buffered_bytes_peak "
            f"{x['buffered_bytes_peak']}",
        ]
        # per-fabric shuffle section (parallel/fabric.py FABRIC_METRICS):
        # the http/ici comparison surface — bytes moved per fabric, the
        # dispatch/compute/wait walls, and the measured overlap fraction
        from ..parallel.fabric import FABRIC_METRICS
        fm = FABRIC_METRICS.snapshot()
        lines += [
            "# TYPE presto_tpu_exchange_fabric_exchanges_total counter",
            "# TYPE presto_tpu_exchange_fabric_chunks_total counter",
            "# TYPE presto_tpu_exchange_fabric_bytes_total counter",
            "# TYPE presto_tpu_exchange_fabric_host_bytes_total counter",
            "# TYPE presto_tpu_exchange_fabric_exchange_wall_seconds_total"
            " counter",
            "# TYPE presto_tpu_exchange_fabric_compute_wall_seconds_total"
            " counter",
            "# TYPE presto_tpu_exchange_fabric_wait_wall_seconds_total"
            " counter",
            "# TYPE presto_tpu_exchange_fabric_fallbacks_total counter",
            "# TYPE presto_tpu_exchange_fabric_overlap_fraction gauge",
        ]
        for fabric in sorted(fm):
            f = fm[fabric]
            tag = 'fabric="%s"' % fabric
            lines += [
                f"presto_tpu_exchange_fabric_exchanges_total{{{tag}}} "
                f"{f['exchanges']}",
                f"presto_tpu_exchange_fabric_chunks_total{{{tag}}} "
                f"{f['chunks']}",
                f"presto_tpu_exchange_fabric_bytes_total{{{tag}}} "
                f"{f['bytes_moved']}",
                f"presto_tpu_exchange_fabric_host_bytes_total{{{tag}}} "
                f"{f['host_bytes']}",
                f"presto_tpu_exchange_fabric_exchange_wall_seconds_total"
                f"{{{tag}}} {f['exchange_wall_s']:.6f}",
                f"presto_tpu_exchange_fabric_compute_wall_seconds_total"
                f"{{{tag}}} {f['compute_wall_s']:.6f}",
                f"presto_tpu_exchange_fabric_wait_wall_seconds_total"
                f"{{{tag}}} {f['wait_wall_s']:.6f}",
                f"presto_tpu_exchange_fabric_fallbacks_total{{{tag}}} "
                f"{f['fallbacks']}",
                f"presto_tpu_exchange_fabric_overlap_fraction{{{tag}}} "
                f"{f['overlap_fraction']:.6f}",
            ]
        # serving tier: canonical plan/executable cache + prepared
        # statements + per-resource-group admission state
        from ..serving import GLOBAL_PLAN_CACHE, SERVING_METRICS
        sv = SERVING_METRICS.snapshot()
        pc = GLOBAL_PLAN_CACHE.info()
        lines += [
            "# TYPE presto_tpu_serving_plan_cache_hits_total counter",
            f"presto_tpu_serving_plan_cache_hits_total {sv['planCacheHits']}",
            "# TYPE presto_tpu_serving_plan_cache_misses_total counter",
            "presto_tpu_serving_plan_cache_misses_total "
            f"{sv['planCacheMisses']}",
            "# TYPE presto_tpu_serving_plan_cache_evictions_total counter",
            "presto_tpu_serving_plan_cache_evictions_total "
            f"{sv['planCacheEvictions']}",
            "# TYPE presto_tpu_serving_plan_cache_invalidations_total counter",
            "presto_tpu_serving_plan_cache_invalidations_total "
            f"{sv['planCacheInvalidations']}",
            "# TYPE presto_tpu_serving_plan_cache_entries gauge",
            f"presto_tpu_serving_plan_cache_entries {pc['entries']}",
            "# TYPE presto_tpu_serving_executable_builds_total counter",
            f"presto_tpu_serving_executable_builds_total "
            f"{sv['executableBuilds']}",
            "# TYPE presto_tpu_serving_prepared_fast_path_total counter",
            "presto_tpu_serving_prepared_fast_path_total "
            f"{sv['preparedFastPath']}",
            "# TYPE presto_tpu_serving_prepared_replans_total counter",
            f"presto_tpu_serving_prepared_replans_total "
            f"{sv['preparedReplans']}",
            # compiler-pool contention (serving/cache.py checkout)
            "# TYPE presto_tpu_serving_compiler_checkouts_total counter",
            "presto_tpu_serving_compiler_checkouts_total "
            f"{sv['compilerCheckouts']}",
            "# TYPE presto_tpu_serving_compiler_pool_exhausted_total counter",
            "presto_tpu_serving_compiler_pool_exhausted_total "
            f"{sv['compilerPoolExhausted']}",
            "# TYPE presto_tpu_serving_compiler_checkout_wait_seconds_total"
            " counter",
            "presto_tpu_serving_compiler_checkout_wait_seconds_total "
            f"{sv['compilerCheckoutWaitNanos'] / 1e9:.6f}",
            "# TYPE presto_tpu_serving_compiler_checkout_depth_peak gauge",
            "presto_tpu_serving_compiler_checkout_depth_peak "
            f"{sv['compilerCheckoutDepthPeak']}",
            # micro-batched point queries (serving/batching.py)
            "# TYPE presto_tpu_serving_batch_batches_total counter",
            f"presto_tpu_serving_batch_batches_total {sv['servingBatches']}",
            "# TYPE presto_tpu_serving_batch_queries_total counter",
            "presto_tpu_serving_batch_queries_total "
            f"{sv['servingBatchQueries']}",
            "# TYPE presto_tpu_serving_batch_launches_saved_total counter",
            "presto_tpu_serving_batch_launches_saved_total "
            f"{sv['servingBatchLaunchesSaved']}",
            "# TYPE presto_tpu_serving_batch_fallbacks_total counter",
            "presto_tpu_serving_batch_fallbacks_total "
            f"{sv['servingBatchFallbacks']}",
            "# TYPE presto_tpu_serving_batch_demux_seconds_total counter",
            "presto_tpu_serving_batch_demux_seconds_total "
            f"{sv['servingBatchDemuxNanos'] / 1e9:.6f}",
            # fragment-level executable sharing (serving/fragments.py)
            "# TYPE presto_tpu_serving_fragment_jit_hits_total counter",
            "presto_tpu_serving_fragment_jit_hits_total "
            f"{sv['fragmentJitHits']}",
            "# TYPE presto_tpu_serving_fragment_jit_misses_total counter",
            "presto_tpu_serving_fragment_jit_misses_total "
            f"{sv['fragmentJitMisses']}",
        ]
        # HBM-resident columnar storage tier (storage/store.py
        # STORAGE_METRICS), namespaced like the other sections;
        # resident_bytes is the only point-in-time gauge
        from ..storage.store import STORAGE_METRICS
        for k in sorted(STORAGE_METRICS):
            if k == "resident_bytes":
                lines.append(f"# TYPE presto_tpu_storage_{k} gauge")
                lines.append(
                    f"presto_tpu_storage_{k} {STORAGE_METRICS[k]}")
            else:
                lines.append(f"# TYPE presto_tpu_storage_{k}_total counter")
                lines.append(
                    f"presto_tpu_storage_{k}_total {STORAGE_METRICS[k]}")
        # adaptive-execution counters (exec/adaptive.py ADAPTIVE_METRICS):
        # dynamic-filter collection/application/pruning plus the runtime
        # exchange-strategy decisions; all monotonic counters
        from ..exec.adaptive import ADAPTIVE_METRICS
        for k, v in sorted(ADAPTIVE_METRICS.snapshot().items()):
            lines.append(f"# TYPE presto_tpu_adaptive_{k}_total counter")
            lines.append(f"presto_tpu_adaptive_{k}_total {v}")
        # lock-order validation + contention metering (common/locks.py):
        # populated when debug.lock-validation (or a session's
        # lock_validation override) armed the OrderedLock bookkeeping
        from ..common.locks import LOCK_METRICS, validation_enabled
        lk = LOCK_METRICS.snapshot()
        lines += [
            "# TYPE presto_tpu_lock_validation_enabled gauge",
            f"presto_tpu_lock_validation_enabled "
            f"{1 if validation_enabled() else 0}",
            "# TYPE presto_tpu_lock_acquisitions_total counter",
            f"presto_tpu_lock_acquisitions_total {lk['acquisitions']}",
            "# TYPE presto_tpu_lock_contended_total counter",
            f"presto_tpu_lock_contended_total {lk['contended']}",
            "# TYPE presto_tpu_lock_contention_wall_seconds_total counter",
            f"presto_tpu_lock_contention_wall_seconds_total "
            f"{lk['contention_wall_s']}",
            "# TYPE presto_tpu_lock_hold_wall_seconds_total counter",
            f"presto_tpu_lock_hold_wall_seconds_total {lk['hold_wall_s']}",
            "# TYPE presto_tpu_lock_order_violations_total counter",
            f"presto_tpu_lock_order_violations_total {lk['violations']}",
        ]
        # memory arbitration + two-tier spill (exec/memory.py): counters
        # for spilled/unspilled bytes and revocations, gauges for the
        # live reserved/revocable split and the eviction overlap fraction
        from ..exec.memory import MEMORY_METRICS
        mem = MEMORY_METRICS.snapshot()
        for k in sorted(mem):
            if k in ("reserved_bytes", "revocable_bytes",
                     "spill_overlap_fraction"):
                lines.append(f"# TYPE presto_tpu_memory_{k} gauge")
                lines.append(f"presto_tpu_memory_{k} {mem[k]}")
            else:
                lines.append(f"# TYPE presto_tpu_memory_{k}_total counter")
                lines.append(f"presto_tpu_memory_{k}_total {mem[k]}")
        # telemetry export pipeline + history store counters
        if s.telemetry is not None:
            tc = s.telemetry.counters()
            lines += [
                "# TYPE presto_tpu_telemetry_enqueued_total counter",
                f"presto_tpu_telemetry_enqueued_total {tc['enqueued']}",
                "# TYPE presto_tpu_telemetry_exported_total counter",
                f"presto_tpu_telemetry_exported_total {tc['exported']}",
                "# TYPE presto_tpu_telemetry_dropped_total counter",
                "presto_tpu_telemetry_dropped_total "
                f"{tc['dropped'] + tc['dropped_after_retry']}",
                "# TYPE presto_tpu_telemetry_retries_total counter",
                f"presto_tpu_telemetry_retries_total {tc['retries']}",
                "# TYPE presto_tpu_telemetry_queue_depth gauge",
                f"presto_tpu_telemetry_queue_depth {tc['queue_depth']}",
            ]
        if s.history is not None:
            hc = s.history.counters()
            lines += [
                "# TYPE presto_tpu_history_entries gauge",
                f"presto_tpu_history_entries {hc['entries']}",
                "# TYPE presto_tpu_history_recorded_total counter",
                f"presto_tpu_history_recorded_total {hc['recorded']}",
                "# TYPE presto_tpu_history_evicted_total counter",
                f"presto_tpu_history_evicted_total {hc['evicted']}",
            ]
        if s.dispatch is not None:
            lines += [
                "# TYPE presto_tpu_serving_group_running gauge",
                "# TYPE presto_tpu_serving_group_queued gauge",
            ]
            for name, g in sorted(s.dispatch.resource_groups.info().items()):
                if name.startswith("__"):
                    continue
                lines.append('presto_tpu_serving_group_running{group="%s"'
                             ',weight="%g"} %d'
                             % (name, g["weight"], g["running"]))
                lines.append('presto_tpu_serving_group_queued{group="%s"} %d'
                             % (name, g["queued"]))
        self._send(200, None, ("\n".join(lines) + "\n").encode(),
                   headers={"Content-Type":
                            "text/plain; version=0.0.4; charset=utf-8"})

    def do_service(self, groups, query):
        s = self.server_ref
        if s.discovery is None:
            self._send(404, {"error": "not a coordinator"})
            return
        with s.discovery_lock:
            services = [a["services"][0] for a in s.discovery.values()]
        self._send(200, {"services": services})

    def do_announce(self, groups, query):
        s = self.server_ref
        if s.discovery is None:
            self._send(404, {"error": "not a coordinator"})
            return
        body = json.loads(self._body())
        with s.discovery_lock:
            s.discovery[groups["node"]] = body
        self._send(202, {"ok": True})

    # -- statement protocol (coordinator role; QueuedStatementResource /
    # ExecutingStatementResource analog — see worker/statement.py) ---------
    def _dispatch_mgr(self):
        d = self.server_ref.dispatch
        if d is None:
            self._send(404, {"error": "not a coordinator"})
        return d

    def _session_headers(self):
        session = {}
        for raw in self.headers.get_all("X-Presto-Session") or []:
            for pair in raw.split(","):
                if "=" in pair:
                    k, v = pair.split("=", 1)
                    session[k.strip()] = v.strip()
        return session

    def _prepared_headers(self):
        """X-Presto-Prepared-Statement: name=urlencoded-sql, repeatable and
        comma-joinable (reference PrestoHeaders.PRESTO_PREPARED_STATEMENT:
        the client replays its prepared map on every request, keeping the
        server stateless across coordinator restarts)."""
        from urllib.parse import unquote_plus
        prepared = {}
        for raw in self.headers.get_all("X-Presto-Prepared-Statement") or []:
            for pair in raw.split(","):
                if "=" in pair:
                    k, v = pair.split("=", 1)
                    prepared[unquote_plus(k.strip())] = \
                        unquote_plus(v.strip())
        return prepared

    @staticmethod
    def _prepare_headers_out(q) -> Dict[str, str]:
        """Response headers the client folds back into its prepared map
        (reference PRESTO_ADDED_PREPARE / PRESTO_DEALLOCATED_PREPARE)."""
        from urllib.parse import quote_plus
        hdrs = {}
        if getattr(q, "added_prepare", None):
            name, text = q.added_prepare
            hdrs["X-Presto-Added-Prepare"] = \
                f"{quote_plus(name)}={quote_plus(text)}"
        if getattr(q, "deallocated_prepare", None):
            hdrs["X-Presto-Deallocated-Prepare"] = \
                quote_plus(q.deallocated_prepare)
        return hdrs

    def do_statement_post(self, groups, query):
        d = self._dispatch_mgr()
        if d is None:
            return
        sql = self._body().decode()
        q = d.submit(
            sql,
            user=self.headers.get("X-Presto-User", "user"),
            source=self.headers.get("X-Presto-Source", ""),
            session=self._session_headers(),
            catalog=self.headers.get("X-Presto-Catalog", "tpch"),
            schema=self.headers.get("X-Presto-Schema", "sf0.01"),
            prepared=self._prepared_headers(),
            trace_token=self.headers.get("X-Presto-Trace-Token", ""))
        self._send(200, d.queued_response(q, 0, self.server_ref.uri,
                                          wait_s=0.0),
                   headers=self._prepare_headers_out(q))

    def _statement_query(self, d, groups):
        try:
            q = d.get(groups["qid"])
        except KeyError:
            self._send(404, {"error": "unknown query"})
            return None
        if q.slug != groups["slug"]:
            self._send(404, {"error": "bad slug"})
            return None
        return q

    def do_statement_queued(self, groups, query):
        d = self._dispatch_mgr()
        if d is None:
            return
        q = self._statement_query(d, groups)
        if q is not None:
            self._send(200, d.queued_response(
                q, int(groups["token"]), self.server_ref.uri),
                headers=self._prepare_headers_out(q))

    def do_statement_executing(self, groups, query):
        d = self._dispatch_mgr()
        if d is None:
            return
        q = self._statement_query(d, groups)
        if q is not None:
            self._send(200, d.executing_response(
                q, int(groups["token"]), self.server_ref.uri),
                headers=self._prepare_headers_out(q))

    def do_statement_cancel(self, groups, query):
        d = self._dispatch_mgr()
        if d is None:
            return
        # the slug is the per-query secret: without it a query id (guessable,
        # sequential) would suffice to cancel other clients' queries
        q = self._statement_query(d, groups)
        if q is None:
            return
        d.cancel(q.query_id)
        self._send(204)

    def do_query_list(self, groups, query):
        """/v1/query[?state=...]: the live dispatch registry merged with
        the durable history store — after a coordinator restart the live
        registry is empty but ?state=FINISHED still lists what the spool
        reloaded (reference QueryResource list + system.runtime.queries
        over completed queries)."""
        d = self._dispatch_mgr()
        if d is None:
            return
        state = (query.get("state", [None])[0] or "").upper() or None
        live = d.list_queries()
        out = [q for q in live if state is None or q["state"] == state]
        hist = self.server_ref.history
        if hist is not None:
            live_ids = {q["queryId"] for q in live}
            for rec in hist.list(state=state):
                if rec["queryId"] in live_ids:
                    continue  # live registry wins (same terminal record)
                out.append({
                    "queryId": rec["queryId"],
                    "state": rec.get("state", "UNKNOWN"),
                    "query": rec.get("query", ""),
                    "user": rec.get("user", ""),
                    "resourceGroup": rec.get("resourceGroup", ""),
                    **({"errorMessage": rec["errorMessage"]}
                       if rec.get("errorMessage") else {})})
        self._send(200, out)

    def do_cluster(self, groups, query):
        """/v1/cluster (reference ClusterStatsResource): query counts by
        lifecycle bucket, task/worker totals, reserved memory from the
        admission gate, and per-fabric shuffle byte rates.  Terminal
        counts take the durable history store when it is ahead of the
        (restart-lossy, eviction-bounded) live registry."""
        s = self.server_ref
        d = s.dispatch
        if d is None:
            self._send(404, {"error": "not a coordinator"})
            return
        by_state: Dict[str, int] = {}
        for q in d.list_queries():
            by_state[q["state"]] = by_state.get(q["state"], 0) + 1
        hist_counts = s.history.counts_by_state() if s.history else {}
        queued = by_state.get("QUEUED", 0)
        adm = d.resource_groups.info().get("__admission", {})
        headroom = adm.get("memoryHeadroomBytes")
        # the arbitrated pool's LIVE reserved+revocable accounting when it
        # exceeds the admission-time estimates (same max the gate applies)
        reserved = max(adm.get("memoryAdmittedBytes", 0),
                       adm.get("memoryReservedBytes", 0)
                       + adm.get("memoryRevocableBytes", 0))
        # memory-gated admission parks queries in QUEUED; when the pool
        # is exhausted those queued queries are blocked-on-memory
        blocked = queued if (headroom is not None and queued
                             and reserved >= headroom) else 0
        c = s.task_manager.counts()
        from ..parallel.fabric import FABRIC_METRICS
        self._send(200, {
            "runningQueries": by_state.get("RUNNING", 0),
            "queuedQueries": queued,
            "blockedQueries": blocked,
            "finishedQueries": max(by_state.get("FINISHED", 0),
                                   hist_counts.get("FINISHED", 0)),
            "failedQueries": max(by_state.get("FAILED", 0),
                                 hist_counts.get("FAILED", 0)),
            "canceledQueries": max(by_state.get("CANCELED", 0),
                                   hist_counts.get("CANCELED", 0)),
            "activeWorkers": len(s.worker_uris()),
            "runningTasks": c["by_state"].get("RUNNING", 0),
            "totalTasks": c["created"],
            "reservedMemoryBytes": reserved,
            "revocableMemoryBytes": adm.get("memoryRevocableBytes", 0),
            **({"memoryHeadroomBytes": headroom}
               if headroom is not None else {}),
            "fabricByteRates": FABRIC_METRICS.byte_rates(),
            **({"workers": s.failure_detector.snapshot()}
               if s.failure_detector else {}),
            "historyEntries": len(s.history) if s.history else 0,
            **({"telemetry": s.telemetry.counters()}
               if s.telemetry else {}),
        })

    @staticmethod
    def _process_metrics() -> dict:
        """Process-wide metric registries, namespaced consistently with
        the /v1/metrics exposition sections — included in QueryInfo so a
        single snapshot carries both query- and process-scoped state."""
        from ..exec.adaptive import ADAPTIVE_METRICS
        from ..exec.kernels.scan_kernel import KERNEL_METRICS
        from ..exec.memory import MEMORY_METRICS
        from ..parallel.fabric import FABRIC_METRICS
        from ..serving import SERVING_METRICS
        from ..storage.store import STORAGE_METRICS
        from .exchange import EXCHANGE_METRICS
        return {"exchange": EXCHANGE_METRICS.snapshot(),
                "fabric": FABRIC_METRICS.snapshot(),
                "serving": SERVING_METRICS.snapshot(),
                "storage": dict(STORAGE_METRICS),
                "kernel": KERNEL_METRICS.snapshot(),
                "memory": MEMORY_METRICS.snapshot(),
                "adaptive": ADAPTIVE_METRICS.snapshot()}

    def do_query_info(self, groups, query):
        d = self._dispatch_mgr()
        if d is None:
            return
        try:
            q = d.get(groups["qid"])
        except KeyError:
            # fall back to the durable history record: terminal queries
            # outlive the in-memory registry (eviction, restarts)
            hist = self.server_ref.history
            rec = hist.get(groups["qid"]) if hist is not None else None
            if rec is not None:
                self._send(200, {**rec, "source": "history"})
                return
            self._send(404, {"error": "unknown query"})
            return
        # stage/task/operator drill-down: the terminal snapshot captured
        # by the executor, else a LIVE snapshot from the running
        # distributed execution matched by trace token
        extra = q.query_info_extra
        if extra is None and not q.done.is_set():
            extra = self.server_ref.live_query_info(q.trace_token)
        self._send(200, {
            "queryId": q.query_id, "query": q.sql, "state": q.state,
            "traceToken": q.trace_token,
            "queryStats": q.stats(), "session": q.session,
            "resourceGroupId": [q.resource_group],
            "peakMemoryBytes": q.peak_memory_bytes,
            **({"profileTraceDir": q.profile_trace_dir}
               if q.profile_trace_dir else {}),
            **({"runtimeStats": q.runtime_stats}
               if q.runtime_stats else {}),
            **({"failureInfo": {"message": q.error}} if q.error else {}),
            **({"stages": extra.get("stages"),
                "operatorStats": extra.get("operatorStats")}
               if extra else {}),
            "processMetrics": self._process_metrics(),
            "resourceGroups": d.resource_groups.info()})

    def do_plan_check(self, groups, query):
        """Sidecar plan validation (presto-native-sidecar-plugin
        nativechecker analog): can the native planner handle this SQL?
        Consumed by the plan-check router scheduler."""
        from .router import plan_checks
        sql = self._body().decode()
        err = plan_checks(sql,
                          schema=self.headers.get("X-Presto-Schema",
                                                  "sf0.01"),
                          catalog=self.headers.get("X-Presto-Catalog",
                                                   "tpch"))
        self._send(200, {"ok": err is None,
                         **({"error": err} if err else {})})

    def do_ui(self, groups, query):
        """Minimal cluster console (the presto-ui query-list analog)."""
        from html import escape
        from urllib.parse import quote
        s = self.server_ref
        rows = []
        if s.dispatch is not None:
            for q in reversed(s.dispatch.list_queries()):
                state = q["state"]
                color = {"FINISHED": "#2d7", "FAILED": "#d55",
                         "RUNNING": "#27d", "QUEUED": "#fa0"}.get(state,
                                                                  "#999")
                sql = (q["query"][:120] + "…") if len(q["query"]) > 120 \
                    else q["query"]
                # query text and ids are client-controlled: escape
                rows.append(
                    f"<tr><td><a href='/v1/query/"
                    f"{quote(q['queryId'])}'>"
                    f"{escape(q['queryId'])}</a></td>"
                    f"<td style='color:{color}'>{escape(state)}</td>"
                    f"<td>{escape(q['resourceGroup'])}</td>"
                    f"<td><code>{escape(sql)}</code></td></tr>")
        # worker URIs arrive via the unauthenticated announcement endpoint:
        # escape like every other client-controlled field
        workers = "".join(f"<li>{escape(u)}</li>" for u in s.worker_uris())
        html = f"""<!doctype html><html><head><title>presto-tpu</title>
<style>body{{font-family:sans-serif;margin:2em}}table{{border-collapse:
collapse}}td,th{{border:1px solid #ccc;padding:4px 8px;text-align:left}}
</style></head><body>
<h1>presto-tpu {'coordinator' if s.coordinator else 'worker'}
 <small>{s.node_id}</small></h1>
<p>state: {s.state} &middot; uptime: {time.time() - s.started_at:.0f}s</p>
<h2>workers</h2><ul>{workers or '<li>(none announced)</li>'}</ul>
<h2>queries</h2>
<table><tr><th>query</th><th>state</th><th>group</th><th>sql</th></tr>
{''.join(rows) or '<tr><td colspan=4>(none)</td></tr>'}</table>
</body></html>"""
        self._send(200, None, html.encode(),
                   headers={"Content-Type": "text/html; charset=utf-8"})

    def do_task_update(self, groups, query):
        if self.server_ref.state != "ACTIVE":
            # draining node refuses new work; the coordinator reroutes
            self._send(503, {"error": "node is shutting down"})
            return
        body = self._body_json()
        if "outputIds" in body or "extraCredentials" in body:
            # reference-shaped request (HttpRemoteTask.java:883-936)
            from .protocol import from_reference_update
            update = from_reference_update(groups["task"], body)
        else:
            update = TaskUpdateRequest.from_dict(body)
        # X-Presto-Task-Deadline carries the query's REMAINING execution
        # budget in ms (no cross-node clock sync needed): the TaskManager
        # reaper and the pipeline drain loop both enforce it
        deadline_ms = None
        raw_deadline = self.headers.get("X-Presto-Task-Deadline")
        if raw_deadline:
            try:
                deadline_ms = float(raw_deadline)
            except ValueError:
                deadline_ms = None
        status = self.server_ref.task_manager.create_or_update(
            update, deadline_ms=deadline_ms)
        from .thrift import task_status_to_thrift
        self._send_negotiated(200, status.to_dict(),
                              thrift_encoder=task_status_to_thrift)

    def do_task_status(self, groups, query):
        task = self.server_ref.task_manager.get(groups["task"])
        current = self.headers.get("X-Presto-Current-State") or \
            (query.get("currentState", [None])[0])
        max_wait = float(query.get("maxWaitMs", ["1000"])[0]) / 1000.0
        status = task.wait_status(current, max_wait)
        from .thrift import task_status_to_thrift
        self._send_negotiated(200, status.to_dict(),
                              thrift_encoder=task_status_to_thrift)

    def do_task_info(self, groups, query):
        task = self.server_ref.task_manager.get(groups["task"])
        self._send_negotiated(200, task.info())

    def do_task_delete(self, groups, query):
        task = self.server_ref.task_manager.get(groups["task"])
        task.cancel()
        from .thrift import task_status_to_thrift
        self._send_negotiated(200, task.status().to_dict(),
                              thrift_encoder=task_status_to_thrift)

    def do_results(self, groups, query):
        task = self.server_ref.task_manager.get(groups["task"])
        max_wait = float(query.get("maxWaitMs", ["1000"])[0]) / 1000.0
        # X-Presto-Max-Size (PrestoHeaders.java:57): the consumer caps how
        # many bytes one response may carry; absent means uncapped
        max_size = self.headers.get("X-Presto-Max-Size")
        max_bytes = None
        if max_size:
            from .protocol import parse_data_size
            try:
                max_bytes = parse_data_size(max_size)
            except (ValueError, TypeError):
                max_bytes = None
        pages, next_token, complete = task.buffers.get(
            int(groups["buffer"]), int(groups["token"]), max_wait,
            max_bytes=max_bytes)
        body = b"".join(pages)
        # reference header names (PrestoHeaders.java:51-52 /
        # presto_protocol_core.cpp:82-84): the Java ExchangeClient reads
        # X-Presto-Page-Sequence-Id / X-Presto-Page-End-Sequence-Id.  The
        # pre-round-4 repo names are kept as aliases for older peers.
        self._send(200, None, body, headers={
            "X-Presto-Page-Sequence-Id": groups["token"],
            "X-Presto-Page-End-Sequence-Id": str(next_token),
            "X-Presto-Page-Token": groups["token"],
            "X-Presto-Page-Next-Token": str(next_token),
            "X-Presto-Buffer-Complete": "true" if complete else "false",
            "X-Presto-Task-Instance-Id": task.task_id,
        })

    def do_results_ack(self, groups, query):
        task = self.server_ref.task_manager.get(groups["task"])
        task.buffers.acknowledge(int(groups["buffer"]), int(groups["token"]))
        self._send(200, {"acknowledged": True})

    def do_results_destroy(self, groups, query):
        task = self.server_ref.task_manager.get(groups["task"])
        task.buffers.destroy(int(groups["buffer"]))
        self._send(200, {"destroyed": True})


class _QuerySpanListener:
    """EventListener bridging terminal queries to the telemetry exporter
    (a plain class with the listener surface: the manager dispatches by
    method name)."""

    def __init__(self, server: "WorkerServer"):
        self._server = server

    def query_created(self, event) -> None:
        pass

    def task_completed(self, event) -> None:
        pass

    def query_completed(self, event) -> None:
        self._server._export_query_spans(event)


class WorkerServer:
    """One worker (or coordinator) process node.  With coordinator=True the
    server also hosts the embedded discovery service, like the reference
    coordinator embeds Airlift discovery (PrestoServer.java:122)."""

    # every not-yet-closed server in this process (weak: a dropped server
    # must not be kept alive by the registry)
    _live: "weakref.WeakSet" = weakref.WeakSet()

    def __init__(self, port: int = 0, node_id: Optional[str] = None,
                 coordinator: bool = False,
                 discovery_uri: Optional[str] = None,
                 environment: str = "test",
                 config: Optional[ExecutionConfig] = None,
                 announce_interval_s: float = 1.0,
                 resource_groups=None, events=None,
                 jwt_enabled: bool = False, jwt_secret: str = "",
                 jwt_expiration_s: int = 300,
                 https_cert_path: Optional[str] = None,
                 https_key_path: Optional[str] = None,
                 internal_ca_path: Optional[str] = None,
                 plan_cache_entries: Optional[int] = None,
                 total_concurrency: Optional[int] = None,
                 admission_headroom_fraction: Optional[float] = None,
                 admission_memory_pool=None,
                 batch_window_ms: float = 3.0,
                 max_batch_size: int = 16,
                 compilation_cache_dir: Optional[str] = None,
                 plan_cache_path: Optional[str] = None,
                 telemetry_sink=None, telemetry_path: str = "",
                 telemetry_endpoint: str = "",
                 telemetry_flush_interval_s: float = 0.2,
                 telemetry_queue_bound: int = 256,
                 telemetry_metrics_interval_s: float = 0.0,
                 history_path: Optional[str] = None,
                 history_max_count: int = 200,
                 history_max_age_s: Optional[float] = None):
        self.environment = environment
        self.coordinator = coordinator
        self.state = "ACTIVE"            # ACTIVE | SHUTTING_DOWN
        self.discovery: Optional[Dict[str, dict]] = {} if coordinator else None
        self.discovery_lock = threading.Lock()
        self.started_at = time.time()
        self.exec_config = config or tuned_config()
        if getattr(self.exec_config, "lock_validation", False):
            # debug.lock-validation=on arms the worker-wide base flag;
            # per-query session overrides compose scopes on top of it
            from ..common.locks import set_validation
            set_validation(True)

        handler = type("Handler", (_Handler,), {"server_ref": self})
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_port
        scheme = "http"
        if https_cert_path:
            # TLS listener (reference https-cert-path / https-key-path,
            # Configs.h:211-212; proxygen's TLS endpoint in the native
            # worker).  One combined PEM is accepted when key_path is
            # omitted, like the reference's kHttpsClientCertAndKeyPath.
            # The handshake is deferred to the per-connection handler
            # thread (do_handshake_on_connect=False + socket timeout):
            # a peer that never sends its ClientHello must not stall the
            # accept loop for everyone else.
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(https_cert_path,
                                https_key_path or None)
            base_get_request = self.httpd.get_request

            def tls_get_request():
                sock, addr = base_get_request()
                sock.settimeout(30)
                return ctx.wrap_socket(sock, server_side=True,
                                       do_handshake_on_connect=False), addr
            self.httpd.get_request = tls_get_request
            scheme = "https"
        self.scheme = scheme
        self.uri = f"{scheme}://127.0.0.1:{self.port}"
        self.node_id = node_id or f"node-{self.port}"
        from .auth import InternalAuth, set_process_auth
        self.auth = InternalAuth(jwt_enabled, jwt_secret, self.node_id,
                                 jwt_expiration_s)
        if jwt_enabled:
            set_process_auth(self.auth)
        if internal_ca_path:
            from .auth import set_internal_ca
            set_internal_ca(internal_ca_path)
        self.task_manager = TaskManager(self.uri, config, events=events)
        # terminal-task eviction must not depend on new tasks arriving
        # (reference PeriodicTaskManager)
        self.task_manager.start_reaper()
        # coordinator role: liveness probing over discovered workers,
        # attached lazily when the first distributed statement runs
        self.failure_detector = None

        # persistent executable cache (serving/persist.py): point JAX's
        # compilation cache at disk BEFORE anything compiles, so every
        # jitted step this process builds is reloadable after a restart
        if compilation_cache_dir:
            from ..serving import enable_compilation_cache
            enable_compilation_cache(compilation_cache_dir)

        # coordinator role: client statement intake (worker/statement.py)
        self.dispatch = None
        self._runner_cache: Dict = {}
        self._runner_lock = threading.Lock()
        self._batcher = None
        self._sidecar = None
        if coordinator:
            from .statement import DispatchManager, ResourceGroupManager
            if plan_cache_entries is not None:
                from ..serving import GLOBAL_PLAN_CACHE
                GLOBAL_PLAN_CACHE.set_max_entries(plan_cache_entries)
            # micro-batched point queries: concurrent same-template
            # EXECUTEs collapse into one device launch (max_batch_size=1
            # disables the window entirely)
            from ..serving import MicroBatcher
            self._batcher = MicroBatcher(window_ms=batch_window_ms,
                                         max_batch=max_batch_size)
            if plan_cache_path:
                from ..serving import PlanCacheSidecar
                self._sidecar = PlanCacheSidecar(plan_cache_path)
            if resource_groups is None and (
                    total_concurrency is not None
                    or admission_memory_pool is not None):
                resource_groups = ResourceGroupManager(
                    total_concurrency=total_concurrency,
                    memory_pool=admission_memory_pool,
                    **({"headroom_fraction": admission_headroom_fraction}
                       if admission_headroom_fraction is not None else {}))
            self.dispatch = DispatchManager(self._execute_statement,
                                            resource_groups, events=events)

        # telemetry export pipeline (presto_tpu/telemetry/): bounded-queue
        # OTLP span/metric export through the configured sink.  The first
        # server to configure telemetry owns the process exporter slot that
        # deep execution layers (tasks, coordinator executions) publish
        # through; test clusters with several in-process servers share it.
        self.telemetry = None
        self._owns_process_exporter = False
        from ..telemetry import (TelemetryExporter, TelemetrySink,
                                 get_process_exporter, make_sink,
                                 set_process_exporter)
        sink = (telemetry_sink if isinstance(telemetry_sink, TelemetrySink)
                else make_sink(telemetry_sink or "none",
                               endpoint=telemetry_endpoint,
                               path=telemetry_path))
        if sink is not None:
            self.telemetry = TelemetryExporter(
                sink, queue_bound=telemetry_queue_bound,
                flush_interval_s=telemetry_flush_interval_s,
                metrics_interval_s=telemetry_metrics_interval_s,
                resource={"service.name": "presto-tpu",
                          "service.instance.id": self.node_id,
                          "deployment.environment": environment})
            if get_process_exporter() is None:
                set_process_exporter(self.telemetry)
                self._owns_process_exporter = True

        # query history service (coordinator role): terminal QueryInfo
        # records, retention-bounded, reloaded from the JSONL spool across
        # restarts; fed by QueryCompletedEvent through the dispatch event
        # manager so failures isolate like any other listener
        self.history = None
        self._history_listener = None
        if coordinator:
            from ..telemetry import HistoryEventListener, QueryHistoryStore
            self.history = QueryHistoryStore(
                history_path or None, max_count=history_max_count,
                max_age_s=history_max_age_s)
            self._history_listener = HistoryEventListener(
                self.history, extra_fields=self._history_extra_fields)
            self.dispatch.events.register(self._history_listener)
            # admission-time history sizing (adaptive.history-sizing):
            # the dispatch manager consults the same store for a repeat
            # query's observed peak memory
            self.dispatch.history = self.history
            # coordinator slice of the distributed trace: query +
            # per-stage fragment spans exported at terminal state (worker
            # processes export their own task/operator spans under the
            # same trace-token-derived trace id)
            self._span_listener = _QuerySpanListener(self)
            self.dispatch.events.register(self._span_listener)

        # system runtime tables (reference system connector /
        # presto_cpp SystemConnector): SQL-queryable server state.  Only
        # the coordinator registers (workers have no dispatch registry,
        # and the global catalog must not be hijacked by the last-built
        # worker in multi-server tests).
        self._registered_system = False
        if coordinator:
            from ..connectors import catalog as _catalog
            from ..connectors.system_tables import SystemTablesConnector
            _catalog.register_connector("system",
                                        SystemTablesConnector(self))
            self._registered_system = True

        # warm restart: replay recorded exemplars BEFORE the listener
        # opens — the recompile cost lands at boot, not on the first
        # client (and mostly loads from the persistent compilation cache)
        if self._sidecar is not None:
            self._warm_start_replay()

        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name=f"http-{self.port}",
            daemon=True)
        self._serve_thread.start()

        self._announcer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        WorkerServer._live.add(self)
        if discovery_uri:
            self._announcer = threading.Thread(
                target=self._announce_loop,
                args=(discovery_uri, announce_interval_s),
                name=f"announcer-{self.node_id}", daemon=True)
            self._announcer.start()

    def _announce_loop(self, discovery_uri: str, interval_s: float) -> None:
        """PUT /v1/announcement/{nodeId} periodically (reference
        presto_cpp/main/Announcer.cpp:59-74)."""
        import urllib.request
        body = json.dumps(make_announcement(
            self.node_id, self.uri, self.environment)).encode()
        url = f"{discovery_uri}/v1/announcement/{self.node_id}"
        while not self._stop.is_set():
            try:
                from .auth import outbound_headers, urlopen_internal
                req = urllib.request.Request(
                    url, data=body, method="PUT",
                    headers={"Content-Type": "application/json",
                             **outbound_headers()})
                urlopen_internal(req, timeout=5).close()
            except OSError:
                pass  # coordinator not up yet; retry next tick
            self._stop.wait(interval_s)

    def worker_uris(self) -> list:
        """Discovered worker URIs (coordinator role)."""
        with self.discovery_lock:
            return [a["services"][0]["properties"]["http"]
                    for a in (self.discovery or {}).values()]

    def _runner_for(self, schema, catalog, session):
        """Get-or-build the cached query runner for one (workers, schema,
        catalog, session) combination.  Runners are cached so repeated
        statements reuse the plan cache and warm jitted pipelines; DDL
        invalidates the cache (it may change any catalog's tables)."""
        from .protocol import apply_session_properties
        cfg = apply_session_properties(self.exec_config, session)
        uris = tuple(sorted(u for u in self.worker_uris() if u != self.uri))
        key = (uris, schema, catalog, tuple(sorted(session.items())))
        with self._runner_lock:
            runner = self._runner_cache.get(key)
            if runner is None:
                if uris:
                    from .coordinator import (HeartbeatFailureDetector,
                                              HttpQueryRunner)
                    det = HeartbeatFailureDetector(
                        list(uris),
                        heartbeat_timeout_s=(
                            cfg.failure_detector_heartbeat_timeout_s
                            or None))
                    runner = HttpQueryRunner(list(uris), schema=schema,
                                             config=cfg, session=session,
                                             failure_detector=det,
                                             catalog=catalog)
                    self.failure_detector = det
                else:
                    from ..exec.runner import LocalQueryRunner
                    runner = LocalQueryRunner(schema, config=cfg,
                                              catalog=catalog)
                self._runner_cache[key] = runner
                while len(self._runner_cache) > 16:
                    old = self._runner_cache.pop(
                        next(iter(self._runner_cache)))
                    self._close_runner(old)
        return runner, uris

    @staticmethod
    def _batch_template_text(runner, q) -> Optional[str]:
        """The prepared-template text behind an EXECUTE..USING statement,
        or None when the statement is not batchable traffic.  The text is
        the micro-batch group key: requests resolve to the same key only
        when a single canonical plan serves them."""
        m = re.match(r"\s*execute\s+([A-Za-z_][A-Za-z0-9_]*)\s+using\b",
                     q.sql, re.IGNORECASE)
        if m is None:
            return None
        name = m.group(1)
        return ((q.prepared or {}).get(name)
                or getattr(runner, "_prepared", {}).get(name))

    def _execute_statement(self, q):
        """DispatchManager executor: run a managed query over the discovered
        workers (HttpQueryRunner) or in-process when none are announced —
        the same fallback a single-node reference deployment makes
        (coordinator with node-scheduler.include-coordinator=true).

        Single-node EXECUTE..USING traffic first passes the micro-batcher:
        requests against the same template that land inside one batching
        window run as ONE device launch (exec/runner.py
        execute_prepared_batch); everything else — and every lane the
        batched drain declines — takes `_run_single`, the unchanged
        sequential path."""
        runner, uris = self._runner_for(q.schema, q.catalog, q.session)
        result = None
        served = False
        if (not uris and self._batcher is not None
                and self._batcher.enabled
                and hasattr(runner, "execute_prepared_batch")):
            text = self._batch_template_text(runner, q)
            if text is not None:
                result = self._batcher.run(
                    (id(runner), text), q,
                    lambda items: runner.execute_prepared_batch(
                        [it.sql for it in items],
                        prepared=[it.prepared for it in items]),
                    lambda item: self._run_single(runner, uris, item))
                served = True
        if not served:
            result = self._run_single(runner, uris, q)
        if self._sidecar is not None:
            self._record_sidecar(q)
        return result

    def _record_sidecar(self, q) -> None:
        """Persist a warm-start exemplar for a successfully served
        statement (PlanCacheSidecar dedups per template)."""
        head = q.sql.lstrip().split(None, 1)
        word = head[0].lower() if head else ""
        if word not in ("select", "with", "prepare", "execute"):
            return
        try:
            self._sidecar.record(q.sql, q.prepared, q.catalog, q.schema,
                                 q.session)
        except Exception:   # noqa: BLE001 — persistence is advisory
            pass

    def _warm_start_replay(self) -> int:
        """Replay the sidecar's recorded exemplars through the same runner
        path that serves traffic: each replay re-registers its prepared
        statement, re-records the skip-parse fast path, and re-inserts the
        canonical PlanCache entry — whose jitted steps load from the
        persistent compilation cache instead of recompiling.  Runs before
        the HTTP listener starts, so the first client request after a
        restart is already a warm hit."""
        n = 0
        for rec in self._sidecar.load():
            try:
                runner, uris = self._runner_for(
                    rec["schema"], rec["catalog"],
                    rec.get("session") or {})
                if uris:
                    continue    # warm start serves the single-node plane
                runner.execute(rec["sql"],
                               prepared=rec.get("prepared") or {})
                n += 1
            except Exception:   # noqa: BLE001 — a stale exemplar (dropped
                continue        # table, bad session) must not block boot
        return n

    def _run_single(self, runner, uris, q):
        if not uris and hasattr(runner, "execute_streaming"):
            # single-node SELECTs stream chunk-by-chunk: the coordinator
            # never materializes the full result (reference Query.java
            # pumps the root-stage buffer)
            sr = runner.execute_streaming(q.sql, prepared=q.prepared)
            if sr is not None:
                from .statement import StreamingResult, _json_value
                columns, row_iter, stats = sr
                return StreamingResult(
                    columns,
                    ([_json_value(v) for v in row] for row in row_iter),
                    stats)
        if not uris:
            result = runner.execute(q.sql, prepared=q.prepared)
            if q.sql.lstrip().lower().startswith("explain") \
                    and getattr(runner, "last_operator_stats", None):
                # EXPLAIN ANALYZE side channel: the per-node operator
                # stats of THIS analyzed run (the runner attribute is
                # sticky, so gate on the statement being an EXPLAIN)
                q.query_info_extra = {
                    "operatorStats": runner.last_operator_stats}
        else:
            result = runner.execute(q.sql, trace_token=q.trace_token)
            exe = getattr(runner, "last_execution", None)
            if exe is not None and getattr(exe, "trace_token",
                                           "") == q.trace_token:
                try:
                    # terminal snapshot for the query-history ring: tasks
                    # stay queryable on workers until TTL eviction
                    q.query_info_extra = exe.query_info_snapshot()
                except Exception:  # noqa: BLE001 — snapshot best-effort
                    pass
        if q.sql.lstrip()[:6].lower() in ("create", "insert") \
                or q.sql.lstrip()[:4].lower() == "drop":
            with self._runner_lock:
                for r in self._runner_cache.values():
                    self._close_runner(r)
                self._runner_cache.clear()
            if self._sidecar is not None:
                # a replayed exemplar would re-plan against changed tables
                self._sidecar.clear()
        return result

    def _history_extra_fields(self, event) -> dict:
        """Enrich the history record with state the completed event does
        not carry: the profiler capture dir and the per-stage breakdown
        summary of a distributed run."""
        try:
            q = self.dispatch.get(event.query_id)
        except KeyError:
            return {}
        extra = {}
        if q.profile_trace_dir:
            extra["profileTraceDir"] = q.profile_trace_dir
        stages = (q.query_info_extra or {}).get("stages")
        if stages:
            extra["nStages"] = len(stages)
            extra["nTasks"] = sum(st.get("nTasks", 0) for st in stages)
        return extra

    def _export_query_spans(self, event) -> None:
        """Coordinator-side slice of the distributed trace for one
        terminal query: a `query` root span plus a `fragment {fid}` span
        per stage, exported under the trace id derived from the query's
        trace token.  Worker processes export their own `task ...` /
        `operator ...` spans with `fragment {fid}` parents, so the
        deterministic (token, name) span ids stitch both slices into ONE
        OTLP trace with no id handshake."""
        exp = self.telemetry
        if exp is None:
            from ..telemetry import get_process_exporter
            exp = get_process_exporter()
        if exp is None or not event.trace_token:
            return
        from ..utils.runtime_stats import Span
        try:
            q = self.dispatch.get(event.query_id)
        except KeyError:
            q = None
        started = (q.started_at if q is not None and q.started_at
                   else event.create_time)
        spans = [Span("query", "", start=started, end=event.end_time,
                      attributes={"queryId": event.query_id,
                                  "sql": event.sql, "user": event.user,
                                  "state": event.state,
                                  "rows": event.rows})]
        extra = q.query_info_extra if q is not None else None
        for st in (extra or {}).get("stages") or []:
            fid = st.get("fragmentId", st.get("stageId", 0))
            wall = float(st.get("wallTimeInNanos", 0) or 0) / 1e9
            spans.append(Span(
                f"fragment {fid}", "query", start=started,
                end=(min(event.end_time, started + wall) if wall
                     else event.end_time),
                attributes={"nTasks": st.get("nTasks", 0),
                            "partitioning": st.get("partitioning", "")}))
        exp.export_spans(event.trace_token, spans,
                         resource={"presto.role": "coordinator",
                                   "presto.node_id": self.node_id})

    def live_query_info(self, trace_token: str) -> Optional[dict]:
        """Live stage/task/operator snapshot for a RUNNING distributed
        query, matched to its execution by trace token (the runner cache
        is shared across queries, so the token is the join key)."""
        if not trace_token:
            return None
        with self._runner_lock:
            runners = list(self._runner_cache.values())
        for r in runners:
            exe = getattr(r, "last_execution", None)
            if exe is not None and getattr(exe, "trace_token",
                                           "") == trace_token:
                try:
                    return exe.query_info_snapshot()
                except Exception:  # noqa: BLE001 — snapshot best-effort
                    return None
        return None

    @staticmethod
    def _close_runner(runner) -> None:
        det = getattr(runner, "failure_detector", None)
        if det is not None:
            det.close()

    def _unregister_system(self) -> None:
        if getattr(self, "_registered_system", False):
            from ..connectors import catalog as _catalog
            if _catalog._CONNECTORS.get("system") is not None and \
                    getattr(_catalog._CONNECTORS["system"], "server",
                            None) is self:
                _catalog.unregister_connector("system")
            self._registered_system = False

    def shutdown(self) -> None:
        """Stop serving (alias of close(): one shutdown path releases
        the process-wide auth context, the listener socket, and running
        tasks alike)."""
        self.close()

    def begin_shutdown(self) -> None:
        """Refuse new tasks, wait for running ones to drain, then stop the
        server (reference GracefulShutdownHandler / native
        PrestoServer.cpp:648-688)."""
        with self.discovery_lock:
            if self.state != "ACTIVE":
                return
            self.state = "SHUTTING_DOWN"

        def drain():
            # grace period first, so the coordinator observes the drain
            # state before the endpoints disappear (the reference waits
            # 2x the announcement interval for the same reason)
            time.sleep(2.0)
            deadline = time.time() + 30.0
            while time.time() < deadline:
                counts = self.task_manager.counts()["by_state"]
                if not any(s in ("RUNNING", "PLANNED") for s in counts):
                    break
                time.sleep(0.1)
            # spooled output (retry-policy=task) outlives task completion:
            # make it durable, then keep serving /results until every
            # consumer has drained it (final DELETE or acked-to-end), so
            # in-flight queries finish with zero failures before we exit
            try:
                self.task_manager.flush_spools()
            except Exception:  # noqa: BLE001 — drain is best-effort
                pass
            while time.time() < deadline:
                if self.task_manager.all_output_consumed():
                    break
                time.sleep(0.1)
            self.close()
        threading.Thread(target=drain, name="drain", daemon=True).start()

    def close(self) -> None:
        from .auth import clear_process_auth
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        try:
            clear_process_auth(self.auth)
            self._unregister_system()
            with self._runner_lock:
                for r in self._runner_cache.values():
                    self._close_runner(r)
                self._runner_cache.clear()
            self.task_manager.cancel_all()
            if self.dispatch is not None:
                if self._history_listener is not None:
                    self.dispatch.events.unregister(self._history_listener)
                span_listener = getattr(self, "_span_listener", None)
                if span_listener is not None:
                    self.dispatch.events.unregister(span_listener)
            if self.telemetry is not None:
                from ..telemetry import (get_process_exporter,
                                         set_process_exporter)
                if self._owns_process_exporter and \
                        get_process_exporter() is self.telemetry:
                    set_process_exporter(None)
                self.telemetry.close()
            if getattr(self.exec_config, "lock_validation", False):
                # disarm the base flag this server armed at init (session
                # scopes are counted separately and unwind on their own)
                from ..common.locks import set_validation
                set_validation(False)
        finally:
            # the listener MUST die even if task teardown raised — a
            # leaked serve_forever thread would outlive the sweep
            WorkerServer._live.discard(self)
            self.httpd.shutdown()
            self.httpd.server_close()

    @classmethod
    def close_all_live(cls) -> None:
        """Close every still-open server in this process.  Test harness
        sweep (reference DistributedQueryRunner.java:108 is closeable):
        leaked serve_forever threads from unclosed fixtures otherwise
        accumulate across a long pytest run."""
        for server in list(cls._live):
            try:
                server.close()
            except Exception:
                pass
