"""Reference-shaped PlanFragment / RowExpression / Split JSON -> engine IR.

The TPU worker's analog of the native worker's plan-translation layer — the
piece that makes a Java coordinator able to drive this worker.  The
reference implements it as one converter per plan-node type plus expression
/ split / type converters:

  presto_cpp/main/types/PrestoToVeloxQueryPlan.{h,cpp}  (h:30-183: one
      toVeloxQueryPlan per node type; cpp 2,358 LoC)
  presto_cpp/main/types/PrestoToVeloxExpr.cpp           (RowExpressions)
  presto_cpp/main/types/PrestoToVeloxSplit.cpp          (splits)
  presto_cpp/main/types/TypeParser.cpp                  (type signatures —
      here: presto_tpu.common.types.parse_type)

Input shapes are the JSON the Java coordinator's HttpRemoteTask actually
produces (struct layouts: presto_cpp/presto_protocol/core/
presto_protocol_core.h; golden fixtures: presto_cpp/main/types/tests/data/
and presto_cpp/presto_protocol/tests/data/ — the unit tests parse those
Java-produced files directly).  Notable wire conventions:

  * plan nodes dispatch on "@type", either ".FilterNode" style or the full
    Java class name (presto_protocol_core.cpp:764 from_json dispatch);
  * map keys for VariableReferenceExpression are "name<type>" strings
    (presto_protocol_core.h:387-400);
  * ConstantExpression carries a base64 "valueBlock" — ONE position of a
    standard Block wire encoding (the repo's common.serde reads the Java
    bytes directly);
  * function identities live in functionHandle.signature.name as
    "presto.default.sum" / "presto.default.$operator$equal"
    (BuiltInFunctionHandle, "@type":"$static").
"""
from __future__ import annotations

import base64
import contextvars
from typing import Dict, List, Optional, Tuple

from ..common.block import block_to_values
from ..common.serde import read_block
from ..common.types import BIGINT, Type, parse_type
from ..connectors import catalog
from ..spi import plan as P
from ..spi.expr import (CallExpression, ConstantExpression, LambdaExpression,
                        RowExpression, SpecialFormExpression,
                        VariableReferenceExpression)


class PlanTranslationError(ValueError):
    """A reference-shaped fragment uses a feature the worker cannot map."""


# ---------------------------------------------------------------------------
# types / variables
# ---------------------------------------------------------------------------

def parse_variable(d: dict) -> VariableReferenceExpression:
    return VariableReferenceExpression(d["name"], parse_type(d["type"]))


def parse_map_key_variable(key: str) -> VariableReferenceExpression:
    """Decode a "name<type>" map key (reference
    VariableReferenceExpression(String), presto_protocol_core.h:392-400:
    split at the FIRST '<', drop the trailing '>')."""
    name, _, sig = key.partition("<")
    if not sig or not sig.endswith(">"):
        raise PlanTranslationError(f"bad variable map key {key!r}")
    return VariableReferenceExpression(name, parse_type(sig[:-1]))


# ---------------------------------------------------------------------------
# expressions (PrestoToVeloxExpr analog)
# ---------------------------------------------------------------------------

def decode_constant(d: dict) -> ConstantExpression:
    """ConstantExpression JSON -> value.  The wire carries a base64 Block
    with exactly one position (presto_protocol_core.h:899); the repo serde
    reads the Java bytes as-is and block_to_values applies the type
    semantics (double/real bit views, decimal rescale, date rendering)."""
    typ = parse_type(d["type"])
    raw = base64.b64decode(d["valueBlock"])
    block, _ = read_block(memoryview(raw), 0)
    values = block_to_values(typ, block)
    if len(values) != 1:
        raise PlanTranslationError(
            f"constant valueBlock has {len(values)} positions")
    return ConstantExpression(values[0], typ)


def function_name(d: dict) -> str:
    """Engine-facing function name from a CallExpression JSON.  Prefer the
    handle's signature name ("presto.default.$operator$equal") over
    displayName ("EQUAL" / "presto.default.sum"), then strip the namespace;
    lowering's canonical_name maps "$operator$..." to the engine names."""
    handle = d.get("functionHandle") or {}
    sig = handle.get("signature") or {}
    name = sig.get("name") or d.get("displayName") or ""
    if not name:
        raise PlanTranslationError("call with no function name")
    return name.split(".")[-1].lower()


def translate_expr(d: dict) -> RowExpression:
    kind = d.get("@type")
    if kind == "variable":
        return parse_variable(d)
    if kind == "constant":
        return decode_constant(d)
    if kind == "call":
        return CallExpression(
            function_name(d), parse_type(d["returnType"]),
            [translate_expr(a) for a in d["arguments"]])
    if kind == "special":
        return SpecialFormExpression(
            d["form"], parse_type(d["returnType"]),
            [translate_expr(a) for a in d["arguments"]])
    if kind == "lambda":
        return LambdaExpression(
            list(d["argumentTypes"]), list(d["arguments"]),
            translate_expr(d["body"]))
    raise PlanTranslationError(f"unknown RowExpression @type {kind!r}")


def _ordering_scheme(d: Optional[dict]) -> Optional[P.OrderingScheme]:
    if not d:
        return None
    return P.OrderingScheme([(parse_variable(o["variable"]), o["sortOrder"])
                             for o in d["orderBy"]])


# ---------------------------------------------------------------------------
# connector handles / splits (PrestoToVeloxSplit analog)
# ---------------------------------------------------------------------------

def _table_handle(d: dict) -> P.TableHandle:
    """Reference TableHandle {connectorId, connectorHandle, transaction,
    connectorTableLayout?} -> repo handle.  Per-connector payloads mirror
    presto_cpp/presto_protocol/connector/ (tpch: tableName+scaleFactor;
    hive/system: schemaName+tableName)."""
    cid = d["connectorId"]
    ch = d.get("connectorHandle") or {}
    if cid.startswith("tpch") or ch.get("@type") == "tpch":
        sf = float(ch.get("scaleFactor", 1.0))
        # repo tpch handles carry the scale in extra (schema is cosmetic)
        return P.TableHandle("tpch", f"sf{sf:g}", ch["tableName"],
                             (("scaleFactor", sf),))
    if cid.startswith("tpcds"):
        sf = float(ch.get("scaleFactor", 1.0))
        return P.TableHandle("tpcds", f"sf{sf:g}", ch["tableName"],
                             (("scaleFactor", sf),))
    schema = ch.get("schemaName", "default")
    table = ch.get("tableName")
    if table is None:
        raise PlanTranslationError(
            f"unsupported connector table handle for {cid!r}")
    return P.TableHandle(cid, schema, table, ())


def _column_handle(d: dict, var: VariableReferenceExpression) -> P.ColumnHandle:
    """ColumnHandle payloads: tpch TpchColumnHandle{columnName,type}
    (presto_protocol_tpch.h:37), hive HiveColumnHandle{name,typeSignature}."""
    name = d.get("columnName") or d.get("name") or var.name
    sig = d.get("type") or d.get("typeSignature")
    typ = parse_type(sig) if sig else var.type
    return P.ColumnHandle(name, typ)


def translate_split(d: dict) -> dict:
    """Reference Split JSON -> the worker's internal split dict.  Handles
    the wrapper {connectorId, connectorSplit, lifespan} (ScheduledSplit
    carries {sequenceId, planNodeId, split}), tpch TpchSplit
    {tableHandle, partNumber, totalParts} (row-range derived the same way
    TpchSplitManager shards the table), and $remote RemoteSplit
    {location:{location}, remoteSourceTaskId}."""
    if "split" in d and "connectorSplit" not in d:
        d = d["split"]                      # ScheduledSplit wrapper
    cs = d.get("connectorSplit", d)
    if cs.get("remote"):
        return cs                           # already the repo remote shape
    t = cs.get("@type", "")
    if t == "$remote" or "remoteSourceTaskId" in cs:
        loc = cs["location"]
        url = loc["location"] if isinstance(loc, dict) else loc
        return {"remote": True, "location": url}
    if t in ("tpch", "tpcds") or "tableHandle" in cs:
        th = cs["tableHandle"]
        table = th["tableName"]
        sf = float(th.get("scaleFactor", 1.0))
        cid = "tpcds" if t == "tpcds" else "tpch"
        total = catalog.table_row_count(table, sf, cid)
        part = int(cs.get("partNumber", 0))
        nparts = max(int(cs.get("totalParts", 1)), 1)
        per = (total + nparts - 1) // nparts
        return catalog.TableSplit(cid, table, sf, min(part * per, total),
                                  min((part + 1) * per, total)).to_dict()
    # repo-internal shapes and connector splits we have no mapping for pass
    # through unchanged; an alien connector split then fails the task at
    # scan setup with a clear message (same failure point as
    # PrestoToVeloxSplit's unknown-connector throw)
    return cs


# ---------------------------------------------------------------------------
# plan nodes (PrestoToVeloxQueryPlan analog, one handler per node type)
# ---------------------------------------------------------------------------

_JAVA = "com.facebook.presto.sql.planner.plan."


def _src(d: dict) -> P.PlanNode:
    return translate_node(d["source"])


def _t_tablescan(d: dict) -> P.PlanNode:
    outputs = [parse_variable(v) for v in d["outputVariables"]]
    assignments = {}
    for key, ch in (d.get("assignments") or {}).items():
        var = parse_map_key_variable(key)
        assignments[var] = _column_handle(ch, var)
    return P.TableScanNode(d["id"], _table_handle(d["table"]), outputs,
                           assignments)


def _t_filter(d: dict) -> P.PlanNode:
    return P.FilterNode(d["id"], _src(d), translate_expr(d["predicate"]))


def _t_project(d: dict) -> P.PlanNode:
    inner = (d.get("assignments") or {}).get("assignments") or {}
    assignments = {parse_map_key_variable(k): translate_expr(e)
                   for k, e in inner.items()}
    return P.ProjectNode(d["id"], _src(d), assignments)


def _t_output(d: dict) -> P.PlanNode:
    return P.OutputNode(d["id"], _src(d), list(d.get("columnNames") or []),
                        [parse_variable(v) for v in d["outputVariables"]])


def _t_values(d: dict) -> P.PlanNode:
    return P.ValuesNode(d["id"],
                        [parse_variable(v) for v in d["outputVariables"]],
                        [[translate_expr(e) for e in row]
                         for row in d.get("rows") or []])


def _t_limit(d: dict) -> P.PlanNode:
    step = d.get("step", "FINAL")
    return P.LimitNode(d["id"], _src(d), int(d["count"]),
                       P.PARTIAL if step == "PARTIAL" else P.FINAL)


def _t_topn(d: dict) -> P.PlanNode:
    step = d.get("step", "SINGLE")
    return P.TopNNode(d["id"], _src(d), int(d["count"]),
                      _ordering_scheme(d["orderingScheme"]), step)


def _t_sort(d: dict) -> P.PlanNode:
    return P.SortNode(d["id"], _src(d),
                      _ordering_scheme(d["orderingScheme"]),
                      bool(d.get("isPartial", False)))


def _t_distinct_limit(d: dict) -> P.PlanNode:
    return P.DistinctLimitNode(
        d["id"], _src(d), int(d["limit"]),
        [parse_variable(v) for v in d["distinctVariables"]])


def _t_mark_distinct(d: dict) -> P.PlanNode:
    return P.MarkDistinctNode(
        d["id"], _src(d), parse_variable(d["markerVariable"]),
        [parse_variable(v) for v in d["distinctVariables"]])


def _t_enforce_single_row(d: dict) -> P.PlanNode:
    return P.EnforceSingleRowNode(d["id"], _src(d))


def _t_assign_unique_id(d: dict) -> P.PlanNode:
    return P.AssignUniqueIdNode(d["id"], _src(d),
                                parse_variable(d["idVariable"]))


def _t_aggregation(d: dict) -> P.PlanNode:
    gsets = d["groupingSets"]
    if int(gsets.get("groupingSetCount", 1)) != 1:
        raise PlanTranslationError(
            "multiple grouping sets arrive via GroupIdNode; a plain "
            "AggregationNode must have exactly one")
    keys = [parse_variable(v) for v in gsets["groupingKeys"]]
    source = _src(d)
    aggregations: Dict[VariableReferenceExpression, P.Aggregation] = {}
    filter_projections: Dict[VariableReferenceExpression, RowExpression] = {}
    for key, agg in (d.get("aggregations") or {}).items():
        var = parse_map_key_variable(key)
        call = translate_expr(agg["call"])
        mask = parse_variable(agg["mask"]) if agg.get("mask") else None
        if agg.get("filter"):
            # FILTER (WHERE p): the engine's Aggregation.mask is exactly
            # the reference's filter semantics (AggregationNode.java pairs
            # them; the coordinator plans FILTER as either field).  A
            # non-variable filter expression is bound below via a
            # synthesized pass-through projection.
            fexpr = translate_expr(agg["filter"])
            if mask is not None:
                # combine with the existing mask INLINE (both operands
                # must resolve against the input batch: projection
                # assignments cannot reference sibling assignments)
                from ..spi.expr import special as _mkspecial
                combined = VariableReferenceExpression(
                    f"{var.name}__filtermask", parse_type("boolean"))
                filter_projections[combined] = _mkspecial(
                    "AND", parse_type("boolean"), mask, fexpr)
                mask = combined
            elif isinstance(fexpr, VariableReferenceExpression):
                mask = fexpr
            else:
                fvar = VariableReferenceExpression(
                    f"{var.name}__filter", parse_type("boolean"))
                filter_projections[fvar] = fexpr
                mask = fvar
        if agg.get("orderBy"):
            raise PlanTranslationError("ORDER BY aggregates are not "
                                       "supported")
        aggregations[var] = P.Aggregation(call, bool(agg.get("distinct")),
                                          mask)
    if filter_projections:
        assigns = {v: v for v in source.output_variables}
        assigns.update(filter_projections)
        source = P.ProjectNode(d["id"] + ".aggfilter", source, assigns)
    return P.AggregationNode(d["id"], source, aggregations, keys,
                             d.get("step", "SINGLE"))


def _t_group_id(d: dict) -> P.PlanNode:
    """GroupIdNode (presto_protocol_core.h:1340-1349): groupingSets are
    lists of OUTPUT grouping columns; groupingColumns maps each output
    column to its input ("name<type>" map keys)."""
    grouping_columns = {parse_map_key_variable(k): parse_variable(v)
                        for k, v in (d.get("groupingColumns") or {}).items()}
    return P.GroupIdNode(
        d["id"], _src(d),
        [[parse_variable(v) for v in s] for s in d["groupingSets"]],
        grouping_columns,
        [parse_variable(v) for v in d.get("aggregationArguments") or []],
        parse_variable(d["groupIdVariable"]))


def _t_join(d: dict) -> P.PlanNode:
    jt = d["type"]
    if jt not in (P.INNER, P.LEFT, P.RIGHT, P.FULL):
        raise PlanTranslationError(f"join type {jt!r}")
    criteria = [(parse_variable(c["left"]), parse_variable(c["right"]))
                for c in d.get("criteria") or []]
    filt = translate_expr(d["filter"]) if d.get("filter") else None
    dyn = {fid: parse_variable(v).name
           for fid, v in (d.get("dynamicFilters") or {}).items()}
    return P.JoinNode(d["id"], jt, translate_node(d["left"]),
                      translate_node(d["right"]), criteria,
                      [parse_variable(v) for v in d["outputVariables"]],
                      filt, d.get("distributionType"), dyn)


def _t_semi_join(d: dict) -> P.PlanNode:
    return P.SemiJoinNode(
        d["id"], _src(d), translate_node(d["filteringSource"]),
        parse_variable(d["sourceJoinVariable"]),
        parse_variable(d["filteringSourceJoinVariable"]),
        parse_variable(d["semiJoinOutput"]))


def _t_remote_source(d: dict) -> P.PlanNode:
    return P.RemoteSourceNode(
        d["id"], [str(f) for f in d["sourceFragmentIds"]],
        [parse_variable(v) for v in d["outputVariables"]],
        bool(d.get("ensureSourceOrdering", False)),
        _ordering_scheme(d.get("orderingScheme")))


def _t_exchange(d: dict) -> P.PlanNode:
    scheme = _partitioning_scheme(d["partitioningScheme"])
    return P.ExchangeNode(
        d["id"], d["type"], d["scope"], scheme,
        [translate_node(s) for s in d["sources"]],
        [[parse_variable(v) for v in row] for row in d.get("inputs") or []])


_BOUND = {"UNBOUNDED_PRECEDING": "UNBOUNDED_PRECEDING",
          "PRECEDING": "PRECEDING", "CURRENT_ROW": "CURRENT",
          "FOLLOWING": "FOLLOWING",
          "UNBOUNDED_FOLLOWING": "UNBOUNDED_FOLLOWING"}


def _resolve_constant_int(src: P.PlanNode, expr: RowExpression):
    """Resolve a frame-offset RowExpression to a Python int.  The
    coordinator binds offsets as variables assigned constants by a
    projection below the window (WindowNode.Frame startValue/endValue are
    variable references); walk the source subtree's projections for the
    binding (the constant-propagation step the native worker performs in
    toVeloxQueryPlan's frame conversion)."""
    if isinstance(expr, ConstantExpression):
        return int(expr.value)
    if isinstance(expr, VariableReferenceExpression):
        for n in P.walk_plan(src):
            if isinstance(n, P.ProjectNode):
                for v, e in n.assignments.items():
                    if v.name == expr.name and \
                            isinstance(e, ConstantExpression):
                        return int(e.value)
    raise PlanTranslationError(
        f"window frame offset is not a resolvable constant: {expr!r}")


def _t_window(d: dict) -> P.PlanNode:
    spec = d["specification"]
    part = [parse_variable(v) for v in spec.get("partitionBy") or []]
    ordering = _ordering_scheme(spec.get("orderingScheme"))
    source = _src(d)
    funcs: Dict[VariableReferenceExpression, P.WindowFunction] = {}
    for key, f in (d.get("windowFunctions") or {}).items():
        var = parse_map_key_variable(key)
        call = translate_expr(f["functionCall"])
        frame_j = f.get("frame") or {}
        frame = None
        if frame_j:
            start = _BOUND[frame_j["startType"]]
            end = _BOUND[frame_j["endType"]]
            def _offset(which):
                if not frame_j.get(which + "Value"):
                    return None
                try:
                    return _resolve_constant_int(
                        source, translate_expr(frame_j[which + "Value"]))
                except PlanTranslationError:
                    # Frame.originalStartValue/originalEndValue carry the
                    # source text of the offset (presto_protocol_core.h:
                    # 1324-1325) — a literal offset parses directly
                    orig = frame_j.get("original" + which.capitalize()
                                       + "Value")
                    if orig is not None:
                        try:
                            return int(str(orig))
                        except ValueError:
                            pass
                    raise

            so = _offset("start")
            eo = _offset("end")
            if frame_j["type"] != "ROWS" and (so is not None
                                              or eo is not None):
                # the window executor implements offset bounds for ROWS
                # frames only (operators.py frame_bounds); RANGE/GROUPS
                # offsets must stay a translate-time rejection
                raise PlanTranslationError(
                    f"{frame_j['type']} frames with value offsets are "
                    f"not supported")
            if not (frame_j["type"] == "RANGE" and so is None and eo is None
                    and start == "UNBOUNDED_PRECEDING" and end == "CURRENT"):
                frame = {"type": frame_j["type"], "startKind": start,
                         "startOffset": so, "endKind": end,
                         "endOffset": eo}
        funcs[var] = P.WindowFunction(call, frame)
    return P.WindowNode(d["id"], source, part, ordering, funcs)


def _row_number_limited(node_id: str, source: P.PlanNode,
                        part: List[VariableReferenceExpression],
                        ordering: Optional[P.OrderingScheme],
                        rn: VariableReferenceExpression,
                        limit: Optional[int]) -> P.PlanNode:
    """row_number() window, optionally filtered to rn <= limit — the
    shared lowering for RowNumberNode.maxRowCountPerPartition and
    TopNRowNumberNode (the reference's TopNRowNumberOperator is an
    execution-time optimization of exactly this pair)."""
    from ..spi.expr import call as _mkcall, constant as _mkconst
    win = P.WindowNode(node_id, source, part, ordering,
                       {rn: P.WindowFunction(
                           CallExpression("row_number", BIGINT, []), None)})
    if limit is None:
        return win
    pred = _mkcall("lte", parse_type("boolean"), rn,
                   _mkconst(int(limit), BIGINT))
    return P.FilterNode(node_id + ".topn", win, pred)


def _t_topn_row_number(d: dict) -> P.PlanNode:
    """TopNRowNumberNode (presto_protocol_core.h:2417-2426)."""
    spec = d["specification"]
    return _row_number_limited(
        d["id"], _src(d),
        [parse_variable(v) for v in spec.get("partitionBy") or []],
        _ordering_scheme(spec.get("orderingScheme")),
        parse_variable(d["rowNumberVariable"]),
        int(d["maxRowCountPerPartition"]))


def _t_row_number(d: dict) -> P.PlanNode:
    return _row_number_limited(
        d["id"], _src(d),
        [parse_variable(v) for v in d.get("partitionBy") or []],
        None, parse_variable(d["rowNumberVariable"]),
        d.get("maxRowCountPerPartition"))


_TABLE_WRITE_INFO = contextvars.ContextVar("table_write_info",
                                           default=None)


def _write_target():
    """The task update's TableWriteInfo writer target
    (presto_protocol_core.h:726; ExecutionWriterTarget subtypes
    CreateHandle/InsertHandle — ExecutionWriterTarget.java:30-35).
    Returns (connector_id, table_name)."""
    twi = _TABLE_WRITE_INFO.get() or {}
    target = twi.get("writerTarget") or {}
    handle = target.get("handle") or {}
    cid = handle.get("connectorId")
    stn = target.get("schemaTableName") or {}
    table = stn.get("table")
    if not cid or not table:
        raise PlanTranslationError(
            "TableWriterNode needs TaskUpdateRequest.tableWriteInfo "
            "with a CreateHandle/InsertHandle writer target")
    return cid, table


def _t_table_writer(d: dict) -> P.PlanNode:
    """TableWriterNode (presto_protocol_core.h:2279-2292,
    TableWriterOperator.java:78).  The wire node carries the output
    variables and column names; the TARGET rides the task update's
    TableWriteInfo (the struct's own target is 'TODO' upstream too)."""
    cid, table = _write_target()
    outputs = [parse_variable(d["rowCountVariable"]),
               parse_variable(d["fragmentVariable"])]
    if d.get("tableCommitContextVariable"):
        outputs.append(parse_variable(d["tableCommitContextVariable"]))
    return P.TableWriterNode(
        d["id"], _src(d), cid, table,
        [str(c) for c in d.get("columnNames") or []], outputs)


def _t_table_finish(d: dict) -> P.PlanNode:
    """TableFinishNode (TableFinishNode.java:46-52,
    TableFinishOperator.java): commits the staged fragments, emits the
    row count."""
    cid, table = _write_target()
    return P.TableFinishNode(
        d["id"], _src(d), cid, table,
        [parse_variable(d["rowCountVariable"])])


def _t_unnest(d: dict) -> P.PlanNode:
    """UnnestNode (presto_protocol_core.h:2431-2438,
    PrestoToVeloxQueryPlan's toVeloxQueryPlan(UnnestNode),
    UnnestOperator.java): unnestVariables is a Jackson map keyed by the
    serialized variable."""
    unnest = []
    for k, elems in (d.get("unnestVariables") or {}).items():
        unnest.append((parse_map_key_variable(k),
                       [parse_variable(e) for e in elems]))
    ov = d.get("ordinalityVariable")
    return P.UnnestNode(
        d["id"], _src(d),
        [parse_variable(v) for v in d.get("replicateVariables") or []],
        unnest, None if ov is None else parse_variable(ov))


_NODE_HANDLERS = {
    ".TableScanNode": _t_tablescan,
    ".FilterNode": _t_filter,
    ".ProjectNode": _t_project,
    ".OutputNode": _t_output,
    ".ValuesNode": _t_values,
    ".LimitNode": _t_limit,
    ".TopNNode": _t_topn,
    ".SortNode": _t_sort,
    ".DistinctLimitNode": _t_distinct_limit,
    ".MarkDistinctNode": _t_mark_distinct,
    ".AggregationNode": _t_aggregation,
    ".GroupIdNode": _t_group_id,
    ".TopNRowNumberNode": _t_topn_row_number,
    ".JoinNode": _t_join,
    ".SemiJoinNode": _t_semi_join,
    ".WindowNode": _t_window,
    ".EnforceSingleRowNode": _t_enforce_single_row,
    ".AssignUniqueId": _t_assign_unique_id,
    ".ExchangeNode": _t_exchange,
    ".RemoteSourceNode": _t_remote_source,
    ".RowNumberNode": _t_row_number,
    ".UnnestNode": _t_unnest,
    ".TableWriterNode": _t_table_writer,
    ".TableFinishNode": _t_table_finish,
}


def translate_node(d: dict) -> P.PlanNode:
    """Dispatch on "@type".  Jackson emits either the MINIMAL_CLASS form
    (".FilterNode") or a full class name depending on which package the
    node class lives in — and that has shifted across releases — so both
    spellings normalize to the bare ".Name" key."""
    t = d.get("@type") or ""
    key = "." + t.rsplit(".", 1)[-1] if "." in t[1:] else t
    handler = _NODE_HANDLERS.get(key)
    if handler is None:
        raise PlanTranslationError(f"unsupported plan node @type {t!r}")
    return handler(d)


# ---------------------------------------------------------------------------
# fragment (toVeloxQueryPlan(PlanFragment) analog)
# ---------------------------------------------------------------------------

def _system_partitioning(handle: dict) -> str:
    """PartitioningHandle {connectorHandle: $remote SystemPartitioningHandle
    {partitioning, function}} -> repo *_DISTRIBUTION constant
    (SystemPartitioningHandle.java:62-68)."""
    ch = (handle or {}).get("connectorHandle") or {}
    if not ch:
        return P.SOURCE_DISTRIBUTION        # absent handle: leaf default
    if "partitioning" not in ch:
        # a connector partitioning handle (e.g. hive bucketing) — mapping
        # it to a system distribution would silently mis-partition output
        raise PlanTranslationError(
            f"non-system partitioning handle {ch.get('@type')!r}")
    part = ch["partitioning"]
    func = ch.get("function", "UNKNOWN")
    if part == "SOURCE":
        return P.SOURCE_DISTRIBUTION
    if part == "SINGLE" or part == "COORDINATOR_ONLY":
        return P.SINGLE_DISTRIBUTION
    if part == "SCALED":
        return P.SCALED_WRITER_DISTRIBUTION
    if part in ("FIXED", "ARBITRARY"):
        if func == "HASH":
            return P.FIXED_HASH_DISTRIBUTION
        if func == "BROADCAST":
            return P.FIXED_BROADCAST_DISTRIBUTION
        return P.FIXED_ARBITRARY_DISTRIBUTION
    raise PlanTranslationError(f"partitioning {part!r}/{func!r}")


def _partitioning_scheme(d: dict) -> P.PartitioningScheme:
    part = d["partitioning"]
    handle = _system_partitioning(part.get("handle"))
    args = []
    for a in part.get("arguments") or []:
        e = translate_expr(a)
        if isinstance(e, VariableReferenceExpression):
            args.append(e)
        elif not isinstance(e, ConstantExpression):
            raise PlanTranslationError(
                "unsupported partitioning argument")
        # constants (hive bucket-function payloads) hash identically for
        # every row — dropping them still yields a consistent partition
        # mapping for a system exchange
    return P.PartitioningScheme(
        handle, args, [parse_variable(v) for v in d["outputLayout"]])


def is_reference_fragment(d: dict) -> bool:
    """Distinguish a coordinator-shaped fragment from the repo's own
    serialization (both tag nodes with "@type"): the reference shape
    carries tableScanSchedulingOrder / stageExecutionDescriptor / a
    variables list (PlanFragment, presto_protocol_core.h:1936-1946)."""
    return ("tableScanSchedulingOrder" in d or "stageExecutionDescriptor"
            in d or "variables" in d)


def translate_fragment(d: dict,
                       table_write_info: Optional[dict] = None
                       ) -> P.PlanFragment:
    token = _TABLE_WRITE_INFO.set(table_write_info)
    try:
        return _translate_fragment_inner(d)
    finally:
        _TABLE_WRITE_INFO.reset(token)


def _translate_fragment_inner(d: dict) -> P.PlanFragment:
    root = translate_node(d["root"])
    partitioning = _system_partitioning(d.get("partitioning"))
    scheme = _partitioning_scheme(d["partitioningScheme"])
    scan_ids = [str(x) for x in d.get("tableScanSchedulingOrder") or []]
    if not scan_ids:
        scan_ids = [n.id for n in P.walk_plan(root)
                    if isinstance(n, P.TableScanNode)]
    return P.PlanFragment(str(d["id"]), root, partitioning, scheme, scan_ids)
