"""External-worker launcher: the integration point a Java coordinator's
test harness uses to spawn this TPU worker per node.

The reference wires native workers into a Java DistributedQueryRunner via
setExternalWorkerLauncher — a BiFunction<workerIndex, discoveryUri,
Process> that writes an etc/ directory (config.properties with the
discovery URI and an ephemeral port, node.properties, catalog mounts) and
execs the worker binary on it (DistributedQueryRunner.java:190-215,
PrestoNativeQueryRunnerUtils.java:434-520).  This module is that launcher
for the TPU worker, in two forms:

- `launch_worker(worker_index, discovery_uri, ...)` — the Python callable
  (spawns `python -m presto_tpu.worker --etc-dir <tmpdir>`).
- `python -m presto_tpu.worker.launcher <workerIndex> <discoveryUri>` —
  the exec form for the Java side: the BiFunction body reduces to
  `new ProcessBuilder(python, "-m", "presto_tpu.worker.launcher",
  String.valueOf(workerIndex), discoveryUri.toString()).start()`.

The spawned worker announces itself to the coordinator's discovery
service and serves the /v1/task protocol with reference-shaped
PlanFragment JSON (worker/plan_translation.py), so the Java scheduler
drives it like any native worker.
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import uuid
from typing import Dict, Optional


def write_etc_dir(worker_index: int, discovery_uri: str,
                  base_dir: Optional[str] = None,
                  extra_config: Optional[Dict[str, str]] = None,
                  catalogs: Optional[Dict[str, str]] = None) -> str:
    """Write the reference launcher's etc/ layout
    (PrestoNativeQueryRunnerUtils.java:453-520) and return its path."""
    root = base_dir or os.path.join(tempfile.gettempdir(),
                                    "presto_tpu_workers")
    os.makedirs(root, exist_ok=True)
    etc = tempfile.mkdtemp(prefix=f"worker{worker_index}-", dir=root)
    config = {
        "discovery.uri": discovery_uri,
        "presto.version": "testversion",
        "http-server.http.port": "0",
        **(extra_config or {}),
    }
    with open(os.path.join(etc, "config.properties"), "w") as f:
        for k, v in config.items():
            f.write(f"{k}={v}\n")
    with open(os.path.join(etc, "node.properties"), "w") as f:
        f.write(f"node.id={uuid.uuid4()}\n"
                "node.internal-address=127.0.0.1\n"
                "node.environment=testing\n"
                "node.location=test-location\n")
    catalog_dir = os.path.join(etc, "catalog")
    os.makedirs(catalog_dir)
    if catalogs is None:
        catalogs = {"tpchstandard": "connector.name=tpch\n"}
    for name, body in catalogs.items():
        with open(os.path.join(catalog_dir, f"{name}.properties"), "w") as f:
            f.write(body)
    return etc


def launch_worker(worker_index: int, discovery_uri: str,
                  base_dir: Optional[str] = None,
                  extra_config: Optional[Dict[str, str]] = None,
                  catalogs: Optional[Dict[str, str]] = None,
                  stdout=None) -> subprocess.Popen:
    """Spawn one external TPU worker process announcing to
    `discovery_uri`; returns the Process (caller owns its lifetime, like
    the reference's externalWorkersBuilder)."""
    etc = write_etc_dir(worker_index, discovery_uri, base_dir,
                        extra_config, catalogs)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [repo_root] + [p for p in
                       os.environ.get("PYTHONPATH", "").split(os.pathsep)
                       if p]))
    out = stdout if stdout is not None else open(
        os.path.join(etc, "worker.out"), "w")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "presto_tpu.worker", "--etc-dir", etc],
            stdout=out, stderr=subprocess.STDOUT, env=env)
    finally:
        if stdout is None:
            out.close()   # the child holds its own duplicate


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) < 2:
        print("usage: python -m presto_tpu.worker.launcher "
              "<workerIndex> <discoveryUri>", file=sys.stderr)
        return 2
    etc = write_etc_dir(int(args[0]), args[1])
    # exec form: become the worker so the caller's Process handle IS the
    # worker (kill/waitFor work as the Java harness expects)
    os.execv(sys.executable,
             [sys.executable, "-m", "presto_tpu.worker", "--etc-dir", etc])
    return 0  # unreachable


if __name__ == "__main__":
    sys.exit(main())
