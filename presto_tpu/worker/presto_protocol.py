"""Reference-shaped protocol DTOs: the Java coordinator's wire JSON.

Field names and nesting mirror the reference protocol structs that the
native worker generates from the Java sources
(presto-native-execution/presto_cpp/presto_protocol/presto_protocol.yml →
presto_protocol_core.h: TaskUpdateRequest :807, TaskSource :797,
ScheduledSplit :782, OutputBuffers :558, SessionRepresentation :697,
TaskStatus :2358; TaskInfo fixture at presto_cpp/main/tests/data/
TaskInfo.json) — scoped to the subset this worker consumes, exactly the
codegen's own strategy.

The worker ACCEPTS this shape on POST /v1/task/{id} alongside its native
compact shape (worker/protocol.py), so an HttpRemoteTask-style
coordinator can drive it; TaskStatus/TaskInfo responses carry these field
names (plus the compact legacy fields for in-repo clients).

Round-trip conformance: tests/test_presto_protocol.py re-serializes the
reference's own TaskInfo.json fixture through these DTOs and diffs
field-by-field (fixtures are read from /root/reference at test time).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

# TaskState enum ordinals follow reference TaskState.java
TASK_STATES = ("PLANNED", "RUNNING", "FINISHED", "CANCELED", "ABORTED",
               "FAILED")


def _opt(d: dict, key: str, value) -> None:
    if value is not None:
        d[key] = value


@dataclass
class SessionRepresentation:
    """presto_protocol_core.h:697 (subset the worker reads)."""
    queryId: str = ""
    user: str = "user"
    clientTransactionSupport: bool = False
    principal: Optional[str] = None
    source: Optional[str] = None
    catalog: Optional[str] = None
    schema: Optional[str] = None
    traceToken: Optional[str] = None
    timeZoneKey: int = 0
    locale: str = "en-US"
    remoteUserAddress: Optional[str] = None
    userAgent: Optional[str] = None
    clientInfo: Optional[str] = None
    clientTags: List[str] = field(default_factory=list)
    startTime: int = 0
    systemProperties: Dict[str, str] = field(default_factory=dict)
    catalogProperties: Dict[str, Dict[str, str]] = field(
        default_factory=dict)

    def to_json(self) -> dict:
        out = {"queryId": self.queryId,
               "clientTransactionSupport": self.clientTransactionSupport,
               "user": self.user, "timeZoneKey": self.timeZoneKey,
               "locale": self.locale, "clientTags": list(self.clientTags),
               "startTime": self.startTime,
               "systemProperties": dict(self.systemProperties),
               "catalogProperties": dict(self.catalogProperties)}
        for k in ("principal", "source", "catalog", "schema", "traceToken",
                  "remoteUserAddress", "userAgent", "clientInfo"):
            _opt(out, k, getattr(self, k))
        return out

    @staticmethod
    def from_json(d: dict) -> "SessionRepresentation":
        return SessionRepresentation(
            queryId=d.get("queryId", ""), user=d.get("user", "user"),
            clientTransactionSupport=d.get("clientTransactionSupport",
                                           False),
            principal=d.get("principal"), source=d.get("source"),
            catalog=d.get("catalog"), schema=d.get("schema"),
            traceToken=d.get("traceToken"),
            timeZoneKey=d.get("timeZoneKey", 0),
            locale=d.get("locale", "en-US"),
            remoteUserAddress=d.get("remoteUserAddress"),
            userAgent=d.get("userAgent"), clientInfo=d.get("clientInfo"),
            clientTags=d.get("clientTags", []),
            startTime=d.get("startTime", 0),
            systemProperties=d.get("systemProperties", {}),
            catalogProperties=d.get("catalogProperties", {}))


@dataclass
class ScheduledSplit:
    """presto_protocol_core.h:782: {sequenceId, planNodeId, split}."""
    sequenceId: int
    planNodeId: str
    split: dict          # {connectorId, transactionHandle?, connectorSplit}

    def to_json(self) -> dict:
        return {"sequenceId": self.sequenceId,
                "planNodeId": self.planNodeId, "split": self.split}

    @staticmethod
    def from_json(d: dict) -> "ScheduledSplit":
        return ScheduledSplit(d.get("sequenceId", 0), d["planNodeId"],
                              d.get("split", {}))


@dataclass
class TaskSource:
    """presto_protocol_core.h:797."""
    planNodeId: str
    splits: List[ScheduledSplit] = field(default_factory=list)
    noMoreSplitsForLifespan: List[dict] = field(default_factory=list)
    noMoreSplits: bool = True

    def to_json(self) -> dict:
        return {"planNodeId": self.planNodeId,
                "splits": [s.to_json() for s in self.splits],
                "noMoreSplitsForLifespan": list(
                    self.noMoreSplitsForLifespan),
                "noMoreSplits": self.noMoreSplits}

    @staticmethod
    def from_json(d: dict) -> "TaskSource":
        return TaskSource(
            d["planNodeId"],
            [ScheduledSplit.from_json(s) for s in d.get("splits", [])],
            d.get("noMoreSplitsForLifespan", []),
            d.get("noMoreSplits", True))


@dataclass
class OutputBuffers:
    """presto_protocol_core.h:558: buffers maps OutputBufferId -> logical
    partition number."""
    type: str = "PARTITIONED"      # PARTITIONED | BROADCAST | ARBITRARY
    version: int = 0
    noMoreBufferIds: bool = True
    buffers: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"type": self.type, "version": self.version,
                "noMoreBufferIds": self.noMoreBufferIds,
                "buffers": dict(self.buffers)}

    @staticmethod
    def from_json(d: dict) -> "OutputBuffers":
        return OutputBuffers(d.get("type", "PARTITIONED"),
                             d.get("version", 0),
                             d.get("noMoreBufferIds", True),
                             {str(k): int(v)
                              for k, v in d.get("buffers", {}).items()})


@dataclass
class TaskUpdateRequest:
    """presto_protocol_core.h:807 — the exact field set HttpRemoteTask
    POSTs (HttpRemoteTask.java:883-936)."""
    session: SessionRepresentation = field(
        default_factory=SessionRepresentation)
    extraCredentials: Dict[str, str] = field(default_factory=dict)
    fragment: Optional[str] = None       # base64(plan fragment json)
    sources: List[TaskSource] = field(default_factory=list)
    outputIds: OutputBuffers = field(default_factory=OutputBuffers)
    tableWriteInfo: Optional[dict] = None

    def to_json(self) -> dict:
        out = {"session": self.session.to_json(),
               "extraCredentials": dict(self.extraCredentials),
               "sources": [s.to_json() for s in self.sources],
               "outputIds": self.outputIds.to_json()}
        _opt(out, "fragment", self.fragment)
        _opt(out, "tableWriteInfo", self.tableWriteInfo)
        return out

    @staticmethod
    def from_json(d: dict) -> "TaskUpdateRequest":
        return TaskUpdateRequest(
            SessionRepresentation.from_json(d.get("session", {})),
            d.get("extraCredentials", {}), d.get("fragment"),
            [TaskSource.from_json(s) for s in d.get("sources", [])],
            OutputBuffers.from_json(d.get("outputIds", {})),
            d.get("tableWriteInfo"))


@dataclass
class TaskStatus:
    """presto_protocol_core.h:2358 / tests/data/TaskInfo.json taskStatus."""
    taskInstanceIdLeastSignificantBits: int = 0
    taskInstanceIdMostSignificantBits: int = 0
    version: int = 0
    state: str = "PLANNED"
    self_uri: str = ""
    completedDriverGroups: List[str] = field(default_factory=list)
    failures: List[dict] = field(default_factory=list)
    queuedPartitionedDrivers: int = 0
    runningPartitionedDrivers: int = 0
    outputBufferUtilization: float = 0.0
    outputBufferOverutilized: bool = False
    physicalWrittenDataSizeInBytes: int = 0
    memoryReservationInBytes: int = 0
    systemMemoryReservationInBytes: int = 0
    fullGcCount: int = 0
    fullGcTimeInMillis: int = 0
    peakNodeTotalMemoryReservationInBytes: int = 0
    totalCpuTimeInNanos: int = 0
    taskAgeInMillis: int = 0
    queuedPartitionedSplitsWeight: int = 0
    runningPartitionedSplitsWeight: int = 0

    _FIELDS = ("taskInstanceIdLeastSignificantBits",
               "taskInstanceIdMostSignificantBits", "version", "state",
               "completedDriverGroups", "failures",
               "queuedPartitionedDrivers", "runningPartitionedDrivers",
               "outputBufferUtilization", "outputBufferOverutilized",
               "physicalWrittenDataSizeInBytes",
               "memoryReservationInBytes",
               "systemMemoryReservationInBytes", "fullGcCount",
               "fullGcTimeInMillis",
               "peakNodeTotalMemoryReservationInBytes",
               "totalCpuTimeInNanos", "taskAgeInMillis",
               "queuedPartitionedSplitsWeight",
               "runningPartitionedSplitsWeight")

    def to_json(self) -> dict:
        out = {k: getattr(self, k) for k in self._FIELDS}
        out["self"] = self.self_uri
        return out

    @staticmethod
    def from_json(d: dict) -> "TaskStatus":
        kw = {k: d[k] for k in TaskStatus._FIELDS if k in d}
        return TaskStatus(self_uri=d.get("self", ""), **kw)
