"""Properties-file configuration layer.

The reference boots from an etc/ directory of Java .properties files:
config.properties (server keys, presto_cpp/main/common/Configs.h:162 and
ConfigPropertyMetadata), node.properties (node.id / node.environment,
NodeConfig), and catalog/*.properties (one connector mount per file,
connector.name selects the plugin — presto_cpp/main/PrestoServer.cpp
registerConnectors / java CatalogManager).  This module parses that
layout and maps the keys this engine understands onto WorkerServer and
ExecutionConfig arguments; unknown keys are ignored the way the native
worker ignores coordinator-only properties.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

from ..exec.pipeline import ExecutionConfig, tuned_config
from .protocol import parse_data_size, parse_duration


def load_properties(path: str) -> Dict[str, str]:
    """Parse a Java .properties file: key=value (or key:value), # / !
    comments, backslash line continuation, whitespace-trimmed keys."""
    props: Dict[str, str] = {}

    def store(line: str) -> None:
        # earliest separator wins (java.util.Properties: '=' and ':' are
        # equivalent; the first unescaped one terminates the key)
        idxs = [i for i in (line.find("="), line.find(":")) if i >= 0]
        if idxs:
            i = min(idxs)
            props[line[:i].strip()] = line[i + 1:].strip()
        else:
            props[line] = ""

    with open(path) as f:
        pending = ""
        for raw in f:
            line = pending + raw.strip()
            pending = ""
            if not line or line[0] in "#!":
                continue
            if line.endswith("\\") and not line.endswith("\\\\"):
                pending = line[:-1]
                continue
            store(line)
        if pending:  # trailing continuation with no following line
            store(pending)
    return props


def _bool(v: str) -> bool:
    return str(v).strip().lower() == "true"


def execution_config_from_properties(props: Dict[str, str],
                                     base: Optional[ExecutionConfig] = None
                                     ) -> ExecutionConfig:
    """config.properties keys -> ExecutionConfig (the worker-side subset
    of Configs.h / SystemSessionProperties)."""
    import dataclasses
    cfg = base or ExecutionConfig()
    kw = {}
    if "query.max-memory-per-node" in props:
        kw["memory_budget_bytes"] = parse_data_size(
            props["query.max-memory-per-node"])
    if "query.max-memory" in props:
        kw["memory_max_query_bytes"] = parse_data_size(
            props["query.max-memory"])
    if "memory.max-query-bytes" in props:      # byte-count alias
        kw["memory_max_query_bytes"] = int(props["memory.max-query-bytes"])
    if "experimental.spill-enabled" in props:
        kw["spill_enabled"] = _bool(props["experimental.spill-enabled"])
    if "experimental.spiller-max-used-space" in props:
        kw["spill_budget_bytes"] = parse_data_size(
            props["experimental.spiller-max-used-space"])
    if "spill.host-budget-bytes" in props:     # byte-count alias
        kw["spill_budget_bytes"] = int(props["spill.host-budget-bytes"])
    if props.get("experimental.spiller-spill-path"):
        kw["spill_path"] = props["experimental.spiller-spill-path"]
    if props.get("spill.path"):                # short alias
        kw["spill_path"] = props["spill.path"]
    if "spill.async-staging" in props:
        kw["spill_async_staging"] = _bool(props["spill.async-staging"])
    if "exchange.compression-enabled" in props:
        kw["exchange_compression"] = _bool(
            props["exchange.compression-enabled"])
    if "exchange.compression-codec" in props:
        codec = props["exchange.compression-codec"].upper()
        from ..common.compression import supported_codecs
        if codec not in supported_codecs():
            raise ValueError(
                f"unsupported exchange.compression-codec {codec!r}")
        kw["exchange_compression_codec"] = codec
    if "task.batch-rows" in props:
        kw["batch_rows"] = int(props["task.batch-rows"])
    if "task.max-drivers-per-task" in props:
        kw["task_concurrency"] = int(props["task.max-drivers-per-task"])
    if "task.fuse-pipelines" in props:
        kw["fuse_pipelines"] = _bool(props["task.fuse-pipelines"])
    if "task.grouped-lifespans" in props:
        kw["grouped_lifespans"] = int(props["task.grouped-lifespans"])
    if "task.grouped-prefetch-depth" in props:
        kw["grouped_prefetch_depth"] = int(
            props["task.grouped-prefetch-depth"])
    if "task.grouped-lifespan-sharding" in props:
        kw["grouped_lifespan_sharding"] = _bool(
            props["task.grouped-lifespan-sharding"])
    if "exchange.max-error-duration" in props:
        kw["exchange_max_error_duration_s"] = parse_duration(
            props["exchange.max-error-duration"])
    if "exchange.client-threads" in props:
        n = int(props["exchange.client-threads"])
        if n < 1:
            raise ValueError(f"exchange.client-threads must be >= 1, got {n}")
        kw["exchange_client_threads"] = n
    if "exchange.max-buffer-size" in props:
        kw["exchange_max_buffer_bytes"] = parse_data_size(
            props["exchange.max-buffer-size"])
    if "exchange.fabric" in props:
        from ..parallel.fabric import FABRICS
        fabric = props["exchange.fabric"].strip().lower()
        if fabric not in FABRICS:
            raise ValueError(
                f"exchange.fabric must be one of {FABRICS}, got {fabric!r}")
        kw["exchange_fabric"] = fabric
    if "exchange.ici-chunk-rows" in props:
        # an EXPLICIT property pins the chunk size and must be a real
        # row count; auto-tuning is requested by OMITTING the key (the
        # ExecutionConfig default of 0)
        n = int(props["exchange.ici-chunk-rows"])
        if n < 1:
            raise ValueError(
                f"exchange.ici-chunk-rows must be >= 1, got {n}")
        kw["ici_chunk_rows"] = n
    if "scan.kernel" in props:
        from ..exec.pipeline import SCAN_KERNEL_MODES
        mode = props["scan.kernel"].strip().lower()
        if mode not in SCAN_KERNEL_MODES:
            raise ValueError(
                f"scan.kernel must be one of {SCAN_KERNEL_MODES}, "
                f"got {mode!r}")
        kw["scan_kernel"] = mode
    if "scan.kernel-dma" in props:
        from ..exec.pipeline import SCAN_KERNEL_DMA_MODES
        mode = props["scan.kernel-dma"].strip().lower()
        if mode not in SCAN_KERNEL_DMA_MODES:
            raise ValueError(
                f"scan.kernel-dma must be one of {SCAN_KERNEL_DMA_MODES}, "
                f"got {mode!r}")
        kw["scan_kernel_dma"] = mode
    if "exchange.max-response-size" in props:
        kw["exchange_max_response_bytes"] = parse_data_size(
            props["exchange.max-response-size"])
    if "task.remote-task-retry-attempts" in props:
        kw["remote_task_retry_attempts"] = int(
            props["task.remote-task-retry-attempts"])
    if "task.fault-injection-probability" in props:
        p = float(props["task.fault-injection-probability"])
        if not 0.0 <= p <= 1.0:
            raise ValueError(
                f"task.fault-injection-probability must be in [0, 1], "
                f"got {p}")
        kw["fault_injection_probability"] = p
    if "task.plan-validation" in props:
        mode = props["task.plan-validation"].strip().lower()
        from ..analysis import VALIDATION_MODES
        if mode not in VALIDATION_MODES:
            raise ValueError(
                f"task.plan-validation must be one of {VALIDATION_MODES}, "
                f"got {mode!r}")
        kw["plan_validation"] = mode
    if "debug.lock-validation" in props:
        kw["lock_validation"] = _bool(props["debug.lock-validation"])
    if "telemetry.profile-dir" in props:
        kw["profile_dir"] = props["telemetry.profile-dir"]
    if "retry-policy" in props:
        from ..exec.pipeline import RETRY_POLICY_MODES
        mode = props["retry-policy"].strip().lower()
        if mode not in RETRY_POLICY_MODES:
            raise ValueError(
                f"retry-policy must be one of {RETRY_POLICY_MODES}, "
                f"got {mode!r}")
        kw["retry_policy"] = mode
    if "query.max-execution-time" in props:
        kw["query_max_execution_time_s"] = parse_duration(
            props["query.max-execution-time"])
    if props.get("spool.path"):
        kw["spool_path"] = props["spool.path"]
    if "spool.staging-budget-bytes" in props:
        kw["spool_staging_budget_bytes"] = parse_data_size(
            props["spool.staging-budget-bytes"])
    if "failure-detector.heartbeat-timeout" in props:
        kw["failure_detector_heartbeat_timeout_s"] = parse_duration(
            props["failure-detector.heartbeat-timeout"])
    return dataclasses.replace(cfg, **kw) if kw else cfg


class SystemConfig:
    """Typed accessors over config.properties — the shape of the native
    worker's SystemConfig (presto_cpp/main/common/Configs.h:162: every key
    is a named constant with a typed default; unknown keys are tolerated).
    Defaults mirror Configs.cpp where the key has a reference default.

    Keys the engine acts on are ALSO mapped into ExecutionConfig /
    WorkerServer kwargs (execution_config_from_properties /
    server_kwargs_from_etc); this accessor is the full config surface a
    deployment reads and the /v1/info plumbing reports."""

    # (key, type, default) — Configs.h:164-420 names
    KEYS = [
        ("presto.version", str, "presto-tpu-0.1"),
        ("http-server.http.port", int, 8080),
        ("http-server.reuse-port", bool, False),
        ("http-server.bind-to-node-internal-address-only-enabled",
         bool, False),
        ("http-server.https.port", int, 8443),
        ("http-server.https.enabled", bool, False),
        ("https-cert-path", str, ""),
        ("https-key-path", str, ""),
        ("internal-communication.https.trust-store-path", str, ""),
        ("discovery.uri", str, ""),
        ("coordinator", bool, False),
        ("node.environment", str, "test"),
        ("node.id", str, ""),
        ("node.location", str, ""),
        ("node.pool", str, "DEFAULT"),               # NodePoolType.java
        ("task.max-drivers-per-task", int, 16),
        ("task.concurrent-lifespans-per-task", int, 1),
        ("task.writer-count", int, 1),
        ("task.partitioned-writer-count", int, 1),
        ("task.max-partial-aggregation-memory", str, "16MB"),
        ("task.batch-rows", int, 1 << 16),
        ("task.fuse-pipelines", bool, True),
        ("task.grouped-lifespans", int, 0),
        ("task.grouped-prefetch-depth", int, 1),
        ("task.grouped-lifespan-sharding", bool, True),
        ("task.remote-task-retry-attempts", int, 2),
        # fault-tolerant execution: task-granular retry over the durable
        # spooled exchange (worker/spooling.py)
        ("retry-policy", str, "query"),          # query | task
        ("query.max-execution-time", str, ""),   # "" = unbounded
        ("spool.path", str, ""),                 # "" = spill.path
        ("spool.staging-budget-bytes", str, "16MB"),
        ("failure-detector.heartbeat-timeout", str, ""),  # "" = streak only
        ("task.fault-injection-probability", float, 0.0),
        ("task.plan-validation", str, "on"),
        # runtime lock-order validation (common/locks.py): worker-wide
        # base flag; sessions compose per-query scopes on top
        ("debug.lock-validation", bool, False),
        ("shutdown-onset-sec", int, 10),
        ("system-memory-gb", int, 16),               # HBM per chip
        ("system-mem-limit-gb", int, 16),
        ("system-mem-pushback-enabled", bool, False),
        ("query.max-memory-per-node", str, ""),
        ("query.max-memory", str, ""),           # typed EXCEEDED_MEMORY_LIMIT
        ("memory.max-query-bytes", str, ""),     # byte-count alias of above
        ("experimental.spill-enabled", bool, True),
        ("experimental.spiller-spill-path", str, ""),
        ("experimental.spiller-max-used-space", str, "8GB"),
        ("spill.path", str, ""),                 # alias of spiller-spill-path
        ("spill.host-budget-bytes", str, ""),    # alias of max-used-space
        ("spill.async-staging", bool, True),
        ("exchange.compression-enabled", bool, False),
        ("exchange.compression-codec", str, "LZ4"),
        ("exchange.http-client.request-timeout", str, "10s"),
        ("exchange.max-error-duration", str, "1m"),
        ("exchange.client-threads", int, 4),
        ("exchange.max-buffer-size", str, "32MB"),
        ("exchange.max-response-size", str, "1MB"),
        # shuffle fabric selection + ICI chunk granularity
        # (parallel/fabric.py; exec/scheduler.py _ici_exchange)
        ("exchange.fabric", str, "auto"),
        # 0 = auto-tune from the observed compute/collective overlap
        # (parallel/fabric.py IciChunkTuner); explicit values pin it
        ("exchange.ici-chunk-rows", int, 0),
        # Pallas fused scan kernel selection (exec/kernels): also
        # gates the in-kernel join probe (kernels/join.py) and the
        # prefix-scan window kernel (kernels/window.py)
        ("scan.kernel", str, "auto"),
        # kernel block staging: single (BlockSpec streaming) or double
        # (manually double-buffered make_async_copy prefetch)
        ("scan.kernel-dma", str, "single"),
        ("announcement-interval-ms", int, 1000),
        ("heartbeat-interval-ms", int, 1000),
        ("async-data-cache-enabled", bool, False),
        ("enable-serialized-page-checksum", bool, True),
        ("native-sidecar", bool, False),
        ("worker-overloaded-threshold-mem-gb", int, 0),
        ("worker-overloaded-threshold-cpu-pct", int, 0),
        ("worker-overloaded-task-queuing-enabled", bool, False),
        ("register-test-functions", bool, False),
        ("system-metrics-collection-enabled", bool, False),
        ("internal-communication.shared-secret", str, ""),
        ("internal-communication.jwt.enabled", bool, False),
        ("internal-communication.jwt.expiration-seconds", int, 300),
        # serving tier (coordinator role): canonical plan/executable cache
        # and fair-share admission (presto_tpu/serving/)
        ("serving.plan-cache-entries", int, 128),
        ("serving.total-concurrency", int, 0),       # 0 = per-group only
        ("serving.admission-headroom-fraction", float, 0.8),
        # micro-batched point-query execution (serving/batching.py):
        # concurrent same-template EXECUTEs collapse into one launch
        ("serving.batch-window-ms", float, 3.0),
        ("serving.max-batch-size", int, 16),         # 1 = batching off
        # persistent executable cache (serving/persist.py): XLA
        # compilation cache dir + plan-cache sidecar for warm restarts
        ("serving.compilation-cache-dir", str, ""),
        ("serving.plan-cache-path", str, ""),
        # telemetry export pipeline + query history + device profiler
        # (presto_tpu/telemetry/)
        ("telemetry.sink", str, "none"),         # none|jsonl|http|collector
        ("telemetry.path", str, ""),             # jsonl sink spool file
        ("telemetry.otlp-endpoint", str, ""),    # http sink collector base
        ("telemetry.flush-interval", str, "200ms"),
        ("telemetry.queue-bound", int, 256),
        ("telemetry.metrics-interval", str, "0s"),  # 0 = no self-scrape
        ("telemetry.history-path", str, ""),     # "" = in-memory history
        ("telemetry.history-max-count", int, 200),
        ("telemetry.history-max-age", str, ""),  # "" = no age bound
        ("telemetry.profile-dir", str, "/tmp/presto_tpu_profiles"),
    ]

    def __init__(self, props: Optional[Dict[str, str]] = None):
        self._props = dict(props or {})
        self._defaults = {k: d for k, _t, d in self.KEYS}
        self._types = {k: t for k, t, _d in self.KEYS}

    def known_keys(self):
        return sorted(self._defaults)

    def get(self, key: str):
        if key not in self._defaults:
            raise KeyError(f"unknown config key {key!r}")
        raw = self._props.get(key)
        if raw is None:
            return self._defaults[key]
        t = self._types[key]
        if t is bool:
            return _bool(raw)
        return t(raw)

    def to_dict(self) -> Dict[str, object]:
        return {k: self.get(k) for k in self.known_keys()}


def server_kwargs_from_etc(etc_dir: str) -> Tuple[dict, Dict[str, str]]:
    """etc/{config,node}.properties -> WorkerServer kwargs + raw props.

    Returns (kwargs, merged_props).  Catalog mounts are handled by
    register_catalogs_from_etc (import side effects live there)."""
    config_path = os.path.join(etc_dir, "config.properties")
    node_path = os.path.join(etc_dir, "node.properties")
    props: Dict[str, str] = {}
    if os.path.exists(config_path):
        props.update(load_properties(config_path))
    if os.path.exists(node_path):
        props.update(load_properties(node_path))

    kwargs: dict = {}
    if "http-server.http.port" in props:
        kwargs["port"] = int(props["http-server.http.port"])
    if "node.id" in props:
        kwargs["node_id"] = props["node.id"]
    if "node.environment" in props:
        kwargs["environment"] = props["node.environment"]
    if "coordinator" in props:
        kwargs["coordinator"] = _bool(props["coordinator"])
    if "discovery.uri" in props:
        kwargs["discovery_uri"] = props["discovery.uri"]
    if "announcement-interval-ms" in props:
        kwargs["announce_interval_s"] = \
            int(props["announcement-interval-ms"]) / 1000.0
    if _bool(props.get("http-server.https.enabled", "false")):
        kwargs["https_cert_path"] = props.get("https-cert-path")
        kwargs["https_key_path"] = props.get("https-key-path")
        if not kwargs["https_cert_path"]:
            raise ValueError(
                "http-server.https.enabled requires https-cert-path")
    if props.get("internal-communication.https.trust-store-path"):
        # applied by WorkerServer.__init__ (a parse must not mutate
        # process-global SSL state)
        kwargs["internal_ca_path"] = \
            props["internal-communication.https.trust-store-path"]
    if _bool(props.get("internal-communication.jwt.enabled", "false")):
        kwargs["jwt_enabled"] = True
        kwargs["jwt_secret"] = props.get(
            "internal-communication.shared-secret", "")
        if "internal-communication.jwt.expiration-seconds" in props:
            kwargs["jwt_expiration_s"] = int(
                props["internal-communication.jwt.expiration-seconds"])
    if "serving.plan-cache-entries" in props:
        kwargs["plan_cache_entries"] = int(
            props["serving.plan-cache-entries"])
    if "serving.total-concurrency" in props:
        n = int(props["serving.total-concurrency"])
        kwargs["total_concurrency"] = n if n > 0 else None
    if "serving.admission-headroom-fraction" in props:
        f = float(props["serving.admission-headroom-fraction"])
        if not 0.0 < f <= 1.0:
            raise ValueError(
                "serving.admission-headroom-fraction must be in (0, 1], "
                f"got {f}")
        kwargs["admission_headroom_fraction"] = f
    if "serving.batch-window-ms" in props:
        w = float(props["serving.batch-window-ms"])
        if w < 0:
            raise ValueError(
                f"serving.batch-window-ms must be >= 0, got {w}")
        kwargs["batch_window_ms"] = w
    if "serving.max-batch-size" in props:
        n = int(props["serving.max-batch-size"])
        if n < 1:
            raise ValueError(
                f"serving.max-batch-size must be >= 1, got {n}")
        kwargs["max_batch_size"] = n
    if props.get("serving.compilation-cache-dir"):
        kwargs["compilation_cache_dir"] = \
            props["serving.compilation-cache-dir"]
    if props.get("serving.plan-cache-path"):
        kwargs["plan_cache_path"] = props["serving.plan-cache-path"]
    # telemetry export + history (presto_tpu/telemetry/)
    if "telemetry.sink" in props:
        kwargs["telemetry_sink"] = props["telemetry.sink"]
    if "telemetry.path" in props:
        kwargs["telemetry_path"] = props["telemetry.path"]
    if "telemetry.otlp-endpoint" in props:
        kwargs["telemetry_endpoint"] = props["telemetry.otlp-endpoint"]
    if "telemetry.flush-interval" in props:
        kwargs["telemetry_flush_interval_s"] = parse_duration(
            props["telemetry.flush-interval"])
    if "telemetry.queue-bound" in props:
        n = int(props["telemetry.queue-bound"])
        if n < 1:
            raise ValueError(
                f"telemetry.queue-bound must be >= 1, got {n}")
        kwargs["telemetry_queue_bound"] = n
    if "telemetry.metrics-interval" in props:
        kwargs["telemetry_metrics_interval_s"] = parse_duration(
            props["telemetry.metrics-interval"])
    if "telemetry.history-path" in props:
        kwargs["history_path"] = props["telemetry.history-path"]
    if "telemetry.history-max-count" in props:
        kwargs["history_max_count"] = int(
            props["telemetry.history-max-count"])
    if props.get("telemetry.history-max-age"):
        kwargs["history_max_age_s"] = parse_duration(
            props["telemetry.history-max-age"])
    # base on the server's tuned defaults (WorkerServer.__init__), not the
    # bare ExecutionConfig — file keys override, absence must not detune
    kwargs["config"] = execution_config_from_properties(
        props, base=tuned_config())
    return kwargs, props


def register_catalogs_from_etc(etc_dir: str) -> Dict[str, str]:
    """Mount every etc/catalog/*.properties connector (CatalogManager
    analog): connector.name picks the connector; returns
    {catalog_name: connector.name} for what was mounted."""
    from ..connectors import catalog as registry
    catalog_dir = os.path.join(etc_dir, "catalog")
    mounted: Dict[str, str] = {}
    if not os.path.isdir(catalog_dir):
        return mounted
    for fn in sorted(os.listdir(catalog_dir)):
        if not fn.endswith(".properties"):
            continue
        name = fn[:-len(".properties")]
        props = load_properties(os.path.join(catalog_dir, fn))
        kind = props.get("connector.name", "")
        if kind == "hive" or kind == "hive-hadoop2":
            from ..connectors import hive
            warehouse = props.get("hive.warehouse.dir",
                                  os.path.join(etc_dir, "warehouse"))
            registry.register_connector(
                name, hive.HiveConnector(
                    warehouse,
                    storage_format=props.get("hive.storage-format",
                                             "PARQUET").upper()))
        elif kind == "memory":
            from ..connectors.memory import MemoryConnector
            registry.register_connector(name, MemoryConnector())
        elif kind == "blackhole":
            from ..connectors.memory import BlackholeConnector
            registry.register_connector(name, BlackholeConnector())
        elif kind in ("tpch", "tpcds"):
            pass  # built-in generated catalogs are always mounted
        else:
            raise ValueError(
                f"catalog {name}: unknown connector.name {kind!r}")
        mounted[name] = kind
    return mounted
