"""Internal-communication JWT authentication.

The analog of the native worker's InternalAuthenticationFilter
(presto_cpp/main/http/filters/InternalAuthenticationFilter.cpp): every
internal request carries an HS256 JWT in the `X-Presto-Internal-Bearer`
header (HttpConstants.h:29); the signing key is SHA256(shared secret)
(InternalAuthenticationFilter.cpp:133-144), the subject claim is the
sender's nodeId and must be non-empty (:147-152), and the filter's
decision table is exactly the reference's:

  token present, JWT disabled  -> 401 (misconfiguration surface)
  token absent,  JWT enabled   -> 401
  token absent,  JWT disabled  -> pass
  token present, JWT enabled   -> verify signature + exp + subject

Config keys (Configs.h:711-717): internal-communication.jwt.enabled,
internal-communication.shared-secret,
internal-communication.jwt.expiration-seconds.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
from typing import Optional

BEARER_HEADER = "X-Presto-Internal-Bearer"
DEFAULT_EXPIRATION_S = 300


class AuthError(ValueError):
    pass


def _b64url(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()


def _b64url_decode(text: str) -> bytes:
    pad = -len(text) % 4
    return base64.urlsafe_b64decode(text + "=" * pad)


def _signing_key(secret: str) -> bytes:
    # the reference signs with SHA256(shared secret), not the raw secret
    return hashlib.sha256(secret.encode()).digest()


def jwt_encode(secret: str, subject: str,
               expiration_s: int = DEFAULT_EXPIRATION_S) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"},
                                separators=(",", ":")).encode())
    now = int(time.time())
    payload = _b64url(json.dumps(
        {"sub": subject, "iat": now, "exp": now + expiration_s},
        separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(_signing_key(secret), signing_input,
                   hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def jwt_verify(token: str, secret: str) -> dict:
    """Signature + exp + non-empty subject, reference decision order.
    Returns the claims on success; raises AuthError otherwise."""
    parts = token.split(".")
    if len(parts) != 3:
        raise AuthError("malformed token")
    header_b64, payload_b64, sig_b64 = parts
    try:
        header = json.loads(_b64url_decode(header_b64))
        payload = json.loads(_b64url_decode(payload_b64))
        sig = _b64url_decode(sig_b64)
    except (ValueError, json.JSONDecodeError) as e:
        raise AuthError(f"undecodable token: {e}") from e
    if not isinstance(header, dict) or not isinstance(payload, dict):
        raise AuthError("malformed token segments")
    if header.get("alg") != "HS256":
        raise AuthError(f"unsupported alg {header.get('alg')!r}")
    expect = hmac.new(_signing_key(secret),
                      f"{header_b64}.{payload_b64}".encode(),
                      hashlib.sha256).digest()
    if not hmac.compare_digest(sig, expect):
        raise AuthError("signature verification failed")
    exp = payload.get("exp")
    if exp is not None and time.time() > float(exp):
        raise AuthError("token expired")
    if not payload.get("sub"):
        raise AuthError("missing subject (sender nodeId)")
    return payload


class InternalAuth:
    """Per-node auth context: validates inbound bearers and mints
    outbound ones (token cached until near expiry, the way the Java
    JsonWebTokenManager reuses tokens)."""

    def __init__(self, enabled: bool, secret: str, node_id: str,
                 expiration_s: int = DEFAULT_EXPIRATION_S):
        if enabled and not secret:
            raise AuthError(
                "internal-communication.jwt.enabled requires "
                "internal-communication.shared-secret")
        self.enabled = enabled
        self.secret = secret
        self.node_id = node_id
        self.expiration_s = expiration_s
        self._lock = threading.Lock()
        self._token: Optional[str] = None
        self._token_exp = 0.0

    def check_inbound(self, token: Optional[str]):
        """Reference decision table; returns None on pass or an error
        string for a 401."""
        if token and not self.enabled:
            return "bearer token present but JWT is not enabled"
        if not token and self.enabled:
            return "missing internal bearer token"
        if not token:
            return None
        try:
            jwt_verify(token, self.secret)
        except AuthError as e:
            return str(e)
        return None

    def outbound_token(self) -> Optional[str]:
        if not self.enabled:
            return None
        with self._lock:
            now = time.time()
            if self._token is None or now > self._token_exp - 30:
                self._token = jwt_encode(self.secret, self.node_id,
                                         self.expiration_s)
                self._token_exp = now + self.expiration_s
            return self._token


_DISABLED = InternalAuth(False, "", "")
_PROCESS_AUTH = _DISABLED


def set_process_auth(auth: "InternalAuth") -> None:
    """Install the process-wide outbound auth context (the cluster's
    shared secret is one per deployment, so every in-process node shares
    it — matching the reference's single SystemConfig)."""
    global _PROCESS_AUTH
    _PROCESS_AUTH = auth


def clear_process_auth(auth: "InternalAuth") -> None:
    """Uninstall `auth` iff it is the installed context (a shut-down
    JWT server must not leave later plain clusters sending stale
    bearers)."""
    global _PROCESS_AUTH
    if _PROCESS_AUTH is auth:
        _PROCESS_AUTH = _DISABLED


def outbound_headers() -> dict:
    tok = _PROCESS_AUTH.outbound_token()
    return {BEARER_HEADER: tok} if tok else {}


_SSL_CONTEXT = [None]


def set_internal_ca(ca_path: Optional[str]) -> None:
    """Trust anchor for internal HTTPS calls (the deployment's internal
    CA; reference https-supported-ciphers/cert plumbing).  None resets
    to library defaults."""
    import ssl
    if ca_path is None:
        _SSL_CONTEXT[0] = None
    else:
        ctx = ssl.create_default_context(cafile=ca_path)
        # internal certs are issued per deployment, often for node ids
        # rather than hostnames — the secret/JWT layer authenticates the
        # PEER; TLS provides transport privacy
        ctx.check_hostname = False
        _SSL_CONTEXT[0] = ctx


def urlopen_internal(req, timeout: float):
    """urlopen with the internal CA context when configured."""
    import urllib.request
    ctx = _SSL_CONTEXT[0]
    if ctx is not None:
        return urllib.request.urlopen(req, timeout=timeout, context=ctx)
    return urllib.request.urlopen(req, timeout=timeout)
