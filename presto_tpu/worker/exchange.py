"""HTTP exchange client: pulls SerializedPages from upstream task buffers.

The analog of the reference's ExchangeClient/PageBufferClient
(presto-main-base/.../operator/ExchangeClient.java:72) and the native
PrestoExchangeSource (presto_cpp/main/PrestoExchangeSource.cpp:171).

Two layers:

  * `pull_pages` — the per-location protocol loop: GET {location}/{token}
    -> acknowledge -> repeat until the complete flag, then DELETE the
    buffer.  Transient transport failures RESUME from the last delivered
    token under an exponential-backoff-with-jitter loop bounded by a real
    error budget (reference exchange.max-error-duration).  When the budget
    expires — or the producer task vanishes outright (404) — a typed
    ExchangeLostError carries the producer location upward so the
    coordinator can map it back to the producing task and retry that task
    instead of failing the query.

  * `ExchangeClient` — the concurrent consumer: one puller per upstream
    location (capped by exchange.client-threads), each running the
    protocol loop above with its OWN token/backoff state, feeding a single
    bounded arrival-order queue (exchange.max-buffer-size bytes).  Pullers
    park when the buffer is full (producer backpressure), acknowledges are
    fire-and-forget on a separate thread, and page deserialization/LZ4
    decode happens IN the puller threads — so decode parallelizes across
    producers and the consuming pipeline computes on page k while pages
    k+1... are in flight.  Every puller sends an X-Presto-Max-Size cap so
    producers coalesce tiny pages into ~max-response-size bodies.

Fault-tolerance semantics are unchanged under concurrency: per-location
token resume, 404/410 -> ExchangeLostError (producer lineage), 500 ->
RemoteTaskError with the producer's [ERROR_TYPE] tag, and exactly-once via
replayable retained buffers (a restarted consumer re-creates the client
and replays every location from token 0).
"""
from __future__ import annotations

import collections
import queue
import random
import re
import struct
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Iterator, List, Optional

from ..common.errors import (ExchangeLostError, RemoteTaskError,
                             is_retryable_type, parse_error_type)
from ..common.locks import OrderedCondition, OrderedLock
from ..common.page import Page
from ..common.serde import DEFAULT_CODEC, deserialize_page, deserialize_pages

DEFAULT_MAX_WAIT_S = 1.0
REQUEST_TIMEOUT_S = 30.0
DEFAULT_MAX_ERROR_DURATION_S = 60.0
DEFAULT_CLIENT_THREADS = 4            # exchange.client-threads
DEFAULT_MAX_BUFFER_BYTES = 32 << 20   # exchange.max-buffer-size
DEFAULT_MAX_RESPONSE_BYTES = 1 << 20  # exchange.max-response-size
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 2.0

_PAGE_HEADER = struct.Struct("<ibiiq")


class ExchangeAbortedError(RuntimeError):
    """Raised through should_abort when the consuming task is already
    terminal: the pull must stop, not drain a doomed query."""


class _Stop(BaseException):
    """Internal puller-thread unwind on client close (BaseException so it
    cannot be swallowed by a broad `except Exception`)."""


class _Relocate(BaseException):
    """Internal puller unwind when a location was superseded by a task
    retry (update_locations): the puller re-resolves the location and
    resumes the SAME stream at its delivered token."""

    def __init__(self, location: str):
        self.location = location


# buffer identity inside a results location:
# http://host:port/v1/task/{taskId}/results/{bufferId}
_LOCATION_KEY = re.compile(r"/v1/task/([^/\s]+)/results/(\d+)")
_RETRY_SUFFIX = re.compile(r"\.r\d+$")


def _location_key(location: str):
    """(base task lineage, buffer id) — stable across retry attempts, so
    an old attempt's location matches its replacement's."""
    m = _LOCATION_KEY.search(location)
    if not m:
        return location
    return _RETRY_SUFFIX.sub("", m.group(1)), m.group(2)


def _request(url: str, method: str = "GET",
             timeout: float = REQUEST_TIMEOUT_S, headers: dict = None):
    from .auth import outbound_headers, urlopen_internal
    h = outbound_headers()
    if headers:
        h.update(headers)
    req = urllib.request.Request(url, method=method, headers=h)
    return urlopen_internal(req, timeout=timeout)


class ExchangeMetrics:
    """Process-wide exchange counters for /v1/metrics (one worker per
    process in deployment; tests reset() before asserting).  The buffered
    gauge aggregates across every live ExchangeClient in the process, so
    its peak proves backpressure actually bounded resident bytes."""

    def __init__(self):
        # rank 100: metrics registries are leaf locks
        self._lock = OrderedLock("metrics:exchange", 100)  # lint: guarded-by(_lock)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.pages = 0
            self.bytes = 0                # wire (possibly compressed) bytes
            self.uncompressed_bytes = 0
            self.responses = 0
            self.pull_wall_s = 0.0        # HTTP request walls, all pullers
            self.decode_wall_s = 0.0      # deserialize/decompress walls
            self.wait_wall_s = 0.0        # consumer blocked on empty buffer
            self.drain_wall_s = 0.0       # client open -> close
            self.buffered_bytes = 0
            self.buffered_bytes_peak = 0
            self.clients = 0

    def on_page(self, nbytes: int, uncompressed: int,
                decode_wall_s: float) -> None:
        with self._lock:
            self.pages += 1
            self.bytes += nbytes
            self.uncompressed_bytes += uncompressed
            self.decode_wall_s += decode_wall_s

    def on_response(self, wall_s: float) -> None:
        with self._lock:
            self.responses += 1
            self.pull_wall_s += wall_s

    def buffered_delta(self, delta: int) -> None:
        with self._lock:
            self.buffered_bytes += delta
            if self.buffered_bytes > self.buffered_bytes_peak:
                self.buffered_bytes_peak = self.buffered_bytes

    def on_client_close(self, wait_wall_s: float, drain_wall_s: float
                        ) -> None:
        with self._lock:
            self.clients += 1
            self.wait_wall_s += wait_wall_s
            self.drain_wall_s += drain_wall_s

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "pages": self.pages, "bytes": self.bytes,
                "uncompressed_bytes": self.uncompressed_bytes,
                "responses": self.responses,
                "pull_wall_s": self.pull_wall_s,
                "decode_wall_s": self.decode_wall_s,
                "wait_wall_s": self.wait_wall_s,
                "drain_wall_s": self.drain_wall_s,
                "buffered_bytes": self.buffered_bytes,
                "buffered_bytes_peak": self.buffered_bytes_peak,
                "clients": self.clients,
            }


EXCHANGE_METRICS = ExchangeMetrics()


def _pull_rounds(location: str,
                 max_error_duration_s: float = DEFAULT_MAX_ERROR_DURATION_S,
                 should_abort: Optional[Callable[[], None]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 max_response_bytes: Optional[int] = None,
                 acknowledge: Optional[Callable[[str], None]] = None,
                 on_round: Optional[Callable[[float], None]] = None,
                 start_token: int = 0,
                 park_on_failure: bool = False,
                 on_token: Optional[Callable[[int], None]] = None,
                 ) -> Iterator[bytes]:
    """The per-location protocol loop, yielding each non-empty response
    BODY (one or more concatenated SerializedPages).  Handles token
    resume, the budgeted jittered backoff, acknowledges (via the
    `acknowledge` callback when given, else inline best-effort), and the
    final DELETE.  `sleep` is injectable so a closing client can interrupt
    a backoff wait.

    `start_token` resumes a relocated stream mid-way (task retry under
    retry-policy=task: the replacement attempt replays the same durable
    spool, so tokens line up).  With `park_on_failure` a RETRYABLE
    producer failure (500 with a retryable [ERROR_TYPE], 404/410 task
    loss) downgrades to the budgeted backoff instead of raising — the
    coordinator will replace the producer and redirect this pull, so the
    consumer survives the producer's death (fault-tolerant mode's
    decoupled lifetimes).  Non-retryable producer errors still propagate
    immediately."""
    token = start_token
    error_since: Optional[float] = None
    attempt = 0
    extra = ({"X-Presto-Max-Size": str(int(max_response_bytes))}
             if max_response_bytes else None)
    while True:
        if should_abort is not None:
            should_abort()
        url = f"{location}/{token}?maxWaitMs={int(DEFAULT_MAX_WAIT_S * 1000)}"
        t0 = time.perf_counter()
        try:
            with _request(url, headers=extra) as resp:
                complete = resp.headers.get(
                    "X-Presto-Buffer-Complete", "false") == "true"
                # reference name first (PrestoHeaders.PRESTO_PAGE_NEXT_TOKEN
                # = X-Presto-Page-End-Sequence-Id), repo alias as fallback
                next_token = int(
                    resp.headers.get("X-Presto-Page-End-Sequence-Id")
                    or resp.headers.get("X-Presto-Page-Next-Token", token))
                body = resp.read()
            error_since, attempt = None, 0
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code in (404, 410):
                if park_on_failure:
                    # the producer attempt is gone but a replacement is
                    # coming: wait (budgeted) for the redirect
                    error_since, attempt = _backoff(
                        location, token, error_since, attempt,
                        max_error_duration_s, e, sleep=sleep)
                    continue
                # the producer task is GONE (worker restarted and lost its
                # task registry): not transient — the task must be rebuilt
                raise ExchangeLostError(
                    location, token,
                    f"exchange source {location} vanished ({e.code}) at "
                    f"token {token}: producer task lost") from e
            if e.code == 503:
                # draining/overloaded producer: transient, budgeted retry
                error_since, attempt = _backoff(
                    location, token, error_since, attempt,
                    max_error_duration_s, e, sleep=sleep)
                continue
            if (park_on_failure
                    and is_retryable_type(parse_error_type(detail))):
                # retryable producer failure under retry-policy=task: the
                # coordinator retries THAT task alone; this consumer parks
                # and resumes against the replacement attempt
                error_since, attempt = _backoff(
                    location, token, error_since, attempt,
                    max_error_duration_s, e, sleep=sleep)
                continue
            # 500 carries a producer-side failure: propagate typed (the
            # [ERROR_TYPE] tag in the detail decides retryability upstream)
            raise RemoteTaskError(location, detail) from e
        except (urllib.error.URLError, TimeoutError, ConnectionError,
                OSError) as e:
            error_since, attempt = _backoff(
                location, token, error_since, attempt,
                max_error_duration_s, e, sleep=sleep)
            continue
        if on_round is not None:
            on_round(time.perf_counter() - t0)
        if body:
            yield body
        if next_token != token:
            ack_url = f"{location}/{next_token}/acknowledge"
            if acknowledge is not None:
                acknowledge(ack_url)     # fire-and-forget (ack thread)
            else:
                try:
                    _request(ack_url).close()
                except (urllib.error.URLError, TimeoutError, OSError):
                    pass  # acknowledge is an optimization; pull re-fetches
            token = next_token
            if on_token is not None:
                on_token(next_token)
        if complete:
            try:
                _request(location, method="DELETE").close()
            except (urllib.error.URLError, TimeoutError, OSError):
                pass
            return


def pull_pages(location: str, codec: str = DEFAULT_CODEC,
               max_error_duration_s: float = DEFAULT_MAX_ERROR_DURATION_S,
               should_abort: Optional[Callable[[], None]] = None,
               max_response_bytes: Optional[int] = None
               ) -> Iterator[Page]:
    """Stream every page from one upstream buffer location
    (http://host:port/v1/task/{taskId}/results/{bufferId}), sequentially.
    `codec` decodes COMPRESSED pages; it is cluster config shared with the
    producer, like the reference exchange.compression-codec.

    `should_abort` is polled once per pull round (it raises to abort).
    This is the single-location building block; multi-location consumers
    use ExchangeClient for concurrency + bounded buffering."""
    for body in _pull_rounds(location,
                             max_error_duration_s=max_error_duration_s,
                             should_abort=should_abort,
                             max_response_bytes=max_response_bytes):
        for page in deserialize_pages(body, codec=codec):
            yield page


def _backoff(location: str, token: int, error_since: Optional[float],
             attempt: int, max_error_duration_s: float,
             cause: Exception,
             sleep: Callable[[float], None] = time.sleep) -> tuple:
    """One budgeted retry step: raise ExchangeLostError once errors have
    persisted past the budget, else sleep exp-backoff + jitter (reference
    PageBufferClient backoff under exchange.max-error-duration)."""
    now = time.monotonic()
    if error_since is None:
        error_since = now
    if now - error_since >= max_error_duration_s:
        raise ExchangeLostError(
            location, token,
            f"exchange source {location} unreachable for "
            f"{now - error_since:.1f}s (budget {max_error_duration_s}s) "
            f"at token {token}: {cause}") from cause
    delay = min(_BACKOFF_CAP_S, _BACKOFF_BASE_S * (2 ** attempt))
    # full jitter keeps a fleet of consumers from re-probing in lockstep
    sleep(delay * (0.5 + random.random() * 0.5))
    return error_since, attempt + 1


class ExchangeClient:
    """Concurrent multi-location exchange consumer (ExchangeClient.java:72
    shape): `pages()` yields decoded pages in ARRIVAL order across all
    locations while puller threads keep the bounded buffer full.

    Backpressure: a puller parks before enqueueing a page that would push
    buffered bytes past `max_buffer_bytes` (a page is always admitted into
    an EMPTY buffer so one oversized page cannot deadlock the stream) —
    so resident bytes stay <= max(max_buffer_bytes, largest page).

    Errors from any puller (ExchangeLostError / RemoteTaskError / whatever
    `should_abort` raises) surface on the consumer immediately — a stalled
    sibling location cannot delay failure propagation."""

    def __init__(self, locations: List[str], codec: str = DEFAULT_CODEC,
                 max_error_duration_s: float = DEFAULT_MAX_ERROR_DURATION_S,
                 should_abort: Optional[Callable[[], None]] = None,
                 client_threads: int = DEFAULT_CLIENT_THREADS,
                 max_buffer_bytes: int = DEFAULT_MAX_BUFFER_BYTES,
                 max_response_bytes: int = DEFAULT_MAX_RESPONSE_BYTES,
                 stats=None, park_on_failure: bool = False):
        self._codec = codec
        self._max_error_s = max_error_duration_s
        self._should_abort = should_abort
        self._park = park_on_failure
        # task-retry redirection (update_locations): old location -> new,
        # plus the delivered-token high-water mark per live location so a
        # redirected pull resumes instead of replaying delivered pages
        self._redirect: Dict[str, str] = {}
        self._loc_tokens: Dict[str, int] = {}
        self._max_buffer = max(1, int(max_buffer_bytes))
        self._max_response = int(max_response_bytes) or None
        self._stats = stats               # utils.runtime_stats.RuntimeStats
        # rank 18: the exchange buffer lock nests only into the metrics
        # leaves; pullers and the consumer hold nothing above it
        self._cond = OrderedCondition(
            "exchange-client", 18)  # lint: guarded-by(_cond)
        self._queue: "collections.deque" = collections.deque()
        self._buffered = 0
        self._buffered_peak = 0
        self._remaining = len(locations)  # locations not yet complete
        self._error: Optional[BaseException] = None
        self._closed = False
        self._stop_event = threading.Event()
        # client-level counters (flushed into `stats` at close)
        self._pull_wall = 0.0
        self._decode_wall = 0.0
        self._wait_wall = 0.0
        self._pages = 0
        self._bytes = 0
        self._uncompressed = 0
        self._t0 = time.perf_counter()
        self._location_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._known = set(locations)      # every location we may pull
        for loc in locations:
            self._location_q.put((loc, 0))
        self._ack_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads: List[threading.Thread] = []
        if locations:
            threading.Thread(target=self._ack_loop, daemon=True,
                             name="exchange-ack").start()
            n = max(1, min(int(client_threads), len(locations)))
            for i in range(n):
                t = threading.Thread(target=self._puller, daemon=True,
                                     name=f"exchange-puller-{i}")
                t.start()
                self._threads.append(t)

    # -- puller side -------------------------------------------------------
    def _abort_check(self) -> None:
        if self._closed or self._error is not None:
            raise _Stop()
        if self._should_abort is not None:
            self._should_abort()

    def _abort_check_loc(self, location: str) -> None:
        """Per-location round check: close/error/abort as usual, plus the
        relocation signal — a superseded location unwinds its puller so it
        can resume against the replacement attempt."""
        self._abort_check()
        with self._cond:
            if location in self._redirect:
                raise _Relocate(location)

    def _resolve_location(self, location: str) -> str:
        """Follow the redirect chain to the newest attempt's location."""
        with self._cond:
            seen = set()
            while location in self._redirect and location not in seen:
                seen.add(location)
                location = self._redirect[location]
            return location

    def update_locations(self, new_locations: List[str]) -> None:
        """Coordinator task-retry: map every known location whose (base
        lineage, buffer id) matches a replacement onto the new attempt's
        location.  Live pullers unwind via _Relocate at their next round
        and resume the stream at its delivered token; queued locations
        resolve at dequeue.  No-op for locations already current."""
        with self._cond:
            if self._closed:
                return
            by_key = {_location_key(loc): loc for loc in new_locations}
            for old in list(self._known):
                new = by_key.get(_location_key(old))
                if new is not None and new != old:
                    self._redirect[old] = new
                    self._known.add(new)
            self._cond.notify_all()

    def _sleep(self, delay: float) -> None:
        if self._stop_event.wait(delay):
            raise _Stop()

    def _on_round(self, wall_s: float) -> None:
        with self._cond:
            self._pull_wall += wall_s
        EXCHANGE_METRICS.on_response(wall_s)

    def _note_token(self, location: str, token: int) -> None:
        with self._cond:
            self._loc_tokens[location] = token

    def _puller(self) -> None:
        """Drain locations off the shared queue (cap: client_threads
        pullers active at once) until none remain; each location resumes
        from its own token with its own backoff budget.  A relocation
        (task retry) unwinds the location's pull and resumes the same
        stream against the replacement attempt at its delivered token."""
        try:
            while True:
                try:
                    loc, tok = self._location_q.get_nowait()
                except queue.Empty:
                    return
                while True:
                    loc = self._resolve_location(loc)
                    self._note_token(loc, tok)
                    try:
                        for body in _pull_rounds(
                                loc,
                                max_error_duration_s=self._max_error_s,
                                should_abort=lambda l=loc:
                                    self._abort_check_loc(l),
                                sleep=self._sleep,
                                max_response_bytes=self._max_response,
                                acknowledge=self._ack_q.put,
                                on_round=self._on_round,
                                start_token=tok,
                                park_on_failure=self._park,
                                on_token=lambda t, l=loc:
                                    self._note_token(l, t)):
                            self._decode_and_offer(body)
                        break                    # stream complete
                    except _Relocate:
                        with self._cond:
                            tok = self._loc_tokens.get(loc, tok)
                        continue                 # resume on new attempt
                with self._cond:
                    self._remaining -= 1
                    if self._remaining <= 0:
                        self._cond.notify_all()
        except _Stop:
            return
        except BaseException as e:
            self._fail(e)

    def _decode_and_offer(self, body: bytes) -> None:
        """Deserialize (and LZ4-decode) each page IN the puller thread,
        then enqueue under backpressure."""
        view = memoryview(body)
        pos, n = 0, len(view)
        while pos < n:
            _, _, uncompressed, _, _ = _PAGE_HEADER.unpack_from(view, pos)
            t0 = time.perf_counter()
            page, nxt = deserialize_page(view, pos, codec=self._codec)
            dt = time.perf_counter() - t0
            nbytes = nxt - pos
            pos = nxt
            with self._cond:
                self._decode_wall += dt
                self._uncompressed += uncompressed
            EXCHANGE_METRICS.on_page(nbytes, uncompressed, dt)
            self._offer(page, nbytes)

    def _offer(self, page: Page, nbytes: int) -> None:
        with self._cond:
            while (self._buffered
                   and self._buffered + nbytes > self._max_buffer
                   and self._error is None and not self._closed):
                self._cond.wait(0.2)     # producer backpressure: park
            if self._closed or self._error is not None:
                raise _Stop()
            self._queue.append((page, nbytes))
            self._buffered += nbytes
            if self._buffered > self._buffered_peak:
                self._buffered_peak = self._buffered
            self._pages += 1
            self._bytes += nbytes
            self._cond.notify_all()
        EXCHANGE_METRICS.buffered_delta(nbytes)

    def _fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    def _ack_loop(self) -> None:
        """Fire-and-forget acknowledges: frees producer buffer memory off
        the pull critical path (the reference sends these async too).
        The pull is BOUNDED so a lost wake token (close() racing the
        queue) can never wedge the thread past the stop flag."""
        while True:
            try:
                url = self._ack_q.get(timeout=0.5)
            except queue.Empty:
                if self._closed or self._stop_event.is_set():
                    return
                continue
            if url is None or self._closed:
                return
            try:
                _request(url, timeout=10.0).close()
            except (urllib.error.URLError, TimeoutError, OSError):
                pass  # optional: an unacked page is re-served, not lost

    # -- consumer side -----------------------------------------------------
    def pages(self) -> Iterator[Page]:
        """Arrival-order page stream; raises the first puller error (or
        whatever should_abort raises).  Closes the client when the
        generator is exhausted or closed."""
        try:
            while True:
                with self._cond:
                    while (not self._queue and self._error is None
                           and self._remaining > 0 and not self._closed):
                        if self._should_abort is not None:
                            self._should_abort()
                        t0 = time.perf_counter()
                        self._cond.wait(0.1)
                        self._wait_wall += time.perf_counter() - t0
                    if self._error is not None:
                        raise self._error
                    if self._queue:
                        page, nbytes = self._queue.popleft()
                        self._buffered -= nbytes
                        self._cond.notify_all()  # unpark parked pullers
                    else:            # complete (or closed underneath us)
                        if self._should_abort is not None:
                            self._should_abort()
                        return
                EXCHANGE_METRICS.buffered_delta(-nbytes)
                yield page
        finally:
            self.close()

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            leftover = self._buffered
            self._queue.clear()
            self._buffered = 0
            self._cond.notify_all()
        self._stop_event.set()
        self._ack_q.put(None)            # wake the ack thread so it exits
        if leftover:
            EXCHANGE_METRICS.buffered_delta(-leftover)
        drain_wall = time.perf_counter() - self._t0
        EXCHANGE_METRICS.on_client_close(self._wait_wall, drain_wall)
        if self._stats is not None:
            nano = 1e9
            self._stats.add("exchangeClientPullWallNanos",
                            self._pull_wall * nano, "NANO")
            self._stats.add("exchangeClientDecodeWallNanos",
                            self._decode_wall * nano, "NANO")
            self._stats.add("exchangeClientWaitWallNanos",
                            self._wait_wall * nano, "NANO")
            self._stats.add("exchangeClientDrainWallNanos",
                            drain_wall * nano, "NANO")
            self._stats.add("exchangeClientBytes", self._bytes, "BYTE")
            self._stats.add("exchangeClientUncompressedBytes",
                            self._uncompressed, "BYTE")
            self._stats.add("exchangeClientPages", self._pages, "NONE")
            self._stats.add("exchangeClientBufferedPeakBytes",
                            self._buffered_peak, "BYTE")

    @property
    def buffered_peak(self) -> int:
        with self._cond:
            return self._buffered_peak


def remote_page_reader(locations: List[str], codec: str = DEFAULT_CODEC,
                       max_error_duration_s: float =
                       DEFAULT_MAX_ERROR_DURATION_S,
                       should_abort: Optional[Callable[[], None]] = None,
                       client_threads: int = DEFAULT_CLIENT_THREADS,
                       max_buffer_bytes: int = DEFAULT_MAX_BUFFER_BYTES,
                       max_response_bytes: int = DEFAULT_MAX_RESPONSE_BYTES,
                       stats=None, park_on_failure: bool = False,
                       on_client: Optional[Callable] = None):
    """A TaskContext.remote_pages callable: pages from every upstream task
    feeding one RemoteSourceNode, pulled concurrently through an
    ExchangeClient.  `should_abort` raises to stop the pull early (worker
    tasks pass their own terminal-state check so a doomed query's remote
    sources stop instead of draining to completion).

    `locations` is held BY REFERENCE: a caller may mutate the list in
    place (task-retry redirection) and a later read() picks up the new
    locations.  `on_client` observes every client created so live pulls
    can be redirected too (ExchangeClient.update_locations);
    `park_on_failure` is the fault-tolerant consumer behavior (see
    _pull_rounds)."""
    def read() -> Iterator[Page]:
        client = ExchangeClient(
            list(locations), codec=codec,
            max_error_duration_s=max_error_duration_s,
            should_abort=should_abort, client_threads=client_threads,
            max_buffer_bytes=max_buffer_bytes,
            max_response_bytes=max_response_bytes, stats=stats,
            park_on_failure=park_on_failure)
        if on_client is not None:
            on_client(client)
        yield from client.pages()        # pages() closes the client
    return read
