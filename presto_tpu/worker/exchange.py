"""HTTP exchange source: pulls SerializedPages from upstream task buffers.

The analog of the reference's ExchangeClient/PageBufferClient
(presto-main-base/.../operator/ExchangeClient.java:72) and the native
PrestoExchangeSource (presto_cpp/main/PrestoExchangeSource.cpp:171): loop
GET {location}/{token} -> acknowledge -> repeat until the complete flag,
then DELETE the buffer.
"""
from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Iterator, List

from ..common.page import Page
from ..common.serde import DEFAULT_CODEC, deserialize_pages

DEFAULT_MAX_WAIT_S = 1.0
REQUEST_TIMEOUT_S = 30.0
RETRY_LIMIT = 5


def _request(url: str, method: str = "GET",
             timeout: float = REQUEST_TIMEOUT_S):
    from .auth import outbound_headers, urlopen_internal
    req = urllib.request.Request(url, method=method,
                                 headers=outbound_headers())
    return urlopen_internal(req, timeout=timeout)


def pull_pages(location: str, codec: str = DEFAULT_CODEC) -> Iterator[Page]:
    """Stream every page from one upstream buffer location
    (http://host:port/v1/task/{taskId}/results/{bufferId}).  `codec`
    decodes COMPRESSED pages; it is cluster config shared with the
    producer, like the reference exchange.compression-codec."""
    token = 0
    retries = 0
    while True:
        url = f"{location}/{token}?maxWaitMs={int(DEFAULT_MAX_WAIT_S * 1000)}"
        try:
            with _request(url) as resp:
                complete = resp.headers.get(
                    "X-Presto-Buffer-Complete", "false") == "true"
                # reference name first (PrestoHeaders.PRESTO_PAGE_NEXT_TOKEN
                # = X-Presto-Page-End-Sequence-Id), repo alias as fallback
                next_token = int(
                    resp.headers.get("X-Presto-Page-End-Sequence-Id")
                    or resp.headers.get("X-Presto-Page-Next-Token", token))
                body = resp.read()
            retries = 0
        except urllib.error.HTTPError as e:
            # 500 carries a producer-side failure: propagate, don't retry
            detail = e.read().decode(errors="replace")
            raise RuntimeError(
                f"exchange source {location} failed: {detail}") from e
        except (urllib.error.URLError, TimeoutError) as e:
            retries += 1
            if retries > RETRY_LIMIT:
                raise RuntimeError(
                    f"exchange source {location} unreachable") from e
            time.sleep(min(2.0, 0.1 * (2 ** retries)))
            continue
        if body:
            for page in deserialize_pages(body, codec=codec):
                yield page
        if next_token != token:
            try:
                _request(f"{location}/{next_token}/acknowledge").close()
            except (urllib.error.URLError, TimeoutError):
                pass  # acknowledge is an optimization; the pull re-fetches
            token = next_token
        if complete:
            try:
                _request(location, method="DELETE").close()
            except (urllib.error.URLError, TimeoutError):
                pass
            return


def remote_page_reader(locations: List[str], codec: str = DEFAULT_CODEC):
    """A TaskContext.remote_pages callable: pages from every upstream task
    feeding one RemoteSourceNode."""
    def read() -> Iterator[Page]:
        for loc in locations:
            yield from pull_pages(loc, codec=codec)
    return read
