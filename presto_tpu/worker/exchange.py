"""HTTP exchange source: pulls SerializedPages from upstream task buffers.

The analog of the reference's ExchangeClient/PageBufferClient
(presto-main-base/.../operator/ExchangeClient.java:72) and the native
PrestoExchangeSource (presto_cpp/main/PrestoExchangeSource.cpp:171): loop
GET {location}/{token} -> acknowledge -> repeat until the complete flag,
then DELETE the buffer.

Transient transport failures RESUME from the last delivered token under an
exponential-backoff-with-jitter loop bounded by a real error budget
(reference exchange.max-error-duration / PageBufferClient's backoff).
When the budget expires — or the producer task vanishes outright (404) —
a typed ExchangeLostError carries the producer location upward so the
coordinator can map it back to the producing task and retry that task
instead of failing the query.
"""
from __future__ import annotations

import random
import time
import urllib.error
import urllib.request
from typing import Callable, Iterator, List, Optional

from ..common.errors import ExchangeLostError, RemoteTaskError
from ..common.page import Page
from ..common.serde import DEFAULT_CODEC, deserialize_pages

DEFAULT_MAX_WAIT_S = 1.0
REQUEST_TIMEOUT_S = 30.0
DEFAULT_MAX_ERROR_DURATION_S = 60.0
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 2.0


def _request(url: str, method: str = "GET",
             timeout: float = REQUEST_TIMEOUT_S):
    from .auth import outbound_headers, urlopen_internal
    req = urllib.request.Request(url, method=method,
                                 headers=outbound_headers())
    return urlopen_internal(req, timeout=timeout)


def pull_pages(location: str, codec: str = DEFAULT_CODEC,
               max_error_duration_s: float = DEFAULT_MAX_ERROR_DURATION_S,
               should_abort: Optional[Callable[[], None]] = None
               ) -> Iterator[Page]:
    """Stream every page from one upstream buffer location
    (http://host:port/v1/task/{taskId}/results/{bufferId}).  `codec`
    decodes COMPRESSED pages; it is cluster config shared with the
    producer, like the reference exchange.compression-codec.

    `should_abort` is polled once per pull round (it raises to abort) —
    the coordinator's early-failure hook, so a root-stage pull stops as
    soon as any task reports FAILED instead of draining to completion."""
    token = 0
    error_since: Optional[float] = None
    attempt = 0
    while True:
        if should_abort is not None:
            should_abort()
        url = f"{location}/{token}?maxWaitMs={int(DEFAULT_MAX_WAIT_S * 1000)}"
        try:
            with _request(url) as resp:
                complete = resp.headers.get(
                    "X-Presto-Buffer-Complete", "false") == "true"
                # reference name first (PrestoHeaders.PRESTO_PAGE_NEXT_TOKEN
                # = X-Presto-Page-End-Sequence-Id), repo alias as fallback
                next_token = int(
                    resp.headers.get("X-Presto-Page-End-Sequence-Id")
                    or resp.headers.get("X-Presto-Page-Next-Token", token))
                body = resp.read()
            error_since, attempt = None, 0
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code in (404, 410):
                # the producer task is GONE (worker restarted and lost its
                # task registry): not transient — the task must be rebuilt
                raise ExchangeLostError(
                    location, token,
                    f"exchange source {location} vanished ({e.code}) at "
                    f"token {token}: producer task lost") from e
            if e.code == 503:
                # draining/overloaded producer: transient, budgeted retry
                error_since, attempt = _backoff(
                    location, token, error_since, attempt,
                    max_error_duration_s, e)
                continue
            # 500 carries a producer-side failure: propagate typed (the
            # [ERROR_TYPE] tag in the detail decides retryability upstream)
            raise RemoteTaskError(location, detail) from e
        except (urllib.error.URLError, TimeoutError, ConnectionError,
                OSError) as e:
            error_since, attempt = _backoff(
                location, token, error_since, attempt,
                max_error_duration_s, e)
            continue
        if body:
            for page in deserialize_pages(body, codec=codec):
                yield page
        if next_token != token:
            try:
                _request(f"{location}/{next_token}/acknowledge").close()
            except (urllib.error.URLError, TimeoutError, OSError):
                pass  # acknowledge is an optimization; the pull re-fetches
            token = next_token
        if complete:
            try:
                _request(location, method="DELETE").close()
            except (urllib.error.URLError, TimeoutError, OSError):
                pass
            return


def _backoff(location: str, token: int, error_since: Optional[float],
             attempt: int, max_error_duration_s: float,
             cause: Exception) -> tuple:
    """One budgeted retry step: raise ExchangeLostError once errors have
    persisted past the budget, else sleep exp-backoff + jitter (reference
    PageBufferClient backoff under exchange.max-error-duration)."""
    now = time.monotonic()
    if error_since is None:
        error_since = now
    if now - error_since >= max_error_duration_s:
        raise ExchangeLostError(
            location, token,
            f"exchange source {location} unreachable for "
            f"{now - error_since:.1f}s (budget {max_error_duration_s}s) "
            f"at token {token}: {cause}") from cause
    delay = min(_BACKOFF_CAP_S, _BACKOFF_BASE_S * (2 ** attempt))
    # full jitter keeps a fleet of consumers from re-probing in lockstep
    time.sleep(delay * (0.5 + random.random() * 0.5))
    return error_since, attempt + 1


def remote_page_reader(locations: List[str], codec: str = DEFAULT_CODEC,
                       max_error_duration_s: float =
                       DEFAULT_MAX_ERROR_DURATION_S):
    """A TaskContext.remote_pages callable: pages from every upstream task
    feeding one RemoteSourceNode."""
    def read() -> Iterator[Page]:
        for loc in locations:
            yield from pull_pages(loc, codec=codec,
                                  max_error_duration_s=max_error_duration_s)
    return read
