"""Device-memory accounting and host-RAM spill staging.

TPU analogs of the reference's node-level memory machinery:
- `MemoryPool` mirrors the worker memory pool + hierarchical contexts
  (presto-main-base/.../memory/MemoryPool.java:46, LocalMemoryManager.java:39,
  the presto-memory-context AggregatedMemoryContext tree): operators reserve
  HBM bytes before materializing and either fall back to spilling or fail
  with the engine's exceeded-limit error.
- `PartitionedSpillStore` mirrors partitioned spilling
  (.../spiller/GenericPartitioningSpiller.java, FileSingleStreamSpiller.java:59)
  with one deliberate difference: on a TPU host the natural spill target is
  host RAM, not disk — it is orders of magnitude larger than HBM and needs
  no serialization, playing exactly the role local SSD plays for the
  reference.  Buckets are key-hash partitions; processing one bucket at a
  time is the reference's grouped-execution Lifespan model
  (Lifespan.java:30, GroupedExecutionTagger.java) compressed into the
  operator that spilled.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .batch import Batch, Column
from . import operators as ops


class MemoryExceededError(RuntimeError):
    """Analog of the reference's EXCEEDED_LOCAL_MEMORY_LIMIT error code."""


class MemoryPool:
    """Byte accounting for one task's device materializations.

    budget=None means unlimited (accounting only — peak still tracked and
    reported in TaskStatus.memoryReservationInBytes)."""

    def __init__(self, budget: Optional[int] = None):
        self.budget = budget
        self.reserved = 0
        self.peak = 0

    def try_reserve(self, n: int) -> bool:
        if self.budget is not None and self.reserved + n > self.budget:
            return False
        self.reserved += n
        self.peak = max(self.peak, self.reserved)
        return True

    def reserve(self, n: int) -> None:
        if not self.try_reserve(n):
            raise MemoryExceededError(
                f"memory budget exceeded: reserved {self.reserved} "
                f"+ {n} > {self.budget} bytes")

    def free(self, n: int) -> None:
        self.reserved = max(0, self.reserved - n)


def batch_bytes(batch: Batch) -> int:
    total = batch.mask.nbytes
    for c in batch.columns.values():
        total += c.values.nbytes
        if c.nulls is not None:
            total += c.nulls.nbytes
    return int(total)


_SPILL_SALT = 0x511


class PartitionedSpillStore:
    """K key-hash buckets of host-staged rows with column encodings kept.

    `add` pulls a batch to the host and routes each valid row to
    hash(keys) % K; `bucket_batches` re-uploads one bucket as device
    Batches.  The same key columns (and salt) on two stores route equal
    keys to equal bucket indices, which is what the grace hash join and
    partitioned aggregation rely on."""

    def __init__(self, k: int, salt: int = _SPILL_SALT,
                 budget_bytes: Optional[int] = None):
        self.k = k
        self.salt = salt
        self.buckets: List[List[Dict[str, Tuple[np.ndarray,
                                                Optional[np.ndarray]]]]] = \
            [[] for _ in range(k)]
        self.meta: Dict[str, Tuple] = {}     # column -> (dictionary, lazy)
        self.rows = [0] * k
        self.bytes = [0] * k
        self.spilled_bytes = 0
        # host-RAM ceiling for staged rows: spilling must not itself OOM
        # the host (reference spiller's max-spill-size); None = unlimited
        self.budget_bytes = budget_bytes

    def add(self, batch: Batch, key_names: List[str]) -> None:
        key_cols = [batch.columns[n] for n in key_names]
        h = np.asarray(ops.hash_columns(key_cols, self.salt)) \
            % np.uint64(self.k)
        mask = np.asarray(batch.mask)
        cols_np = {}
        for name, c in batch.columns.items():
            self.meta.setdefault(name, (c.dictionary, c.lazy))
            cols_np[name] = (np.asarray(c.values),
                             None if c.nulls is None else np.asarray(c.nulls))
        for p in range(self.k):
            sel = mask & (h == p)
            n = int(sel.sum())
            if n == 0:
                continue
            rows = {name: (v[sel], None if m is None else m[sel])
                    for name, (v, m) in cols_np.items()}
            self.buckets[p].append(rows)
            self.rows[p] += n
            nb = sum(v.nbytes + (0 if m is None else m.nbytes)
                     for v, m in rows.values())
            self.bytes[p] += nb
            self.spilled_bytes += nb
            if self.budget_bytes is not None \
                    and self.spilled_bytes > self.budget_bytes:
                raise MemoryExceededError(
                    f"spill store exceeds host budget "
                    f"{self.budget_bytes} bytes "
                    f"({self.spilled_bytes} staged)")

    def bucket_batches(self, p: int, capacity: int) -> Iterator[Batch]:
        """Re-upload bucket p as device Batches of at most `capacity` rows."""
        chunks = self.buckets[p]
        if not chunks:
            return
        names = list(chunks[0])
        merged = {}
        for name in names:
            vs = np.concatenate([c[name][0] for c in chunks])
            if any(c[name][1] is not None for c in chunks):
                ms = np.concatenate([
                    c[name][1] if c[name][1] is not None
                    else np.zeros(len(c[name][0]), dtype=bool)
                    for c in chunks])
            else:
                ms = None
            merged[name] = (vs, ms)
        total = self.rows[p]
        for lo in range(0, total, capacity):
            n = min(capacity, total - lo)
            cols = {}
            for name, (vs, ms) in merged.items():
                buf = np.zeros(capacity, dtype=vs.dtype)
                buf[:n] = vs[lo:lo + n]
                nulls = None
                if ms is not None:
                    nb = np.zeros(capacity, dtype=bool)
                    nb[:n] = ms[lo:lo + n]
                    nulls = jnp.asarray(nb)
                dictionary, lazy = self.meta[name]
                cols[name] = Column(jnp.asarray(buf), nulls, dictionary, lazy)
            mask = np.zeros(capacity, dtype=bool)
            mask[:n] = True
            yield Batch(cols, jnp.asarray(mask))

    def bucket_rows(self, p: int) -> int:
        return self.rows[p]

    def bucket_bytes(self, p: int) -> int:
        return self.bytes[p]
