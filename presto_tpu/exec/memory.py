"""Hierarchical device-memory accounting, revocable arbitration, and
two-tier (host RAM -> LZ4 disk) spill staging.

TPU analogs of the reference's memory machinery:

- `MemoryPool` mirrors the worker memory pool (MemoryPool.java:46,
  LocalMemoryManager.java:39) extended with the reference's RESERVED vs
  REVOCABLE split (MemoryPool.reserveRevocable, QueryContext.java): a
  revocable reservation names bytes an operator can give back on demand
  by spilling (hash join build state, aggregation state, retained output
  buffers).  Under pressure the pool's arbitrator — the analog of
  MemoryRevokingScheduler.java:60 — revokes the LARGEST revocable holder
  through its registered spill callback instead of failing the
  reservation, so `MemoryExceededError` is raised only when nothing
  revocable remains.
- `MemoryContext` is the presto-memory-context AggregatedMemoryContext
  tree (query -> task -> operator): children bubble reservations up to
  the root, and a root `max_bytes` is the `query.max-memory` limit —
  exceeding it is the TYPED user error (EXCEEDED_MEMORY_LIMIT, fail
  fast, never retried), distinct from pool pressure which arbitration
  and spill recover from.
- `PartitionedSpillStore` mirrors partitioned spilling
  (.../spiller/GenericPartitioningSpiller.java, FileSingleStreamSpiller.java:59)
  as a TWO-TIER hierarchy: host RAM is the hot spill tier (orders of
  magnitude larger than HBM, no serialization), and when staged bytes
  exceed the host budget whole buckets overflow to LZ4-compressed disk
  files reusing the SerializedPage block serde — the cold tier local SSD
  plays for the reference.  Buckets are key-hash partitions; processing
  one bucket at a time is the reference's grouped-execution Lifespan
  model (Lifespan.java:30) compressed into the operator that spilled.
  With `async_staging` the device->host eviction runs double-buffered on
  a background staging thread so revocation overlaps the operator's
  continuing compute; the overlap fraction (1 - wait/stage) is metered
  through RuntimeStats alongside spill/unspill bytes and walls.
"""
from __future__ import annotations

import os
import queue as queue_mod
import struct
import tempfile
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..common.locks import OrderedLock
from .batch import Batch, Column
from . import operators as ops

NANO = 1_000_000_000


class MemoryExceededError(RuntimeError):
    """Analog of the reference's EXCEEDED_LOCAL_MEMORY_LIMIT error code:
    pool pressure that spill + arbitration could not absorb.  Classified
    INSUFFICIENT_RESOURCES (retryable) by common/errors.py."""


class QueryMemoryLimitExceededError(MemoryExceededError):
    """The `query.max-memory` limit (reference EXCEEDED_GLOBAL_MEMORY_LIMIT,
    ClusterMemoryManager.java): the QUERY asked for more than its
    configured ceiling.  Unlike pool pressure this is the user's to fix
    (raise the limit or shrink the query), so it fails fast — the
    [USER_ERROR] tag and `error_type` keep it non-retryable across the
    string-typed distributed failure chain."""

    error_type = "USER_ERROR"
    error_code = "EXCEEDED_MEMORY_LIMIT"

    def __init__(self, used: int, requested: int, limit: int,
                 context: str = ""):
        super().__init__(
            f"[USER_ERROR] EXCEEDED_MEMORY_LIMIT: query memory "
            f"{used} + {requested} bytes exceeds query.max-memory "
            f"{limit} bytes" + (f" (context {context})" if context else ""))
        self.used = used
        self.requested = requested
        self.limit = limit


# ---------------------------------------------------------------------------
# process-wide memory metrics (the /v1/metrics presto_tpu_memory_* section,
# same singleton shape as worker/exchange.py's ExchangeMetrics)
# ---------------------------------------------------------------------------

class MemoryMetrics:
    _COUNTERS = ("spilled_bytes", "disk_spilled_bytes", "unspilled_bytes",
                 "spill_wall_s", "spill_wait_wall_s", "unspill_wall_s",
                 "revocations", "revoked_bytes", "arbitrations",
                 "arbitration_failures", "over_free", "over_free_bytes",
                 "query_limit_failures")
    _GAUGES = ("reserved_bytes", "revocable_bytes")

    def __init__(self):
        # rank 100: metrics registries are LEAF locks, bumped from every
        # thread family while any other lock may be held
        self._lock = OrderedLock("metrics:memory", 100)  # lint: guarded-by(_lock)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            for name in self._COUNTERS + self._GAUGES:
                setattr(self, name, 0)

    def incr(self, name: str, delta=1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + delta)

    def gauge(self, name: str, value) -> None:
        with self._lock:
            setattr(self, name, value)

    def snapshot(self) -> dict:
        with self._lock:
            out = {name: getattr(self, name)
                   for name in self._COUNTERS + self._GAUGES}
        stage, wait = out["spill_wall_s"], out["spill_wait_wall_s"]
        out["spill_overlap_fraction"] = (
            max(0.0, 1.0 - wait / stage) if stage > 0 else 0.0)
        return out


MEMORY_METRICS = MemoryMetrics()


# ---------------------------------------------------------------------------
# revocable holders + the arbitrated pool
# ---------------------------------------------------------------------------

class RevocableHolder:
    """One registered revocable reservation (the analog of an operator's
    revocable LocalMemoryContext + its OperatorContext.requestMemoryRevoking
    callback).  `revoke_cb() -> bytes freed` must be NON-BLOCKING: a
    holder that cannot safely spill right now (its device state is
    mid-probe) declines by returning 0 and the arbitrator moves to the
    next-largest victim — blocking here is how arbitration deadlocks."""

    def __init__(self, pool: "MemoryPool", name: str,
                 revoke_cb: Callable[[], int]):
        self._pool = pool
        self.name = name
        self._revoke_cb = revoke_cb
        self.bytes = 0
        self.revoke_requested = False
        self.closed = False

    def try_reserve(self, n: int, arbitrate: bool = True) -> bool:
        """Revocable reservation with arbitration of OTHER holders.
        Callers that hold their own operator lock while charging (the
        output buffers) MUST pass arbitrate=False and self-spill on
        failure: entering arbitration under an operator lock is the
        lock-inversion that deadlocks against that operator's own revoke
        callback."""
        if not self._pool.try_reserve(n, revocable=True, exclude=self,
                                      arbitrate=arbitrate):
            return False
        self.bytes += n
        return True

    def free(self, n: int) -> None:
        n = min(int(n), self.bytes)
        if n <= 0:
            return
        self.bytes -= n
        self._pool.free(n, revocable=True)

    def close(self) -> None:
        """Release whatever is still held and unregister."""
        if self.closed:
            return
        self.closed = True
        self.free(self.bytes)
        self._pool._unregister(self)

    def _run_revoke(self) -> int:
        try:
            freed = int(self._revoke_cb() or 0)
        except Exception:
            return 0
        if freed > 0:
            self.revoke_requested = False
        return freed


class MemoryPool:
    """Byte accounting for device materializations, with the reference's
    reserved/revocable split and a built-in arbitrator.

    budget=None means unlimited (accounting only — peak still tracked and
    reported in TaskStatus.memoryReservationInBytes).  All mutators are
    thread-safe: the serving tier shares ONE worker pool across
    concurrently executing queries."""

    def __init__(self, budget: Optional[int] = None):
        self.budget = budget
        self.reserved = 0
        self.revocable = 0
        self.peak = 0
        # satellite: MemoryPool.free used to clamp an over-free to 0
        # silently, hiding reservation-accounting leaks — now every clamp
        # is counted (memoryOverFree) so leaks surface in tests/metrics
        self.over_free_count = 0
        self.over_free_bytes = 0
        self.revocations = 0
        self.revoked_bytes = 0
        self.arbitrations = 0
        self.spilled_bytes = 0        # host-staged by stores under this pool
        self.disk_spilled_bytes = 0   # overflowed from host RAM to disk
        self.unspilled_bytes = 0      # read back for bucket processing
        # reentrant: MemoryContext composes multi-step updates under it
        self._lock = OrderedLock(
            "memory-pool", 40, reentrant=True)  # lint: guarded-by(_lock)
        # one arbitration pass at a time: revoke callbacks run OUTSIDE the
        # accounting lock (they free into it) but inside this one, so two
        # starved threads do not revoke the same victim twice.  Rank 20 <
        # buffer/spool/pool: the arbitrator is the OUTERMOST lock of the
        # revocation chain.
        self._arb_lock = OrderedLock("memory-arbitrator", 20)
        self._holders: List[RevocableHolder] = []

    # -- reservation ------------------------------------------------------
    @property
    def total_reserved(self) -> int:
        """reserved + revocable: the arbitrated accounting the admission
        gate and /v1/cluster report."""
        return self.reserved + self.revocable

    @property
    def limited(self) -> bool:
        """Duck-types MemoryContext.limited for code handed a bare pool:
        a pool enforces nothing beyond its budget."""
        return self.budget is not None

    def _try_locked(self, n: int, revocable: bool) -> bool:
        with self._lock:
            if self.budget is not None \
                    and self.reserved + self.revocable + n > self.budget:
                return False
            if revocable:
                self.revocable += n
            else:
                self.reserved += n
            total = self.reserved + self.revocable
            if total > self.peak:
                self.peak = total
            return True

    def try_reserve(self, n: int, revocable: bool = False,
                    exclude: Optional[RevocableHolder] = None,
                    arbitrate: bool = True) -> bool:
        if self._try_locked(n, revocable):
            return True
        if not arbitrate:
            return False
        return self._arbitrate(n, revocable, exclude)

    def reserve(self, n: int, revocable: bool = False) -> None:
        if not self.try_reserve(n, revocable=revocable):
            raise MemoryExceededError(
                f"memory budget exceeded: reserved {self.reserved} "
                f"(+{self.revocable} revocable) + {n} > {self.budget} "
                f"bytes and no revocable memory remains")

    def free(self, n: int, revocable: bool = False) -> None:
        with self._lock:
            held = self.revocable if revocable else self.reserved
            if n > held:
                # an over-free means some reservation was double-freed (or
                # freed with the wrong size) — clamp for safety, but COUNT
                # it so the leak is visible (memoryOverFree in stats)
                self.over_free_count += 1
                self.over_free_bytes += n - held
                MEMORY_METRICS.incr("over_free")
                MEMORY_METRICS.incr("over_free_bytes", n - held)
                n = held
            if revocable:
                self.revocable -= n
            else:
                self.reserved -= n

    # -- revocable holder registry + arbitration --------------------------
    def register_revocable(self, name: str,
                           revoke_cb: Callable[[], int]) -> RevocableHolder:
        h = RevocableHolder(self, name, revoke_cb)
        with self._lock:
            self._holders.append(h)
        return h

    def _unregister(self, holder: RevocableHolder) -> None:
        with self._lock:
            try:
                self._holders.remove(holder)
            except ValueError:
                pass

    def _arbitrate(self, n: int, revocable: bool,
                   exclude: Optional[RevocableHolder]) -> bool:
        """The MemoryArbitrator: revoke the largest revocable holder (via
        its spill callback), retry the reservation, repeat until it fits
        or nothing revocable remains.  Never blocks on a holder: one that
        declines (returns 0) is skipped for this pass."""
        with self._lock:
            self.arbitrations += 1
        MEMORY_METRICS.incr("arbitrations")
        declined: set = set()
        with self._arb_lock:
            while True:
                if self._try_locked(n, revocable):
                    return True
                with self._lock:
                    candidates = [h for h in self._holders
                                  if h is not exclude and not h.closed
                                  and h.bytes > 0 and id(h) not in declined]
                if not candidates:
                    MEMORY_METRICS.incr("arbitration_failures")
                    return False
                victim = max(candidates, key=lambda h: h.bytes)
                victim.revoke_requested = True
                freed = victim._run_revoke()
                if freed <= 0:
                    declined.add(id(victim))
                else:
                    with self._lock:
                        self.revocations += 1
                        self.revoked_bytes += freed
                    MEMORY_METRICS.incr("revocations")
                    MEMORY_METRICS.incr("revoked_bytes", freed)

    # -- spill accounting (fed by PartitionedSpillStore) ------------------
    def note_spill(self, n: int) -> None:
        with self._lock:
            self.spilled_bytes += n

    def note_disk_spill(self, n: int) -> None:
        with self._lock:
            self.disk_spilled_bytes += n

    def note_unspill(self, n: int) -> None:
        with self._lock:
            self.unspilled_bytes += n

    def stats_dict(self) -> dict:
        with self._lock:
            return {
                "reservedBytes": self.reserved,
                "revocableBytes": self.revocable,
                "totalReservedBytes": self.reserved + self.revocable,
                "peakBytes": self.peak,
                "spilledBytes": self.spilled_bytes,
                "diskSpilledBytes": self.disk_spilled_bytes,
                "unspilledBytes": self.unspilled_bytes,
                "revocations": self.revocations,
                "revokedBytes": self.revoked_bytes,
                "arbitrations": self.arbitrations,
                "memoryOverFree": self.over_free_count,
                "memoryOverFreeBytes": self.over_free_bytes,
            }


class MemoryContext:
    """One node of the query -> task -> operator context tree (reference
    AggregatedMemoryContext / QueryContext.java): reservations bubble up
    to the root so a query's aggregate usage is enforceable wherever its
    tasks run.  A root `max_bytes` is the query.max-memory ceiling —
    REVOCABLE bytes are exempt (matching the reference, where revocable
    memory does not count against the query limit: it is the engine's to
    reclaim by spilling, not the query's footprint).

    Duck-types the MemoryPool reservation surface (budget / peak /
    reserved / try_reserve / reserve / free / register_revocable /
    note_spill...) so a context slots in wherever TaskContext.memory
    carried a bare pool."""

    def __init__(self, pool: MemoryPool, name: str = "query",
                 parent: Optional["MemoryContext"] = None,
                 max_bytes: Optional[int] = None):
        self.pool = pool
        self.name = name
        self.parent = parent
        self.max_bytes = max_bytes
        self.reserved = 0
        self.revocable = 0
        self.peak = 0

    def new_child(self, name: str) -> "MemoryContext":
        return MemoryContext(self.pool, name, parent=self)

    @property
    def budget(self):
        return self.pool.budget

    @property
    def limited(self) -> bool:
        """True when reservations must be accounted: the pool carries a
        budget, or this context (or an ancestor) carries a
        `query.max-memory` ceiling.  The unbudgeted fast paths (fused
        single-program execution, unreserved build seeding, HBM result
        caches) key off this rather than `budget` so a bare limit still
        engages the reservation bookkeeping that enforces it."""
        if self.pool.budget is not None:
            return True
        node = self
        while node is not None:
            if node.max_bytes is not None:
                return True
            node = node.parent
        return False

    # -- tree bookkeeping -------------------------------------------------
    def _check_limit_up(self, n: int) -> None:
        node = self
        while node is not None:
            if node.max_bytes is not None \
                    and node.reserved + n > node.max_bytes:
                MEMORY_METRICS.incr("query_limit_failures")
                raise QueryMemoryLimitExceededError(
                    node.reserved, n, node.max_bytes, context=node.name)
            node = node.parent

    def _apply_up(self, n: int, revocable: bool) -> None:
        node = self
        while node is not None:
            if revocable:
                node.revocable += n
            else:
                node.reserved += n
            total = node.reserved + node.revocable
            if total > node.peak:
                node.peak = total
            node = node.parent

    # -- reservation (pool surface) ---------------------------------------
    def try_reserve(self, n: int, revocable: bool = False,
                    exclude: Optional[RevocableHolder] = None,
                    arbitrate: bool = True) -> bool:
        with self.pool._lock:
            if not revocable:
                self._check_limit_up(n)
        if not self.pool.try_reserve(n, revocable=revocable,
                                     exclude=exclude, arbitrate=arbitrate):
            return False
        with self.pool._lock:
            self._apply_up(n, revocable)
        return True

    def reserve(self, n: int, revocable: bool = False) -> None:
        if not self.try_reserve(n, revocable=revocable):
            raise MemoryExceededError(
                f"memory budget exceeded: reserved {self.pool.reserved} "
                f"(+{self.pool.revocable} revocable) + {n} > "
                f"{self.pool.budget} bytes and no revocable memory remains")

    def free(self, n: int, revocable: bool = False) -> None:
        self.pool.free(n, revocable=revocable)
        with self.pool._lock:
            held = self.revocable if revocable else self.reserved
            self._apply_up(-min(n, held), revocable)

    # -- pass-throughs ----------------------------------------------------
    def register_revocable(self, name: str,
                           revoke_cb: Callable[[], int]) -> RevocableHolder:
        # the holder charges THROUGH this context (so revocable bytes
        # bubble up the tree) but registers with the root pool, where the
        # arbitrator looks for victims
        h = RevocableHolder(self, f"{self.name}/{name}", revoke_cb)
        with self.pool._lock:
            self.pool._holders.append(h)
        return h

    def _unregister(self, holder: RevocableHolder) -> None:
        self.pool._unregister(holder)

    def note_spill(self, n: int) -> None:
        self.pool.note_spill(n)

    def note_disk_spill(self, n: int) -> None:
        self.pool.note_disk_spill(n)

    def note_unspill(self, n: int) -> None:
        self.pool.note_unspill(n)

    @property
    def spilled_bytes(self) -> int:
        return self.pool.spilled_bytes

    @property
    def total_reserved(self) -> int:
        return self.reserved + self.revocable

    def stats_dict(self) -> dict:
        d = self.pool.stats_dict()
        d["contextReservedBytes"] = self.reserved
        d["contextRevocableBytes"] = self.revocable
        d["contextPeakBytes"] = self.peak
        return d


def batch_bytes(batch: Batch) -> int:
    total = batch.mask.nbytes
    for c in batch.columns.values():
        total += c.values.nbytes
        if c.nulls is not None:
            total += c.nulls.nbytes
    return int(total)


# ---------------------------------------------------------------------------
# two-tier partitioned spill store
# ---------------------------------------------------------------------------

_SPILL_SALT = 0x511

# staging queue depth 2 = classic double buffering: the operator fills
# batch k+1 while the staging thread evicts batch k; a third slot would
# only add host-RAM pressure without more overlap
_STAGING_DEPTH = 2
_STAGING_STOP = object()
# every wait in the staging drain path is bounded so a wedged staging
# thread can never hang a query abort or a worker decommission
_STAGING_POLL_S = 0.5
_STAGING_DRAIN_TIMEOUT_S = 60.0


def _np_to_block_view(v: np.ndarray):
    """View an array as the width-matched signed-int dtype the fixed-width
    block serde carries (the wire just sees bits); None when the shape or
    width has no fixed-width encoding."""
    if v.ndim != 1 or v.dtype.itemsize not in (1, 2, 4, 8) \
            or v.dtype.kind not in "fuib":
        return None
    return v.view(np.dtype(f"i{v.dtype.itemsize}"))


class PartitionedSpillStore:
    """K key-hash buckets of host-staged rows with column encodings kept.

    `add` pulls a batch to the host and routes each valid row to
    hash(keys) % K; `bucket_batches` re-uploads one bucket as device
    Batches.  The same key columns (and salt) on two stores route equal
    keys to equal bucket indices, which is what the grace hash join and
    partitioned aggregation rely on.

    Tiering: staged rows live in host RAM up to `budget_bytes`; past it
    the largest resident bucket overflows to an LZ4-compressed disk file
    (one per store, under `spill_path`) via the SerializedPage block
    serde, chunk order preserved so re-reading is bit-identical to the
    unspilled run.  Without a disk path the old behavior stands: the
    host budget raises (spilling must not itself OOM the host).

    `async_staging` moves the device->host transfer + routing onto a
    double-buffered background thread so eviction overlaps the producing
    operator's compute; `add` only blocks when both staging slots are
    busy, and that wait is metered (spillWaitWallNanos) against the
    thread's stage wall (spillStageWallNanos) to report the overlap
    fraction.  Chunks are staged strictly FIFO, so routing results are
    identical to the synchronous path."""

    def __init__(self, k: int, salt: int = _SPILL_SALT,
                 budget_bytes: Optional[int] = None,
                 spill_path: Optional[str] = None,
                 stats=None, async_staging: bool = False,
                 pool=None):
        self.k = k
        self.salt = salt
        self.buckets: List[List[Dict[str, Tuple[np.ndarray,
                                                Optional[np.ndarray]]]]] = \
            [[] for _ in range(k)]
        self.meta: Dict[str, Tuple] = {}     # column -> (dictionary, lazy)
        self.rows = [0] * k
        self.bytes = [0] * k                 # logical bytes (both tiers)
        self.host_bytes = [0] * k            # resident host-RAM bytes only
        self.spilled_bytes = 0               # cumulative staged bytes
        self.disk_bytes = 0                  # cumulative disk-written bytes
        self.unspilled_bytes = 0             # cumulative disk re-reads
        # host-RAM ceiling for staged rows: spilling must not itself OOM
        # the host (reference spiller's max-spill-size); None = unlimited
        self.budget_bytes = budget_bytes
        self.spill_path = spill_path
        self.stats = stats                   # RuntimeStats sink (optional)
        self.pool = pool                     # MemoryPool/MemoryContext sink
        # disk tier state: one append-only file of serialized chunks;
        # per-bucket ordered record lists keep original chunk order
        self._disk_file: Optional[str] = None
        self._disk_records: List[List[Tuple[int, int, list, int]]] = \
            [[] for _ in range(k)]           # (offset, length, cols, rows)
        # async staging state
        self.async_staging = bool(async_staging)
        self._q: Optional[queue_mod.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stage_err: Optional[BaseException] = None
        self._stage_wall = 0.0               # staging-thread eviction wall
        self._wait_wall = 0.0                # producer blocked on staging
        self._reported = False

    # -- staging (device -> host, tier 1) ---------------------------------
    def add(self, batch: Batch, key_names: List[str]) -> None:
        if not self.async_staging:
            self._stage(batch, list(key_names))
            return
        self._raise_staging_error()
        if self._thread is None:
            self._q = queue_mod.Queue(maxsize=_STAGING_DEPTH)
            self._thread = threading.Thread(
                target=self._staging_loop, name="spill-staging", daemon=True)
            self._thread.start()
        t0 = time.perf_counter()  # lint: allow-wall-clock
        self._q.put((batch, list(key_names)))
        self._wait_wall += time.perf_counter() - t0  # lint: allow-wall-clock

    def _staging_loop(self) -> None:
        while True:
            try:
                # bounded pull: the loop re-checks rather than parking
                # forever, so a lost stop token can't wedge the thread
                item = self._q.get(timeout=_STAGING_POLL_S)
            except queue_mod.Empty:
                continue
            if item is _STAGING_STOP:
                self._q.task_done()
                return
            t0 = time.perf_counter()  # lint: allow-wall-clock
            try:
                if self._stage_err is None:
                    self._stage(*item)
            except BaseException as e:  # propagated at the next add/drain
                self._stage_err = e
            finally:
                self._stage_wall += \
                    time.perf_counter() - t0  # lint: allow-wall-clock
                self._q.task_done()

    def _raise_staging_error(self) -> None:
        if self._stage_err is not None:
            err, self._stage_err = self._stage_err, None
            raise err

    def drain(self) -> None:
        """Wait for in-flight staging, stop the thread, and report the
        spill walls + overlap fraction once.  Reads go through here, so
        every consumer sees fully staged buckets."""
        if self._thread is not None:
            t0 = time.perf_counter()  # lint: allow-wall-clock
            self._q.put(_STAGING_STOP)
            # the stop token is staged FIFO behind every queued chunk, so
            # thread exit implies all prior items finished; join with a
            # bound (NOT q.join(), which has no timeout) so a wedged
            # staging thread fails the query instead of hanging drain
            self._thread.join(timeout=_STAGING_DRAIN_TIMEOUT_S)
            wedged = self._thread.is_alive()
            self._wait_wall += \
                time.perf_counter() - t0  # lint: allow-wall-clock
            self._thread = None
            self._q = None
            if wedged and self._stage_err is None:
                self._stage_err = RuntimeError(
                    f"spill staging thread failed to drain within "
                    f"{_STAGING_DRAIN_TIMEOUT_S}s")
        self._raise_staging_error()
        self._report_staging()

    def _report_staging(self) -> None:
        if self._reported or self.spilled_bytes == 0:
            return
        self._reported = True
        MEMORY_METRICS.incr("spill_wall_s", self._stage_wall)
        MEMORY_METRICS.incr("spill_wait_wall_s", self._wait_wall)
        if self.stats is not None:
            self.stats.add("spillBytes", self.spilled_bytes, "BYTE")
            if self.disk_bytes:
                self.stats.add("spillDiskBytes", self.disk_bytes, "BYTE")
            if self._stage_wall > 0:
                self.stats.add("spillStageWallNanos",
                               self._stage_wall * NANO, "NANO")
                self.stats.add("spillWaitWallNanos",
                               self._wait_wall * NANO, "NANO")
                self.stats.add(
                    "spillOverlapFraction",
                    max(0.0, 1.0 - self._wait_wall / self._stage_wall))

    def _stage(self, batch: Batch, key_names: List[str]) -> None:
        key_cols = [batch.columns[n] for n in key_names]
        h = np.asarray(ops.hash_columns(key_cols, self.salt)) \
            % np.uint64(self.k)
        mask = np.asarray(batch.mask)
        cols_np = {}
        for name, c in batch.columns.items():
            self.meta.setdefault(name, (c.dictionary, c.lazy))
            cols_np[name] = (np.asarray(c.values),
                             None if c.nulls is None else np.asarray(c.nulls))
        for p in range(self.k):
            sel = mask & (h == p)
            n = int(sel.sum())
            if n == 0:
                continue
            rows = {name: (v[sel], None if m is None else m[sel])
                    for name, (v, m) in cols_np.items()}
            self.buckets[p].append(rows)
            self.rows[p] += n
            nb = sum(v.nbytes + (0 if m is None else m.nbytes)
                     for v, m in rows.values())
            self.bytes[p] += nb
            self.host_bytes[p] += nb
            self.spilled_bytes += nb
            MEMORY_METRICS.incr("spilled_bytes", nb)
            if self.pool is not None:
                self.pool.note_spill(nb)
        self._enforce_host_budget()

    # -- tier 2: disk overflow --------------------------------------------
    def _enforce_host_budget(self) -> None:
        if self.budget_bytes is None:
            return
        while sum(self.host_bytes) > self.budget_bytes:
            p = max(range(self.k), key=lambda i: self.host_bytes[i])
            if self.host_bytes[p] == 0 or not self._flush_bucket(p):
                raise MemoryExceededError(
                    f"spill store exceeds host budget "
                    f"{self.budget_bytes} bytes "
                    f"({sum(self.host_bytes)} staged) and no disk "
                    f"spill path is configured")

    def _open_disk(self):
        if self._disk_file is None:
            d = self.spill_path
            if d:
                os.makedirs(d, exist_ok=True)
            fd, self._disk_file = tempfile.mkstemp(
                prefix="presto-spill-", suffix=".bin", dir=d or None)
            os.close(fd)
        return open(self._disk_file, "ab")

    def _flush_bucket(self, p: int) -> bool:
        """Move bucket p's resident chunks to the disk file (in chunk
        order, so a later read is bit-identical to the resident run)."""
        if self.spill_path is None and self._disk_file is None \
                and not self._spill_dir_default():
            return False
        chunks, self.buckets[p] = self.buckets[p], []
        freed = self.host_bytes[p]
        self.host_bytes[p] = 0
        with self._open_disk() as f:
            for rows in chunks:
                offset = f.tell()
                payload, cols, nrows = _chunk_to_bytes(rows)
                f.write(payload)
                self._disk_records[p].append(
                    (offset, len(payload), cols, nrows))
                self.disk_bytes += len(payload)
                MEMORY_METRICS.incr("disk_spilled_bytes", len(payload))
                if self.pool is not None:
                    self.pool.note_disk_spill(len(payload))
        del chunks
        return freed > 0

    def _spill_dir_default(self) -> bool:
        """No explicit spill path: overflow into the system temp dir
        rather than fail — `spill.path` pins the location for real
        deployments (fast local SSD)."""
        self.spill_path = tempfile.gettempdir()
        return True

    def _load_disk_chunks(self, p: int) -> List[dict]:
        records = self._disk_records[p]
        if not records:
            return []
        t0 = time.perf_counter()  # lint: allow-wall-clock
        out = []
        with open(self._disk_file, "rb") as f:
            for offset, length, cols, nrows in records:
                f.seek(offset)
                out.append(_chunk_from_bytes(f.read(length), cols, nrows))
                self.unspilled_bytes += length
                if self.pool is not None:
                    self.pool.note_unspill(length)
        wall = time.perf_counter() - t0  # lint: allow-wall-clock
        MEMORY_METRICS.incr("unspilled_bytes",
                            sum(r[1] for r in records))
        MEMORY_METRICS.incr("unspill_wall_s", wall)
        if self.stats is not None:
            self.stats.add("unspillBytes",
                           sum(r[1] for r in records), "BYTE")
            self.stats.add("unspillWallNanos", wall * NANO, "NANO")
        return out

    def close(self) -> None:
        """Drop the staging thread and the disk file (idempotent)."""
        try:
            self.drain()
        except Exception:
            pass
        if self._disk_file is not None:
            try:
                os.unlink(self._disk_file)
            except OSError:
                pass
            self._disk_file = None

    def __del__(self):  # best-effort: stores are operator-scoped
        try:
            if self._disk_file is not None:
                os.unlink(self._disk_file)
        except Exception:
            pass

    # -- reads (host -> device) -------------------------------------------
    def bucket_batches(self, p: int, capacity: int) -> Iterator[Batch]:
        """Re-upload bucket p as device Batches of at most `capacity` rows."""
        self.drain()
        chunks = self._load_disk_chunks(p) + self.buckets[p]
        if not chunks:
            return
        names = list(chunks[0])
        merged = {}
        for name in names:
            vs = np.concatenate([c[name][0] for c in chunks])
            if any(c[name][1] is not None for c in chunks):
                ms = np.concatenate([
                    c[name][1] if c[name][1] is not None
                    else np.zeros(len(c[name][0]), dtype=bool)
                    for c in chunks])
            else:
                ms = None
            merged[name] = (vs, ms)
        total = self.rows[p]
        for lo in range(0, total, capacity):
            n = min(capacity, total - lo)
            cols = {}
            for name, (vs, ms) in merged.items():
                buf = np.zeros(capacity, dtype=vs.dtype)
                buf[:n] = vs[lo:lo + n]
                nulls = None
                if ms is not None:
                    nb = np.zeros(capacity, dtype=bool)
                    nb[:n] = ms[lo:lo + n]
                    nulls = jnp.asarray(nb)
                dictionary, lazy = self.meta[name]
                cols[name] = Column(jnp.asarray(buf), nulls, dictionary, lazy)
            mask = np.zeros(capacity, dtype=bool)
            mask[:n] = True
            yield Batch(cols, jnp.asarray(mask))

    def bucket_rows(self, p: int) -> int:
        self.drain()
        return self.rows[p]

    def bucket_bytes(self, p: int) -> int:
        self.drain()
        return self.bytes[p]


# ---------------------------------------------------------------------------
# disk-chunk serde (reuses the SerializedPage block framing + LZ4 gate)
# ---------------------------------------------------------------------------

def _chunk_to_bytes(rows: Dict[str, Tuple[np.ndarray,
                                          Optional[np.ndarray]]]
                    ) -> Tuple[bytes, list, int]:
    """One staged chunk -> length-prefixed JSON column descriptor + an
    LZ4-compressed SerializedPage.  Values ride as width-matched
    fixed-width blocks (float64 -> LONG_ARRAY bits, bool -> BYTE_ARRAY);
    null masks ride as their own BYTE_ARRAY channel so null positions'
    VALUE bits survive the round trip exactly."""
    from ..common.page import Page
    from ..common.block import FixedWidthBlock
    from ..common import serde
    blocks, cols = [], []
    nrows = 0
    for name in rows:
        v, m = rows[name]
        nrows = len(v)
        iv = _np_to_block_view(v)
        if iv is None:
            raise MemoryExceededError(
                f"column {name!r} dtype {v.dtype}/{v.ndim}d has no "
                f"fixed-width disk-spill encoding")
        blocks.append(FixedWidthBlock(iv))
        cols.append([name, v.dtype.str, m is not None])
        if m is not None:
            blocks.append(FixedWidthBlock(m.view(np.int8)))
    page = serde.serialize_page(Page(blocks, nrows), compress=True,
                                codec="LZ4")
    return struct.pack("<i", len(page)) + page, cols, nrows


def _chunk_from_bytes(payload: bytes, cols: list, nrows: int
                      ) -> Dict[str, Tuple[np.ndarray,
                                           Optional[np.ndarray]]]:
    from ..common import serde
    (plen,) = struct.unpack_from("<i", payload, 0)
    page, _ = serde.deserialize_page(payload[4:4 + plen], codec="LZ4")
    out: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]] = {}
    i = 0
    for name, dtype_str, has_nulls in cols:
        values = page.blocks[i].values.view(np.dtype(dtype_str))
        i += 1
        nulls = None
        if has_nulls:
            nulls = page.blocks[i].values.view(np.bool_)
            i += 1
        out[name] = (values, nulls)
    return out
