"""Adaptive query execution: runtime feedback folded back into the plan.

Three cooperating pieces (reference analogs: PrestoDB dynamic filtering
`DynamicFilterService`, `DynamicFilterSourceOperator`; history-based
optimization `HistoryBasedPlanStatisticsCalculator`):

- `DynamicFilterSummary` / `DynamicFilterCollector`: when a build-side
  stage finishes, its per-key domain (min/max always, the exact value
  set under `dynamic-filtering.max-distinct-values`) is summarized and
  collected per filter id; downstream scans consume the summary through
  `storage/pushdown.py` ``["dyn", fid, bound]`` marker entries (zone-map
  chunk prune) and a traced row filter (no recompile — bounds ride as
  jit arguments, the PR 7 parameterization idiom).

- `decide_exchange`: at a stage boundary, compares the observed
  build-side row count against the fragmenter's planned estimate and
  flips a partitioned exchange to broadcast (or swaps join sides) when
  the plan-time assumption was wrong by `ADAPTIVE_RATIO` or more.

- `ADAPTIVE_METRICS`: process-wide counter registry (`/v1/metrics`
  ``presto_tpu_adaptive_*``, OTLP scrape, EXPLAIN ANALYZE footer).

Everything here is host-side and advisory: a summary that never arrives
only costs pruning opportunity (scans proceed unfiltered after the
bounded `dynamic-filtering.wait-timeout`), never correctness.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common.locks import OrderedLock

# Flip partitioned->broadcast only when the planned estimate missed by
# at least this factor AND the observed build fits the broadcast
# threshold; a mild miss is not worth re-deciding.
ADAPTIVE_RATIO = 10.0


# ---------------------------------------------------------------------------
# metrics registry (same locked-singleton shape as STORAGE_METRICS)
# ---------------------------------------------------------------------------

_ADAPTIVE_COUNTERS = (
    "filters_collected",      # summaries published by build stages
    "filters_applied",        # scans that consumed >=1 summary
    "filter_rows_in",         # rows entering runtime row filters
    "filter_rows_pruned",     # rows dropped by runtime row filters
    "filter_chunks_skipped",  # zone-map chunks skipped ONLY by dyn entries
    "filter_wait_timeouts",   # scans that gave up waiting and ran unfiltered
    "filter_late_arrivals",   # summaries delivered after the scan started
    "exchange_broadcast_flips",  # partitioned->broadcast at runtime
    "exchange_side_swaps",       # build/probe swapped at runtime
    "exchange_kept",             # boundaries inspected, plan kept
    "history_sized_queries",     # queries sized from a history record
)


class AdaptiveMetrics:
    """Locked adaptive-decision counter registry (dict-like read surface,
    mirroring storage/store.StorageMetrics)."""

    def __init__(self):
        # rank 100: metrics registries are leaf locks
        self._lock = OrderedLock("metrics:adaptive", 100)  # lint: guarded-by(_lock)
        self._values: Dict[str, int] = {k: 0 for k in _ADAPTIVE_COUNTERS}

    def reset(self) -> None:
        with self._lock:
            for k in _ADAPTIVE_COUNTERS:
                self._values[k] = 0

    def incr(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._values[name] += delta

    def __getitem__(self, name: str) -> int:
        with self._lock:
            return self._values[name]

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._values

    def __iter__(self):
        return iter(self.keys())

    def keys(self):
        with self._lock:
            return list(self._values)

    def items(self):
        return self.snapshot().items()

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._values)


ADAPTIVE_METRICS = AdaptiveMetrics()


def reset_adaptive_metrics() -> None:
    ADAPTIVE_METRICS.reset()


# ---------------------------------------------------------------------------
# dynamic filter summaries
# ---------------------------------------------------------------------------

@dataclass
class DynamicFilterSummary:
    """Domain summary of one dynamic-filter key, as published by a
    completed build-side stage.

    `min`/`max` are None when the key column's domain could not be
    bounded (non-integer storage, empty side with no rows observed is
    min>max instead) — consumers must then keep every chunk/row.
    `values` is the exact distinct set when it fit under the collection
    cap, else None (bounds-only).  All values are host ints in STORED
    column units, the same units zone maps carry."""

    filter_id: str
    min: Optional[int] = None
    max: Optional[int] = None
    values: Optional[Tuple[int, ...]] = None
    row_count: int = 0

    @property
    def empty(self) -> bool:
        """True when the build side had no rows: every probe chunk can
        be pruned (min>max is the zone-map empty convention)."""
        return self.row_count == 0

    @property
    def bounded(self) -> bool:
        return self.min is not None and self.max is not None

    def to_dict(self) -> dict:
        d: dict = {"filterId": self.filter_id, "rowCount": self.row_count}
        if self.min is not None:
            d["min"] = int(self.min)
        if self.max is not None:
            d["max"] = int(self.max)
        if self.values is not None:
            d["values"] = [int(v) for v in self.values]
        return d

    @staticmethod
    def from_dict(d: dict) -> "DynamicFilterSummary":
        vals = d.get("values")
        return DynamicFilterSummary(
            filter_id=d["filterId"],
            min=d.get("min"), max=d.get("max"),
            values=None if vals is None else tuple(vals),
            row_count=int(d.get("rowCount", 0)))

    def merge(self, other: "DynamicFilterSummary",
              max_distinct: int) -> "DynamicFilterSummary":
        """Union of two partial summaries (two tasks of one build stage).
        Bounds widen; the exact set survives only while BOTH sides have
        one and the union stays under the cap.  An unbounded side makes
        the merge unbounded — conservatism over cleverness."""
        rows = self.row_count + other.row_count
        if self.row_count == 0:
            return DynamicFilterSummary(self.filter_id, other.min,
                                        other.max, other.values, rows)
        if other.row_count == 0:
            return DynamicFilterSummary(self.filter_id, self.min,
                                        self.max, self.values, rows)
        if not (self.bounded and other.bounded):
            return DynamicFilterSummary(self.filter_id, None, None,
                                        None, rows)
        values = None
        if self.values is not None and other.values is not None:
            u = set(self.values) | set(other.values)
            if len(u) <= max_distinct:
                values = tuple(sorted(u))
        return DynamicFilterSummary(
            self.filter_id, min(self.min, other.min),
            max(self.max, other.max), values, rows)


def summarize_key_column(filter_id: str, values, mask,
                         max_distinct: int) -> DynamicFilterSummary:
    """Summary over one host array of key values (`mask` selects live,
    non-null rows; either may be None).  Only integer-kind arrays get
    bounds — zone maps hold stored-unit ints, and float equality pruning
    is not worth the soundness analysis."""
    import numpy as np
    v = np.asarray(values)
    if mask is not None:
        v = v[np.asarray(mask, dtype=bool)]
    rows = int(v.size)
    if rows == 0:
        return DynamicFilterSummary(filter_id, row_count=0)
    if v.dtype.kind not in ("i", "u", "b"):
        return DynamicFilterSummary(filter_id, row_count=rows)
    values_out: Optional[Tuple[int, ...]] = None
    # cheap exactness probe: a full unique() on a huge build side is
    # wasted work when the cap is tiny, so bail early on the row count
    if rows <= max(max_distinct * 64, 4096):
        uniq = np.unique(v)
        if uniq.size <= max_distinct:
            values_out = tuple(int(x) for x in uniq)
    return DynamicFilterSummary(
        filter_id, int(v.min()), int(v.max()), values_out, rows)


class DynamicFilterCollector:
    """Per-query accumulation of summaries keyed by filter id, merging
    partials as build tasks complete.  Thread-safe: the in-process
    scheduler's task pool and the coordinator's status watcher both
    publish from worker threads."""

    def __init__(self, max_distinct: int = 256):
        self.max_distinct = max_distinct
        # rank 58: sits between exchange-client locks and query-history
        self._lock = OrderedLock("adaptive:df-collector", 58)  # lint: guarded-by(_lock)
        self._summaries: Dict[str, DynamicFilterSummary] = {}

    def publish(self, summary: DynamicFilterSummary) -> None:
        with self._lock:
            cur = self._summaries.get(summary.filter_id)
            self._summaries[summary.filter_id] = (
                summary if cur is None
                else cur.merge(summary, self.max_distinct))
        ADAPTIVE_METRICS.incr("filters_collected")

    def get(self, filter_id: str) -> Optional[DynamicFilterSummary]:
        with self._lock:
            return self._summaries.get(filter_id)

    def snapshot(self) -> Dict[str, DynamicFilterSummary]:
        with self._lock:
            return dict(self._summaries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._summaries)


def summaries_to_runtime(
        summaries: Dict[str, DynamicFilterSummary]) -> Dict[str, dict]:
    """The `TaskContext.dynamic_filters` / wire form: fid -> plain dict."""
    return {fid: s.to_dict() for fid, s in summaries.items()}


# ---------------------------------------------------------------------------
# exchange strategy decisions
# ---------------------------------------------------------------------------

@dataclass
class ExchangeDecision:
    """One stage-boundary re-decision, for metering and EXPLAIN."""
    node_id: str
    action: str               # "broadcast" | "swap_sides" | "keep"
    planned_rows: Optional[int]
    observed_rows: int
    detail: str = ""


def decide_exchange(planned_rows: Optional[int], observed_rows: int,
                    broadcast_threshold: int,
                    ratio: float = ADAPTIVE_RATIO) -> bool:
    """True when a PARTITIONED build side should flip to broadcast: the
    observed build fits under the broadcast threshold AND the planner's
    estimate was off by at least `ratio` (an estimate that was simply
    absent counts as wrong — the planner had nothing to stand on)."""
    if observed_rows > broadcast_threshold:
        return False
    if planned_rows is None:
        return True
    return observed_rows * ratio <= planned_rows


def decide_side_swap(left_rows: Optional[int], right_rows: Optional[int],
                     ratio: float = 2.0) -> bool:
    """True when the observed build (right) side is so much larger than
    the probe that hashing the probe instead wins.  Only INNER joins may
    act on this — LEFT/FULL pin sides by preservation semantics."""
    if left_rows is None or right_rows is None:
        return False
    return right_rows >= left_rows * ratio and right_rows > 0


@dataclass
class AdaptiveState:
    """Per-execution adaptive context threaded through the scheduler:
    the filter collector plus the decision log the EXPLAIN ANALYZE
    footer and tests read back."""
    collector: DynamicFilterCollector = field(
        default_factory=DynamicFilterCollector)
    decisions: List[ExchangeDecision] = field(default_factory=list)

    def record(self, decision: ExchangeDecision) -> None:
        self.decisions.append(decision)
        if decision.action == "broadcast":
            ADAPTIVE_METRICS.incr("exchange_broadcast_flips")
        elif decision.action == "swap_sides":
            ADAPTIVE_METRICS.incr("exchange_side_swaps")
        else:
            ADAPTIVE_METRICS.incr("exchange_kept")
