"""Fragment plan -> executable pipelines.

The TPU analog of the reference LocalExecutionPlanner
(presto-main-base/.../sql/planner/LocalExecutionPlanner.java:363: visitTableScan
:1612, visitAggregation :1360, visitJoin :1934) plus the Driver page loop
(operator/Driver.java:303,421-451).  Differences forced by XLA:

- Linear Filter/Project chains above a leaf are FUSED into one jitted function
  per batch (XLA fuses the elementwise work into one kernel), instead of an
  operator chain passing pages.
- Aggregation is a jitted scatter-update per batch over a persistent device
  table (operators.agg_update) with host-side salt retry on slot collisions.
- Joins materialize the build side on device, then stream probe batches
  through a jitted searchsorted probe with a static output capacity; probe
  overflow splits the probe batch and retries.
- All shapes static: (capacity, agg slots, join capacity) come from the
  ExecutionConfig, and jit caching is keyed by them.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import threading
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common.page import Page
from ..common.types import (BIGINT, BOOLEAN, DOUBLE, DecimalType, DoubleType,
                            RealType, Type, VarcharType, CharType)
from ..connectors import catalog, tpch
from ..spi.expr import (CallExpression, ConstantExpression, RowExpression,
                        VariableReferenceExpression)
from ..spi import plan as P
from .batch import (Batch, Column, batch_to_page, page_to_batch,
                    pages_to_batches)
from . import operators as ops
from .lowering import Lowering, canonical_name, expr_has_params
from .memory import (MemoryContext, MemoryExceededError, MemoryPool,
                     PartitionedSpillStore, QueryMemoryLimitExceededError,
                     batch_bytes)

DEFAULT_CAPACITY = 1 << 20
# ceiling on the materialized (keys + agg inputs) bytes for sort-based
# grouped aggregation; beyond it the scatter hash table takes over
SORT_AGG_MAX_BYTES = 6 << 30

# module-level jitted singletons: compiled once per process/shape, reused by
# every query (the compile-once/execute-many property that makes repeated
# queries cheap — the analog of the reference's reusable DriverFactories)
_jit_concat = jax.jit(lambda batches: _concat_batches(batches))
_jit_compact = jax.jit(ops.compact, static_argnums=1)


def _compact_concat(batches: List[Batch]) -> Batch:
    """Concatenate batches, dropping masked-out padding when it dominates.

    Operators that materialize their whole input (sort, window, join build)
    compile per merged shape; concatenating full-capacity padded batches
    after a selective filter yields huge mostly-dead arrays (e.g. 8M-row
    merges holding 80k live rows) whose sort kernels take ~50s to compile
    and dominate execution.  When under 1/4 of the merged rows are live,
    each batch is compacted (fixed per-capacity shapes, compiled once) and
    sliced to a power-of-two bucket, so downstream sorts compile at a small
    bucketed capacity shared across queries."""
    if len(batches) == 1:
        return batches[0]
    total_cap = sum(b.capacity for b in batches)
    counts = [int(c) for c in jax.device_get(  # lint: allow-host-sync
        [b.mask.sum() for b in batches])]
    if sum(counts) * 4 >= total_cap:
        return _jit_concat(batches)
    out = []
    for b, n in zip(batches, counts):
        if n == 0:
            continue
        bucket = _bucket_for(n) or 1 << (int(n) - 1).bit_length()
        out.append(b if bucket >= b.capacity
                   else _jit_compact(b, bucket))
    if not out:
        return batches[0]      # all rows masked: keep an all-dead batch
    if len(out) == 1:
        return out[0]
    return _jit_concat(out)
# coarse bucket set bounds the number of compiled shape variants for
# compacted batches (shared by every compaction site)
_COMPACT_BUCKETS = (1 << 12, 1 << 16, 1 << 18, 1 << 20)


def _bucket_for(live: int):
    """Smallest standard bucket holding `live` rows (None above the
    largest bucket)."""
    return next((s for s in _COMPACT_BUCKETS if s >= live), None)


def _maybe_compact(batch: Batch) -> Batch:
    """Compact a single mostly-dead batch (e.g. a sparse aggregation table)
    to a bucketed capacity so downstream sorts/joins/probes don't pay
    full-capacity costs.  One host sync for the live count."""
    live = int(jax.device_get(batch.mask.sum()))  # lint: allow-host-sync
    if live * 4 >= batch.capacity:
        return batch
    bucket = _bucket_for(live)
    if bucket is None or bucket >= batch.capacity:
        return batch
    return _jit_compact(batch, bucket)


_jit_sort = None
_jit_build = None
_jit_window = None


def _jits():
    global _jit_sort, _jit_build, _jit_window
    if _jit_sort is None:
        _jit_sort = jax.jit(ops.sort_batch, static_argnums=1)
        _jit_build = jax.jit(ops.build_table, static_argnums=(1,))
        _jit_window = jax.jit(ops.window_batch, static_argnums=(1, 2, 3))
    return _jit_sort, _jit_build, _jit_window


@dataclass
class ExecutionConfig:
    batch_rows: int = DEFAULT_CAPACITY      # scan page/batch capacity
    agg_slots: int = 4096                   # initial group table size
    join_out_capacity: int = 1 << 21        # probe output capacity
    max_agg_retries: int = 6
    splits_per_scan: int = 4
    # HBM accounting / spill (reference MemoryPool + spiller, exec/memory.py)
    memory_budget_bytes: Optional[int] = None   # None = unlimited
    spill_enabled: bool = True
    spill_partitions: int = 8
    # host-RAM ceiling for spill staging (None = unlimited); past it
    # whole buckets overflow to LZ4-compressed disk files (the second
    # spill tier) — config key spill.host-budget-bytes
    spill_budget_bytes: Optional[int] = None
    # directory for tier-2 spill files (config key spill.path); None =
    # the system temp dir.  Real deployments pin this to fast local SSD
    spill_path: Optional[str] = None
    # stage device->host spill transfers on a double-buffered background
    # thread so eviction overlaps the operator's continuing compute
    # (spillOverlapFraction meters the achieved overlap); False runs the
    # old synchronous staging.  Config key spill.async-staging
    spill_async_staging: bool = True
    # query-level memory ceiling (reference query.max-memory /
    # EXCEEDED_MEMORY_LIMIT): exceeding it is a TYPED USER error that
    # fails fast, unlike pool pressure which spill/arbitration absorb.
    # Revocable (spillable) reservations are exempt.  None = unlimited
    memory_max_query_bytes: Optional[int] = None
    # compile scan→filter/project→direct-agg chains into ONE XLA program
    # (fori_loop over split chunks): eliminates per-batch dispatch overhead
    fuse_pipelines: bool = True
    # EXPLAIN ANALYZE profiles the FUSED execution by default (chains emit
    # device-side row counters as extra jit outputs); True restores the
    # old behavior of disabling fusion so every operator streams through
    # its instrumented BatchSource (session property analyze_unfused)
    analyze_unfused: bool = False
    # compress exchange pages on the wire (SerializedPage COMPRESSED
    # marker; opt-in like the reference's exchange.compression-enabled —
    # same-host exchanges have no bandwidth to save, cross-host ones do)
    exchange_compression: bool = False
    # codec for COMPRESSED pages (reference exchange.compression-codec /
    # PagesSerdeFactory.java:69-80): LZ4 | SNAPPY | ZSTD | GZIP | ZLIB | NONE
    exchange_compression_codec: str = "LZ4"
    # grouped (lifespan) execution over connector co-bucketed tables
    # (reference Lifespan.java:30-37 / GroupedExecutionTagger /
    # session grouped_execution; exec/grouped.py): 0 = auto (engage when
    # the anchor keyspace exceeds AUTO_SPAN_THRESHOLD — the SF100-class
    # joins whose whole-table builds exceed HBM), 1 = off, N>=2 = force N
    # bucket lifespans
    grouped_lifespans: int = 0
    # lifespans staged AHEAD of the one the device is computing: bucket
    # k+1's split reads / on-the-fly column generation and host->HBM
    # transfers dispatch while bucket k's program runs (JAX async
    # dispatch keeps the device queue full).  0 = strictly serial — each
    # bucket's host work blocks on the previous bucket's consumption
    grouped_prefetch_depth: int = 1
    # distributed grouped stages: when a source stage is grouped-eligible
    # (exec/grouped.py stage_shards_lifespans), give every task the FULL
    # split set plus a disjoint round-robin subset of the bucket layout
    # (task i runs lifespans i, i+N, ...) — K lifespans spread across N
    # tasks/chips instead of replayed per task; per-bucket partial
    # aggregates merge at the FINAL stage exactly as same-task buckets do
    grouped_lifespan_sharding: bool = True
    # intra-task driver concurrency (reference task_concurrency /
    # driver-per-split, SqlTaskExecution.java:548): leaf scans drain
    # splits on this many threads through exec/local_exchange.py, and the
    # worker task overlaps pipeline drain with page serialization.  >1
    # overlaps HOST work with DEVICE dispatch; the chip itself serializes
    # kernels either way.  NOTE: a pipeline the whole-program fuser
    # accepts (fuse_pipelines=True, all-device scan chain) runs as ONE
    # XLA program with no per-batch host work to overlap — driver threads
    # apply to the STREAMING paths (host columns, windows, sorts, spills).
    # Measured on chip (round 5): a single-chip streaming group-by showed
    # no wall-clock win at 4 drivers (5.50s vs 5.56s) because the device
    # serializes kernels; the default stays 1, and >1 remains for
    # multi-core HOST work (spill IO, page serde, host-generated columns)
    task_concurrency: int = 1
    # -- fault tolerance (distributed HTTP runtime) -----------------------
    # per-lineage retry attempts for FAILED/lost remote tasks (reference
    # presto-spark ErrorClassifier retries; 0 = fail-fast streaming MPP).
    # >0 additionally makes worker output buffers RETAIN acknowledged
    # pages until task teardown, so a restarted consumer replays its
    # input from token 0 — memory-for-replayability; the durable
    # alternative is the batch scheduler's shuffle staging
    remote_task_retry_attempts: int = 2
    # how long an exchange client keeps retrying an unreachable source
    # (exponential backoff + jitter) before declaring the producer lost
    # (reference exchange.max-error-duration, Configs.h)
    exchange_max_error_duration_s: float = 60.0
    # concurrent pullers per ExchangeClient (reference
    # exchange.client-threads, ExchangeClientConfig.java): each upstream
    # location gets its own puller (capped here), so pulls + LZ4 decode
    # parallelize across producers and the consuming pipeline computes
    # while pages stream in
    exchange_client_threads: int = 4
    # bound on bytes buffered inside one ExchangeClient (reference
    # exchange.max-buffer-size): pullers park when the arrival queue holds
    # this much decoded data — producer backpressure end to end
    exchange_max_buffer_bytes: int = 32 << 20
    # target response size for the results endpoint (reference
    # exchange.max-response-size): producers coalesce small serialized
    # pages up to ~this many bytes per pull round, and the client sends it
    # as an X-Presto-Max-Size cap, so tiny-page stages stop paying a
    # request round trip per page
    exchange_max_response_bytes: int = 1 << 20
    # retry policy (reference retry-policy=QUERY|TASK, fault-tolerant
    # execution over a spooled exchange): "query" keeps the streaming
    # restart-with-ancestors behavior over retained in-memory buffers;
    # "task" spools every stage's output pages durably through
    # worker/spooling.py (host-RAM staging -> LZ4 block files under
    # spool.path/spill.path, charged revocable, retained past task
    # completion) so a failed task is retried ALONE on a surviving
    # worker with no ancestor-stage restart.  Config key retry-policy /
    # session retry_policy
    retry_policy: str = "query"
    # durable spool directory under retry-policy=task (config key
    # spool.path); None falls back to spill_path, then the system temp
    # dir.  Spool block files survive a graceful worker exit
    spool_path: Optional[str] = None
    # host-RAM ceiling for spool staging per task; past it (or under
    # memory-pool revocation) staged pages overflow to the LZ4 block
    # file.  Config key spool.staging-budget-bytes
    spool_staging_budget_bytes: int = 16 << 20
    # query wall-clock budget (reference query.max-execution-time /
    # QueryTracker.enforceTimeLimits): the coordinator mints the typed
    # non-retryable EXCEEDED_TIME_LIMIT user error when it elapses and
    # forwards each task's remaining budget via the
    # X-Presto-Task-Deadline header, which the TaskManager reaper and
    # the pipeline drain loops enforce.  0 = no deadline
    query_max_execution_time_s: float = 0.0
    # coordinator worker-loss trigger on heartbeat AGE (config key
    # failure-detector.heartbeat-timeout): a worker whose last
    # successful probe is older than this is dropped from scheduling
    # even if its transport streak has not tripped.  0 = streak-only
    failure_detector_heartbeat_timeout_s: float = 0.0
    # chaos hook: probability a task fails at start.  The roll is
    # deterministic per task id, so a retry (new attempt id) rolls
    # independently and chaos tests replay exactly
    fault_injection_probability: float = 0.0
    # plan sanity/type validation (presto_tpu/analysis, the reference
    # PlanChecker analog): "on" validates post-plan / post-optimize /
    # post-fragment; "strict" additionally validates after every
    # optimizer-rule firing; "off" disables.  Violations raise the
    # non-retryable PLAN_VALIDATION error
    plan_validation: str = "on"
    # runtime lock-order validation (common/locks.py, the dynamic half of
    # analysis/concurrency.py): task driver threads record per-thread
    # acquisition stacks, raise LockOrderError on rank inversion, and
    # meter hold/contention into /v1/metrics presto_tpu_lock_*.  Worker
    # property debug.lock-validation; session key lock_validation
    lock_validation: bool = False
    # -- HBM-resident columnar storage (presto_tpu/storage) ---------------
    # scans materialize device-generated columns once per process into an
    # encoded resident cache with zone maps; False = regenerate per chunk
    storage_enabled: bool = True
    # LRU budget for resident encoded bytes (charged to the store's
    # MemoryPool; over-budget columns fall back to on-the-fly generation)
    storage_budget_bytes: Optional[int] = 6 << 30
    # a column whose PLAIN bytes exceed this is never materialized (the
    # build transiently holds ~2x plain bytes)
    storage_max_column_bytes: int = 1 << 30
    # zone-map granularity in rows: chunk pruning aggregates the zones
    # covering each scan chunk, so finer zones prune better and cost
    # (n_rows / zone_rows) host floats per column
    storage_zone_rows: int = 1 << 16
    # dictionary/RLE encodings for resident columns; False = plain only
    storage_encodings: bool = True
    # -- exchange fabric (parallel/fabric.py) -----------------------------
    # which fabric hashed remote-exchange edges ride (reference analog:
    # a per-edge shuffle-transport choice): "auto" picks the ICI
    # all_to_all whenever producer+consumer stages can be pinned 1:1 to
    # one mesh (the scheduler CHOOSES task counts to fit), "http" forces
    # the PR 4 ExchangeClient page path, "ici" requests ICI and falls
    # back to http (with a recorded fallback) when the edge is
    # ineligible.  Config key exchange.fabric / session exchange_fabric
    exchange_fabric: str = "auto"
    # chunk granularity of the chunked ICI exchange (exchange.ici-chunk-rows):
    # each producer's rows split into fixed-size chunks whose collectives
    # dispatch back-to-back with NO host sync between them, so chunk k+1's
    # all_to_all is in flight while the consumer computes on chunk k.
    # Fixed chunk shapes also mean ONE compiled exchange program reused
    # across stages (no re-padding to a fresh per-stage global max).
    # 0 = auto-tune: the scheduler picks the next run's chunk size from
    # the observed compute/collective overlap_fraction in FabricMetrics
    # (parallel/fabric.py IciChunkTuner, multiplicative feedback)
    ici_chunk_rows: int = 0
    # -- Pallas scan kernel (exec/kernels) --------------------------------
    # the fused scan->filter->project->partial-agg hot path: "pallas"
    # requests the hand-written Pallas kernel (decode + prefix-sum
    # compaction + subtile aggregation in one VMEM-resident grid pass),
    # "xla" keeps the jnp fused chain, "auto" picks Pallas exactly when
    # the backend is a real TPU AND the chain is eligible (direct-mode
    # agg, resident encoded columns, aligned chunks) — off-TPU the
    # kernel only runs in interpret-mode emulation, which is never a
    # performance win, so "auto" declines with Backend and tests pin
    # "pallas" to exercise it.  Ineligibility is metered per scan as
    # kernelDeclined{reason} runtime-stats counters.  Config key
    # scan.kernel / session scan_kernel
    scan_kernel: str = "auto"
    # DMA staging discipline for the kernel's encoded input slabs:
    # "single" streams each grid block through the BlockSpec pipeline
    # as before; "double" stages per-row slabs through a manually
    # double-buffered VMEM scratch (pltpu.make_async_copy) so block
    # k+1's HBM copy overlaps block k's decode/aggregate compute.  The
    # achieved prefetch coverage is metered as kernelDmaOverlapFraction.
    # Config key scan.kernel-dma / session scan_kernel_dma
    scan_kernel_dma: str = "single"
    # -- per-query device profiler capture (telemetry/profiler.py) --------
    # session property `profile = true` wraps THIS query's execution in
    # jax.profiler.trace() writing a TensorBoard-loadable trace dir under
    # profile_dir; the path lands on QueryInfo and the EXPLAIN ANALYZE
    # footer.  Best-effort: profiler failures never fail the query.
    profile: bool = False
    # Config key telemetry.profile-dir; "" disables capture entirely
    profile_dir: str = "/tmp/presto_tpu_profiles"
    # -- adaptive query execution (exec/adaptive.py) ----------------------
    # master switch for runtime dynamic filters (config key
    # optimizer.dynamic-filtering / session dynamic_filtering): completed
    # build-side stages publish key-domain summaries that prune
    # downstream scans at the zone-map level and through a traced row
    # filter (bounds ride as jit args — no recompile on arrival);
    # False = intra-task probe-side narrowing only
    dynamic_filtering: bool = True
    # bounded wall a remote scan task waits for an expected summary
    # before proceeding unfiltered (dynamic-filtering.wait-timeout); a
    # late or lost filter costs pruning opportunity, never a deadlock
    dynamic_filtering_wait_timeout_s: float = 0.5
    # distinct-value cap for exact set summaries
    # (dynamic-filtering.max-distinct-values); past it a summary carries
    # min/max bounds only
    dynamic_filtering_max_distinct: int = 256
    # re-decide broadcast-vs-partitioned exchange (and INNER join sides)
    # at stage boundaries from OBSERVED build cardinality (config key
    # adaptive.exchange / session adaptive_exchange)
    adaptive_exchange: bool = True
    # seed task counts, agg slot sizing, and admission memory estimates
    # from matching query-history records keyed on the canonical plan
    # template (adaptive.history-sizing / session adaptive_history_sizing)
    adaptive_history_sizing: bool = False
    # observed group count from a prior run of the same plan template
    # (set by the runner's history-sizing pass, never by hand): when
    # present it REPLACES the optimizer's group estimate for aggregation
    # table sizing.  A dataclass field so the plan-cache config
    # fingerprint re-keys compiled plans on a changed hint.
    history_agg_groups: Optional[int] = None
    # -- serving plane (presto_tpu/serving) -------------------------------
    # share jitted scan/filter/project step callables across DIFFERENT
    # plans by subtree structural key (serving/fragments.py): queries
    # sharing a scan→filter→agg subchain reuse one compiled artifact.
    # Only engages for local compilers (task-scoped shared-jit caches
    # keep their node-id keys); a fingerprinted field, so flipping it
    # re-keys the canonical plan cache
    fragment_share: bool = True


# legal scan.kernel / scan_kernel values (worker/properties.py and the
# session-property validation both check against this)
SCAN_KERNEL_MODES = ("xla", "pallas", "auto")

# legal scan.kernel-dma / scan_kernel_dma values
SCAN_KERNEL_DMA_MODES = ("single", "double")

# legal retry-policy / retry_policy values (worker/properties.py and the
# session-property validation both check against this)
RETRY_POLICY_MODES = ("query", "task")


def tuned_config(**overrides) -> "ExecutionConfig":
    """The server/runner default ExecutionConfig: 64K-row scan batches and
    256K-row join output keep HBM footprint and dispatch count balanced on
    one chip.  Single source of truth — WorkerServer, LocalQueryRunner,
    TaskManager, and the etc-dir properties loader all start from this."""
    return ExecutionConfig(batch_rows=1 << 16, join_out_capacity=1 << 18,
                           **overrides)


@dataclass
class TaskContext:
    """Execution context for one task: configuration + split assignment."""
    config: ExecutionConfig = field(default_factory=ExecutionConfig)
    # table-scan node id -> list of splits this task owns
    splits: Dict[str, List[tpch.TpchSplit]] = field(default_factory=dict)
    # remote-source node id -> iterator of host Pages (exchange input)
    remote_pages: Dict[str, Callable[[], Iterator[Tuple[Page, List[str], List[Type]]]]] = field(default_factory=dict)
    # remote-source node id -> iterator of DEVICE Batches (ICI exchange
    # input: rows arrived via all_to_all, no host round-trip); wins over
    # remote_pages when both are present
    remote_batches: Dict[str, Callable[[], Iterator["Batch"]]] = field(default_factory=dict)
    # this task's index in its stage: namespaces AssignUniqueId across tasks
    task_index: int = 0
    # per-STAGE shared jitted-program cache (scheduler-provided): the N
    # tasks of a stage compile byte-identical step closures, and Python
    # tracing is GIL-serialized — without sharing, an N-task stage pays
    # N traces on one core (measured 8x the single-task wall on the
    # 8-device dryrun).  The reference analog: tasks share the
    # coordinator-shipped plan; here they share the XLA trace.
    shared_jits: Optional[Dict] = None
    # HBM byte accounting for this task (created by PlanCompiler if absent)
    memory: Optional[MemoryPool] = None
    # EXPLAIN ANALYZE: node id -> {rows, wall_s, batches} (None = disabled)
    stats: Optional[Dict[str, dict]] = None
    # lifespan sharding (exec/grouped.py stage_shards_lifespans): when set
    # to (shard_index, shard_count), this task owns bucket lifespans
    # shard_index, shard_index+shard_count, ... of the grouped layout;
    # its scans hold the FULL split set, and if grouped execution fails
    # to engage at runtime only shard 0 runs the compiled fallback (the
    # aggregation gen() guard) so no rows are duplicated
    grouped_shard: Optional[Tuple[int, int]] = None
    # runner-provided RuntimeStats sink (utils/runtime_stats.py): grouped
    # execution records per-bucket generation/compute walls here
    runtime_stats: Optional[object] = None
    # serving tier (sql/canonical.py): the bound-parameter vector for this
    # execution.  `params` holds device scalars that ride parameterized
    # steps as jit arguments (so one executable serves every binding);
    # `params_fingerprint` holds the host values, appended to
    # value-sensitive result-cache keys (materialized builds) whenever the
    # cached subtree contains parameter leaves
    params: Optional[Tuple] = None
    params_fingerprint: Optional[Tuple] = None
    # runtime dynamic-filter summaries delivered by the scheduler (or
    # the worker task-update channel): filter id -> DynamicFilterSummary
    # wire dict (exec/adaptive.py).  The dict object is SHARED and
    # mutated in place on delivery; scans read it lazily at split drain
    # time, so a summary landing before a split's chunk list resolves
    # still prunes (late binding, no recompile)
    dynamic_filters: Dict[str, dict] = field(default_factory=dict)


def _var_types(variables) -> List[Type]:
    return [v.type for v in variables]


def output_schema(node: P.PlanNode) -> Tuple[List[str], List[Type]]:
    vs = node.output_variables
    return [v.name for v in vs], [v.type for v in vs]


# ---------------------------------------------------------------------------
# batch-source compilation (recursive)
# ---------------------------------------------------------------------------

class BatchSource:
    """A compiled sub-pipeline that can be iterated (possibly repeatedly)."""

    def __init__(self, fn: Callable[[], Iterator[Batch]],
                 names: List[str], types: List[Type]):
        self._fn = fn
        self.names = names
        self.types = types

    def batches(self) -> Iterator[Batch]:
        return self._fn()


class _RevocableBuildBuffer:
    """Join build-side staging whose reservation is REVOCABLE: under
    memory pressure the arbitrator converts the collected device batches
    into the partitioned host spill store (the grace-join input) via the
    registered callback instead of the query failing (reference:
    HashBuilderOperator's revocable memory + MemoryRevokingScheduler).

    Locking discipline — the two rules that keep arbitration deadlock-
    free: (1) `add` reserves BEFORE taking the buffer lock, because the
    arbitrator may pick this very holder as its victim while the
    reservation waits; (2) the revoke callback never blocks — if the
    buffer is mid-mutation it declines (returns 0) and the arbitrator
    moves to the next victim."""

    def __init__(self, compiler: "PlanCompiler", keys, spill_enabled: bool):
        self._compiler = compiler
        self._pool = compiler.ctx.memory
        self._keys = list(keys)
        self._spill_enabled = spill_enabled
        self._lock = threading.Lock()
        self._finished = False
        self.collected: List[Batch] = []
        self.spill = None
        self._reserved = 0
        self._table_bytes = 0
        self._holder = self._pool.register_revocable(
            "join-build", self._revoke)

    # -- arbitrator-facing -------------------------------------------------
    def _revoke(self) -> int:
        if not self._spill_enabled:
            return 0
        if not self._lock.acquire(blocking=False):
            return 0   # mid-mutation: decline, never block
        try:
            if self._finished or not self._reserved:
                return 0
            return self._spill_locked()
        finally:
            self._lock.release()

    def _spill_locked(self) -> int:
        freed = self._reserved
        if self.spill is None:
            self.spill = self._compiler._new_spill_store()
        for cb in self.collected:
            self.spill.add(cb, self._keys)
        self.collected = []
        if freed:
            self._holder.free(freed)
            self._reserved = 0
        return freed

    # -- build-loop-facing -------------------------------------------------
    def add(self, b: Batch) -> None:
        nb = batch_bytes(b)
        ok = self.spill is None and self._holder.try_reserve(nb)
        with self._lock:
            if ok and self.spill is None:
                self.collected.append(b)
                self._reserved += nb
                return
            if ok:
                # revoked between the reservation and the lock: the
                # batch is headed for the store, give the bytes back
                self._holder.free(nb)
            if self.spill is None:
                if not self._spill_enabled:
                    raise MemoryExceededError(
                        f"join build side exceeds memory budget "
                        f"{self._pool.budget} bytes and spill is disabled")
                self._spill_locked()
            self.spill.add(b, self._keys)

    def seed(self, batches: List[Batch]) -> None:
        """Pre-collected batches with no reservation (the fused
        materialization path, which only runs unbudgeted)."""
        with self._lock:
            self.collected.extend(batches)

    def finish(self):
        """-> (collected, spill).  Stops revocation: past this point the
        batches feed the device hash table, which spilling the staging
        copy cannot shrink — so the bytes stop being revocable and are
        re-charged as plain user memory (covering the table until
        close()).  The re-charge is where the `query.max-memory` ceiling
        fires (typed, fail-fast; reference: revocable memory converts to
        user memory when HashBuilder finishes revoking); plain pool
        pressure at the handoff instead converts the build into a grace
        hash join spill."""
        with self._lock:
            if self._reserved and self.spill is None:
                n = self._reserved
                self._holder.free(n)
                self._reserved = 0
                if self._pool.try_reserve(n):
                    self._table_bytes = n
                elif self._spill_enabled:
                    self._spill_locked()
                else:
                    self._finished = True
                    raise MemoryExceededError(
                        f"join build table of {n} bytes exceeds memory "
                        f"budget {self._pool.budget} bytes and spill is "
                        f"disabled")
            self._finished = True
            return self.collected, self.spill

    def close(self) -> None:
        with self._lock:
            self._finished = True
            self._holder.close()   # frees whatever is still reserved
            if self._table_bytes:
                self._pool.free(self._table_bytes)
                self._table_bytes = 0
            self.collected = []
            self._reserved = 0


def _fragment_batch_sig(batch: Batch) -> tuple:
    """Hashable digest of the first-batch column structure a step's
    expression resolution depends on (laziness, dictionary presence,
    dtypes) — part of the fragment_jit cache key, so structurally equal
    subtrees whose resolution would differ never share a callable.
    Shape is deliberately EXCLUDED: jax.jit retraces per aval."""
    out = []
    for n in sorted(batch.columns):
        c = batch.columns[n]
        out.append((n, str(c.values.dtype), c.values.ndim,
                    None if c.dictionary is None else len(c.dictionary),
                    c.lazy, c.nulls is not None, c.lengths is not None))
    return tuple(out)


class PlanCompiler:
    def __init__(self, ctx: TaskContext):
        if ctx.memory is None:
            # a fresh query-level context over its own pool: the
            # query.max-memory ceiling applies even when nobody handed us
            # a worker-shared pool (LocalQueryRunner, EXPLAIN ANALYZE)
            ctx.memory = MemoryContext(
                MemoryPool(ctx.config.memory_budget_bytes), "query",
                max_bytes=ctx.config.memory_max_query_bytes)
        self.ctx = ctx
        self._sources: Dict[str, BatchSource] = {}
        self.lowering = Lowering()
        self._jit_cache: Dict = {}
        # batch buffers of shared (multi-consumer) sources; cleared per
        # execution (see _share)
        self._shared_states: List[dict] = []

    def shared_jit(self, key, fn, **kw):
        """jax.jit with a per-stage shared cache: tasks of one stage share
        ONE traced program per (node id, purpose) key instead of each
        re-tracing an identical closure (TaskContext.shared_jits).  Falls
        back to a plain jit when no stage cache is installed."""
        cache = self.ctx.shared_jits
        if cache is None:
            return jax.jit(fn, **kw)
        ent = cache.get(key)
        if ent is None:
            ent = cache.setdefault(key, jax.jit(fn, **kw))
        return ent

    def fragment_jit(self, node, purpose: str, fn, extra=(), **kw):
        """Fragment-level executable sharing (serving/fragments.py):
        jitted step callables for linear scan/filter/project fragments
        are cached PROCESS-GLOBALLY on the subtree's structural key, so
        two different plans sharing a scan→filter subchain share one
        compiled artifact.  Falls back to shared_jit whenever a stage
        cache is installed (distributed tasks) or the fragment_share
        knob is off.  `extra` must carry every host constant the traced
        closure bakes in beyond (subtree, config) — chunk capacity,
        first-batch laziness/dictionary signature — since a false share
        would execute the wrong program, while a missed share only costs
        one retrace."""
        cfg = self.ctx.config
        if self.ctx.shared_jits is not None or not cfg.fragment_share:
            return self.shared_jit((node.id, purpose) + tuple(extra), fn,
                                   **kw)
        from ..serving.fragments import FRAGMENT_JIT_CACHE
        from ..sql.canonical import config_fingerprint
        key = (purpose, P.structural_key(node), tuple(extra),
               config_fingerprint(cfg))
        return FRAGMENT_JIT_CACHE.get_or_build(
            key, lambda: jax.jit(fn, **kw))

    def _new_spill_store(self, salt: Optional[int] = None
                         ) -> PartitionedSpillStore:
        """One place wires the two-tier + async-staging spill config into
        every operator's store, so spill bytes/walls always land in this
        query's RuntimeStats and memory context."""
        cfg = self.ctx.config
        kw = {} if salt is None else {"salt": salt}
        return PartitionedSpillStore(
            cfg.spill_partitions, budget_bytes=cfg.spill_budget_bytes,
            spill_path=cfg.spill_path, stats=self.ctx.runtime_stats,
            async_staging=cfg.spill_async_staging, pool=self.ctx.memory,
            **kw)

    # -- public -----------------------------------------------------------
    def compile(self, root: P.PlanNode) -> BatchSource:
        return self._compile(root)

    def run_to_pages(self, root: P.PlanNode) -> Iterator[Page]:
        for st in self._shared_states:
            st.update(buf=[], it=None, done=False)
        src = self.compile(root)
        for batch in src.batches():
            page = batch_to_page(batch, src.names, src.types)
            if page.position_count:
                yield page

    def run_to_batches(self, root: P.PlanNode) -> Iterator[Batch]:
        """Device-resident drain of the fragment (the ICI exchange path:
        output rows stay in HBM for the cross-device shuffle)."""
        for st in self._shared_states:
            st.update(buf=[], it=None, done=False)
        src = self.compile(root)
        yield from src.batches()

    # -- dispatch ---------------------------------------------------------
    def _compile(self, node: P.PlanNode) -> BatchSource:
        # memoized per node id: replayed subtrees (decorrelation deep
        # copies share ids) and re-executions reuse the same BatchSource,
        # so its cached jitted steps stay warm
        cached = self._sources.get(node.id)
        if cached is not None:
            # a second consumer of the same subtree: tee its batches so the
            # subtree executes ONCE per query (decorrelated plans replay
            # whole join chains several times — TPC-H Q2/Q21 shape; the
            # reference gets this for free from its CTE materialization)
            self._share(cached)
            return cached
        m = getattr(self, "_compile_" + type(node).__name__, None)
        if m is None:
            raise NotImplementedError(f"no compiler for {type(node).__name__}")
        src = m(node)
        if self.ctx.stats is not None:
            src = self._instrument(node, src)
        self._sources[node.id] = src
        return src

    def _share(self, src: BatchSource) -> None:
        """Convert a BatchSource into a teeing source: the first consumer's
        batches are buffered (device-resident) and replayed to later — or
        interleaved — consumers, so multi-consumer subtrees execute once."""
        if getattr(src, "_shared", False):
            return
        src._shared = True
        inner_fn = src._fn
        state = {"buf": [], "it": None, "done": False}
        self._shared_states.append(state)

        def shared_fn():
            i = 0
            while True:
                if i < len(state["buf"]):
                    yield state["buf"][i]
                    i += 1
                    continue
                if state["done"]:
                    return
                if state["it"] is None:
                    state["it"] = iter(inner_fn())
                try:
                    b = next(state["it"])
                except StopIteration:
                    state["done"] = True
                    continue
                state["buf"].append(b)
                yield b
                i += 1
        src._fn = shared_fn

    def _instrument(self, node: P.PlanNode, src: BatchSource) -> BatchSource:
        """EXPLAIN ANALYZE wrapper: cumulative wall time (includes
        children, like the reference's operator getOutput accounting),
        output row counts, and estimated output bytes per plan node."""
        stats = self.ctx.stats
        # 8 value bytes + 1 null byte per column: an ESTIMATE (dictionary
        # and lazy columns are cheaper on device), stable across paths so
        # fused/unfused byte counts compare
        row_bytes = 9 * max(1, len(node.output_variables))

        def gen():
            import time
            ent = stats.setdefault(
                node.id, {"rows": 0, "wall_s": 0.0, "batches": 0})
            ent.setdefault("bytes", 0)
            ent.setdefault("operatorType", type(node).__name__)
            it = src.batches()
            while True:
                t0 = time.perf_counter()  # lint: allow-wall-clock
                try:
                    b = next(it)
                except StopIteration:
                    ent["wall_s"] += time.perf_counter() - t0  # lint: allow-wall-clock
                    return
                ent["wall_s"] += time.perf_counter() - t0  # lint: allow-wall-clock
                rows = int(b.mask.sum())
                ent["rows"] += rows
                ent["bytes"] += rows * row_bytes
                ent["batches"] += 1
                yield b
        out = BatchSource(gen, src.names, src.types)
        # the fused-chain assembler reads scan metadata off the compiled
        # source (assemble_chain); the wrapper must not hide it, or
        # ANALYZE would silently decline fusion at every scan
        meta = getattr(src, "fused_scan", None)
        if meta is not None:
            out.fused_scan = meta
        return out

    # -- leaves -----------------------------------------------------------
    # HBM-resident storage of device-generated columns lives in
    # presto_tpu/storage: generating a column is a uint64 splitmix hash
    # per row — 64-bit integer multiplies are EMULATED on the TPU vector
    # unit and dominate fused-scan wall clock — so whole-table columns
    # materialize ONCE into an encoded LRU cache with zone maps, and
    # every scan chunk becomes a slice_decode.

    def _compile_TableScanNode(self, node: P.TableScanNode) -> BatchSource:
        names = [v.name for v in node.outputs]
        types = [v.type for v in node.outputs]
        columns = [node.assignments[v].name for v in node.outputs]
        th = node.table
        sf = dict(th.extra).get("scaleFactor", 0.01)
        splits = self.ctx.splits.get(node.id)
        if splits is None:
            splits = catalog.make_splits(th.table_name, sf,
                                         self.ctx.config.splits_per_scan,
                                         th.connector_id)
        cap = self.ctx.config.batch_rows
        table = th.table_name
        cid = th.connector_id
        from ..connectors import device_gen

        # split columns into device-generated (a jitted counter-hash kernel
        # materializes them straight into HBM — no host generation, no
        # host->device transfer) and host-generated (strings, small dims)
        dev: List[Tuple[str, str, str]] = []   # (out name, column, kind)
        host: List[Tuple[str, str]] = []
        for name, colname in zip(names, columns):
            if (table, colname) in catalog.OPEN_DOMAIN:
                dev.append((name, colname, "lazy"))
            elif device_gen.supported(cid, table, colname):
                dev.append((name, colname, "gen"))
            else:
                host.append((name, colname))

        i32 = {colname: (colname.endswith("date")
                         or catalog.column_type(table, colname, cid).storage
                         == "INT_ARRAY")
               for _n, colname, kind in dev if kind == "gen"}

        # HBM-resident whole-table columns (presto_tpu/storage): the
        # decision is made at trace time, so cache eligible columns BEFORE
        # the kernels compile.  Budgeted runs keep the pure-kernel path
        # (cache residency is outside their accounting).  A column the
        # store cannot fit (tight storage budget, SF100-class size) comes
        # back None and stays on-the-fly — graceful degradation, never
        # MemoryExceededError.
        cfg = self.ctx.config
        cached_cols: Dict[str, object] = {}
        zone_maps: Dict[str, object] = {}
        if not self.ctx.memory.limited and dev and cfg.storage_enabled:
            from ..storage import get_store
            store = get_store(cfg.storage_budget_bytes,
                              cfg.storage_max_column_bytes)
            n_rows = catalog.table_row_count(table, sf, cid)
            for _name, colname, kind in dev:
                if kind != "gen":
                    continue
                ent = store.get_or_build(
                    cid, table, colname, sf, n_rows, cap, i32[colname],
                    zone_rows=cfg.storage_zone_rows,
                    encodings=cfg.storage_encodings)
                if ent is not None:
                    cached_cols[colname] = ent.column
                    zone_maps[colname] = ent.zones
        # advisory chunk-skip metadata: conjuncts the optimizer pushed
        # down (plan_scan_pushdown) — the parent FilterNode still runs,
        # so pruning only has to be conservative, not exact
        pushdown = [dict(e) for e in getattr(node, "pushdown", ())]
        # runtime dynamic filters this scan may consume
        # (plan_runtime_filter_pushdown); summaries land in
        # ctx.dynamic_filters and are read LAZILY at drain time
        runtime_filters = ([dict(e) for e in
                            getattr(node, "runtime_filters", ())]
                           if cfg.dynamic_filtering else [])

        def dyn_summaries():
            if not runtime_filters:
                return None
            return self.ctx.dynamic_filters or None

        def make_factory(cap2):
            """Pure scan kernel at an arbitrary chunk capacity (fused join
            chains shrink the chunk so in-loop fanout expansion stays within
            the configured batch footprint)."""
            def make(pos, valid, cached):
                # `cached` carries the HBM-resident whole-table columns AS
                # AN ARGUMENT pytree: closing over the arrays would embed
                # hundreds of MB as XLA literal constants and blow up
                # compilation
                idx0 = jnp.arange(cap2, dtype=jnp.int64)
                live = idx0 < valid
                idx = pos + idx0
                outs = {}
                for name, colname, kind in dev:
                    if kind == "lazy":
                        # padding must hold a valid row id (materializers
                        # run over the full capacity)
                        outs[name] = jnp.where(live, idx, 0)
                        continue
                    arr = cached.get(colname)
                    if arr is not None:
                        # ResidentColumn: encoded HBM bytes stream out,
                        # decode (dict gather / RLE searchsorted) runs in
                        # vector registers — late materialization
                        v = arr.slice_decode(pos, cap2)
                    else:
                        v = device_gen.column(cid, table, colname, sf, idx)
                        if v.dtype == jnp.int64 and i32[colname]:
                            v = v.astype(jnp.int32)
                    outs[name] = jnp.where(live, v, jnp.zeros((), v.dtype))
                return outs, live
            return make

        make = make_factory(cap)
        # the scan kernel is a pure function of (table identity incl.
        # scale factor — all inside the node's structural key — chunk
        # capacity, config); resident columns ride as an argument pytree,
        # so plans sharing this scan share one compiled program.  The
        # ACTUAL output variable names are baked into the closure but
        # canonicalized away by the structural key, so they join the key
        dev_make = self.fragment_jit(node, "scan_make", make,
                                     extra=(cap, tuple(names)))

        def split_chunks(split):
            out = []
            p = split.start
            while p < split.end:
                out.append((p, min(cap, split.end - p)))
                p += cap
            if zone_maps and pushdown:
                # zone-map chunk skipping (host numpy over build-time
                # stats); the FilterNode above re-filters survivors, so
                # skipping is free of correctness burden beyond the
                # conservative unsatisfiability rules
                from ..storage import prune_chunks
                out, _skipped = prune_chunks(out, zone_maps, pushdown,
                                             self.ctx.params_fingerprint,
                                             dyn_summaries(),
                                             keep_one=False)
            return out

        # traced row-level runtime filter: summary bounds ride the jitted
        # step as SCALAR ARGUMENTS (the PR 7 parameterization idiom), so
        # one compiled program serves every bound and a summary arriving
        # between splits engages without a recompile.  Only plain integer
        # device columns qualify — dict codes and lazy row ids are not in
        # stored key units.  A dropped row is one the annotated join
        # would drop anyway (plan_runtime_filter_pushdown's guarantee).
        rf_cols = []
        if runtime_filters:
            for e in runtime_filters:
                for v, ch in node.assignments.items():
                    if ch.name == e["column"]:
                        rf_cols.append((e["id"], v.name))

        def make_rf_step(name):
            def _step(batch, lo, hi):
                c = batch.columns[name]
                keep = batch.mask & (c.values >= lo) & (c.values <= hi)
                return batch.with_mask(keep), keep.sum(), batch.mask.sum()
            return self.shared_jit((node.id, "rf", name), _step)

        def apply_runtime_filters(batches):
            engaged = False
            rows_in = rows_out = None
            for b in batches:
                dyn = dyn_summaries()
                if dyn:
                    for fid, vname in rf_cols:
                        s = dyn.get(fid)
                        if not (isinstance(s, dict)
                                and isinstance(s.get("min"), int)
                                and isinstance(s.get("max"), int)):
                            continue
                        c = b.columns.get(vname)
                        if c is None or c.dictionary is not None \
                                or c.lazy is not None \
                                or not jnp.issubdtype(c.values.dtype,
                                                      jnp.integer):
                            continue
                        step = make_rf_step(vname)
                        b, kept, inn = step(b, jnp.asarray(
                            s["min"], c.values.dtype),
                            jnp.asarray(s["max"], c.values.dtype))
                        if not engaged:
                            engaged = True
                            from .adaptive import ADAPTIVE_METRICS
                            ADAPTIVE_METRICS.incr("filters_applied")
                        rows_in = inn if rows_in is None else rows_in + inn
                        rows_out = (kept if rows_out is None
                                    else rows_out + kept)
                yield b
            if engaged and rows_in is not None:
                inn, out = jax.device_get(  # lint: allow-host-sync
                    (rows_in, rows_out))
                from .adaptive import ADAPTIVE_METRICS
                ADAPTIVE_METRICS.incr("filter_rows_in", int(inn))
                ADAPTIVE_METRICS.incr("filter_rows_pruned",
                                      int(inn) - int(out))
                rs = self.ctx.runtime_stats
                if rs is not None:
                    rs.add("dynamicFilterRowsIn", int(inn))
                    rs.add("dynamicFilterRowsPruned", int(inn) - int(out))

        def split_gen(split):
                for pos, n in split_chunks(split):
                    cols = {}
                    if dev:
                        douts, dmask = dev_make(jnp.int64(pos),
                                                jnp.int64(n), cached_cols)
                        for name, colname, kind in dev:
                            if kind == "lazy":
                                cols[name] = Column(
                                    douts[name], None, None,
                                    (split.connector, table, colname,
                                     split.sf))
                            else:
                                cols[name] = Column(
                                    douts[name], None,
                                    device_gen.dictionary(cid, table,
                                                          colname))
                    for name, colname in host:
                        raw = catalog.generate_column(
                            table, colname, split.sf, pos, n,
                            split.connector)
                        nulls = None
                        if isinstance(raw, catalog.HostColumn):
                            if raw.nulls is not None:
                                nbuf = np.zeros(cap, dtype=bool)
                                nbuf[:n] = raw.nulls
                                nulls = jnp.asarray(nbuf)
                            raw = raw.values
                        if isinstance(raw, tuple):
                            codes, values = raw
                            buf = np.zeros(cap, dtype=np.int32)
                            buf[:n] = codes
                            cols[name] = Column(jnp.asarray(buf), nulls,
                                                tuple(values))
                        else:
                            if raw.dtype == np.bool_:
                                dtype = np.bool_
                            elif raw.dtype in (np.float64, np.float32):
                                dtype = np.float64
                            elif (raw.dtype == np.int32
                                  or colname.endswith("date")
                                  or catalog.column_type(
                                      table, colname,
                                      split.connector).storage
                                  == "INT_ARRAY"):
                                dtype = np.int32
                            else:
                                dtype = np.int64
                            buf = np.zeros(cap, dtype=dtype)
                            buf[:n] = raw
                            cols[name] = Column(jnp.asarray(buf), nulls)
                    if dev:
                        mask = dmask
                    else:
                        m = np.zeros(cap, dtype=bool)
                        m[:n] = True
                        mask = jnp.asarray(m)
                    yield Batch(cols, mask)

        def gen():
            tc = self.ctx.config.task_concurrency
            if tc > 1 and len(splits) > 1:
                # driver-per-split leaf parallelism (LocalExchange +
                # task_concurrency): split drains overlap host-side work;
                # driver walls land in EXPLAIN ANALYZE stats
                from .local_exchange import parallel_drain
                dstats = None
                if self.ctx.stats is not None:
                    dstats = self.ctx.stats.setdefault(
                        node.id, {"rows": 0, "wall_s": 0.0, "batches": 0})
                yield from parallel_drain(
                    [lambda s=s: split_gen(s) for s in splits], tc, dstats)
                return
            for split in splits:
                yield from split_gen(split)

        def gen_filtered():
            yield from apply_runtime_filters(gen())
        src = BatchSource(gen_filtered if rf_cols else gen, names, types)
        if not host and all(kind == "gen" for _n, _c, kind in dev):
            # whole-pipeline fusion metadata (see _fuse_scan_chain): the scan
            # is a pure jax function of (pos, valid) — an aggregation above a
            # Filter/Project chain over this scan can run as ONE compiled
            # program with a fori_loop over split chunks, eliminating the
            # per-batch dispatch round-trips that dominate wall-clock
            src.fused_scan = {
                "make": make, "make_factory": make_factory,
                "splits": splits, "cap": cap, "cached_cols": cached_cols,
                "dicts": {name: device_gen.dictionary(cid, table, colname)
                          for name, colname, _k in dev},
                # lineage metadata for grouped (lifespan) execution
                "table": table, "cid": cid, "sf": sf,
                "colmap": {name: colname for name, colname, _k in dev},
                # zone-map chunk skipping inside FusedChain.chunks_for:
                # host-side stats keyed by connector column name, matched
                # against the scan's pushed-down conjuncts
                "zone_maps": zone_maps, "pushdown": pushdown,
                # runtime dynamic-filter summaries, read lazily so fused
                # chunk pruning sees filters that arrive pre-drain
                "dyn_summaries": dyn_summaries,
            }
        return src

    def _compile_TableWriterNode(self, node: P.TableWriterNode) -> BatchSource:
        """Stream source batches into a connector write handle (reference
        TableWriterOperator.java:78): pages are staged, not visible until
        TableFinish commits.  Emits one row (rows-written, staging token)."""
        src = self._compile(node.source)
        names = [v.name for v in node.outputs]
        types = [v.type for v in node.outputs]

        def gen():
            conn = catalog.module(node.connector_id)
            # parquet fields carry the SQL-visible column names, not the
            # planner's internal variable names
            handle = conn.begin_write(node.table_name,
                                      list(node.column_names),
                                      list(src.types))
            rows = 0
            wrote = False
            try:
                for b in src.batches():
                    page = batch_to_page(b, src.names, src.types)
                    if page.position_count:
                        rows += handle.write_page(page)
                        wrote = True
                if not wrote:
                    # an empty result still defines the table's schema:
                    # stage one zero-row part so scans of the empty table
                    # see real columns (matches reference CTAS semantics)
                    from ..common.block import block_from_values
                    handle.write_page(Page(
                        [block_from_values(t, []) for t in src.types], 0))
            except BaseException:
                handle.abort()
                raise
            rv, fv = node.outputs[:2]
            cols = {rv.name: Column(jnp.asarray(np.array([rows],
                                                         dtype=np.int64))),
                    fv.name: Column(jnp.asarray(np.zeros(1, np.int32)), None,
                                    (handle.staging_id,))}
            if len(node.outputs) > 2:
                # coordinator-shaped fragments carry a third
                # tableCommitContext output (TableCommitContext.java); a
                # task-wide single-commit context is constant
                cols[node.outputs[2].name] = Column(
                    jnp.asarray(np.zeros(1, np.int32)), None,
                    ('{"lifespan":"TaskWide","pageSinkCommitStrategy":'
                     '"NO_COMMIT"}',))
            yield Batch(cols, jnp.asarray(np.array([True])))
        return BatchSource(gen, names, types)

    def _compile_TableFinishNode(self, node: P.TableFinishNode) -> BatchSource:
        """Commit every staged fragment from the writer(s) and emit the total
        row count (reference TableFinishOperator.java)."""
        src = self._compile(node.source)
        names = [v.name for v in node.outputs]
        types = [v.type for v in node.outputs]

        def gen():
            from ..common.block import block_to_values
            conn = catalog.module(node.connector_id)
            total = 0
            for b in src.batches():
                page = batch_to_page(b, src.names, src.types)
                rows = block_to_values(src.types[0], page.blocks[0])
                frags = block_to_values(src.types[1], page.blocks[1])
                for r, f in zip(rows, frags):
                    total += int(r)
                    conn.staged(f).commit()
            cols = {node.outputs[0].name:
                    Column(jnp.asarray(np.array([total], dtype=np.int64)))}
            yield Batch(cols, jnp.asarray(np.array([True])))
        return BatchSource(gen, names, types)

    def _compile_ValuesNode(self, node: P.ValuesNode) -> BatchSource:
        names = [v.name for v in node.outputs]
        types = [v.type for v in node.outputs]
        from ..common.block import block_from_values
        from .lowering import constant_device_value

        def gen():
            n = len(node.rows)
            cap = max(n, 1)
            cols = {}
            for i, (name, typ) in enumerate(zip(names, types)):
                vals = [constant_device_value(r[i].value, typ)
                        for r in node.rows]
                blk = block_from_values(
                    typ, [None if v is None else v for v in vals]
                    if not isinstance(typ, (VarcharType, CharType))
                    else [None if v is None else str(v) for v in vals])
                from .batch import block_to_column
                cols[name] = block_to_column(typ, blk, cap)
            mask = np.zeros(cap, dtype=bool)
            mask[:n] = True
            yield Batch(cols, jnp.asarray(mask))
        return BatchSource(gen, names, types)

    def _compile_RemoteSourceNode(self, node: P.RemoteSourceNode) -> BatchSource:
        names = [v.name for v in node.outputs]
        types = [v.type for v in node.outputs]
        cap = self.ctx.config.batch_rows
        ctx = self.ctx

        def gen():
            dev = ctx.remote_batches.get(node.id)
            if dev is not None:
                # ICI path: batches arrive device-resident from the
                # all_to_all exchange (parallel/exchange.py)
                yield from dev()
                return
            # HTTP/host path: string columns are materialized + remapped
            # to a union dictionary (producer tasks ship independent
            # dictionaries; jitted consumers need one per column)
            yield from pages_to_batches(ctx.remote_pages[node.id](),
                                        names, types, cap)
        return BatchSource(gen, names, types)

    # -- streaming transforms --------------------------------------------
    def _compile_FilterNode(self, node: P.FilterNode) -> BatchSource:
        src = self._compile(node.source)
        low = self.lowering
        hoister = _StringHoister([node.predicate])
        cache: dict = {}  # resolution is laziness-dependent only: jit once

        def gen():
            it = iter(src.batches())
            first = next(it, None)
            if first is None:
                return
            if "step" not in cache:
                (pred,), hoisted = hoister.resolve(first)
                sig = _fragment_batch_sig(first)
                if expr_has_params(pred):
                    # bound parameters ride as an explicit jit argument so
                    # the trace is reused across constant bindings
                    def pstep(batch, params, _pred=pred):
                        return ops.apply_filter(
                            batch, low.eval(_pred, batch.with_params(params)))
                    jitted = self.fragment_jit(node, "filter_p", pstep,
                                               extra=(sig,))
                    cache["step"] = \
                        lambda b, _j=jitted: _j(b, self.ctx.params)
                else:
                    def step(batch, _pred=pred):
                        return ops.apply_filter(batch, low.eval(_pred, batch))
                    cache["step"] = self.fragment_jit(node, "filter", step,
                                                      extra=(sig,))
                cache["hoisted"] = hoisted
            step, hoisted = cache["step"], cache["hoisted"]
            for b in itertools.chain([first], it):
                yield step(_add_hoisted(b, hoisted))
        return BatchSource(gen, src.names, src.types)

    def _compile_ProjectNode(self, node: P.ProjectNode) -> BatchSource:
        src = self._compile(node.source)
        names = [v.name for v in node.assignments]
        types = [v.type for v in node.assignments]
        items = list(node.assignments.items())
        low = self.lowering
        hoister = _StringHoister([e for _, e in items])
        cache: dict = {}

        def gen():
            it = iter(src.batches())
            first = next(it, None)
            if first is None:
                return
            if "step" not in cache:
                exprs, hoisted = hoister.resolve(first)
                sig = _fragment_batch_sig(first)
                if any(expr_has_params(e) for e in exprs):
                    def pstep(batch, params, _exprs=exprs):
                        pb = batch.with_params(params)
                        cols = {v.name: low.eval(e, pb)
                                for (v, _), e in zip(items, _exprs)}
                        return Batch(cols, batch.mask)
                    jitted = self.fragment_jit(node, "project_p", pstep,
                                               extra=(sig, tuple(names)))
                    cache["step"] = \
                        lambda b, _j=jitted: _j(b, self.ctx.params)
                else:
                    def step(batch, _exprs=exprs):
                        cols = {v.name: low.eval(e, batch)
                                for (v, _), e in zip(items, _exprs)}
                        return Batch(cols, batch.mask)
                    cache["step"] = self.fragment_jit(
                        node, "project", step, extra=(sig, tuple(names)))
                cache["hoisted"] = hoisted
            step, hoisted = cache["step"], cache["hoisted"]
            for b in itertools.chain([first], it):
                yield step(_add_hoisted(b, hoisted))
        return BatchSource(gen, names, types)

    def _compile_OutputNode(self, node: P.OutputNode) -> BatchSource:
        src = self._compile(node.source)
        # OutputNode renames columns positionally
        inner = [v.name for v in node.source.output_variables]
        outer = [v.name for v in node.outputs]
        types = [v.type for v in node.outputs]
        if inner == outer:
            return BatchSource(src.batches, outer, types)

        def gen():
            for b in src.batches():
                cols = {o: b.columns[i] for i, o in zip(inner, outer)}
                yield Batch(cols, b.mask)
        return BatchSource(gen, outer, types)

    def _compile_UnnestNode(self, node: P.UnnestNode) -> BatchSource:
        """One output row per array element; source columns replicated
        (reference UnnestOperator.java).  With the fixed-width (cap, W)
        array layout this is the same shape transform as the fused join
        fanout expansion: output capacity = cap * W, slot i*W + j = (source
        row i, element j); multiple arrays zip by position, shorter ones
        null-padded (SQL UNNEST semantics)."""
        src = self._compile(node.source)
        names = [v.name for v in node.output_variables]
        types = [v.type for v in node.output_variables]
        rep_names = [v.name for v in node.replicate_variables]
        pairs = [(av.name, elems[0].name)
                 for av, elems in node.unnest_variables]
        ord_name = (None if node.ordinality_variable is None
                    else node.ordinality_variable.name)

        def step(batch):
            cap = batch.capacity
            arrs = {an: batch.columns[an] for an, _en in pairs}
            W = max([a.values.shape[1] for a in arrs.values()] + [1])
            # rows per source row = max of the zipped arrays' lengths
            rowlen = None
            for a in arrs.values():
                ln = jnp.where(a.null_mask(), 0, a.lengths)
                rowlen = ln if rowlen is None else jnp.maximum(rowlen, ln)
            j = jnp.arange(W, dtype=jnp.int32)
            cols = {}
            for rn in rep_names:
                c = batch.columns[rn]
                if c.lengths is not None:
                    vals = jnp.repeat(c.values, W, axis=0)
                else:
                    vals = jnp.repeat(c.values, W)
                cols[rn] = Column(
                    vals,
                    None if c.nulls is None else jnp.repeat(c.nulls, W),
                    c.dictionary, c.lazy,
                    None if c.lengths is None
                    else jnp.repeat(c.lengths, W))
            for an, en in pairs:
                a = arrs[an]
                aw = a.values.shape[1]
                padded = (a.values if aw == W else jnp.pad(
                    a.values, ((0, 0), (0, W - aw))))
                vals = padded.reshape(cap * W)
                ln = jnp.where(a.null_mask(), 0, a.lengths)
                valid = (j[None, :] < ln[:, None]).reshape(cap * W)
                cols[en] = Column(vals, ~valid)
            if ord_name is not None:
                cols[ord_name] = Column(
                    jnp.tile(j.astype(jnp.int64) + 1, cap))
            mask = (batch.mask[:, None]
                    & (j[None, :] < rowlen[:, None])).reshape(cap * W)
            return Batch(cols, mask)

        step = self.shared_jit((node.id, "unnest"), step)

        def gen():
            for b in src.batches():
                out = step(b)
                yield out.select(names)
        return BatchSource(gen, names, types)

    # -- limit / topn / sort ---------------------------------------------
    def _compile_LimitNode(self, node: P.LimitNode) -> BatchSource:
        src = self._compile(node.source)
        n = node.count

        step = self.shared_jit(
            (node.id, "limit"),
            lambda batch, consumed: ops.limit(batch, n, consumed))

        def gen():
            consumed = jnp.zeros((), dtype=jnp.int64)
            for b in src.batches():
                out, consumed = step(b, consumed)
                yield out
                if int(consumed) >= n:
                    break
        return BatchSource(gen, src.names, src.types)

    def _compile_TopNNode(self, node: P.TopNNode) -> BatchSource:
        src = self._compile(node.source)
        keys = [(v.name, order) for v, order in node.ordering_scheme.orderings]
        n = node.count

        def _step(buffer, batch):
            merged = _concat_batches([buffer, batch])
            return ops.topn(merged, keys, n)

        step = self.shared_jit((node.id, "topn_step"), _step)
        first = self.shared_jit((node.id, "topn_first"),
                                lambda batch: ops.topn(batch, keys, n))

        def gen():
            key_names = [k for k, _o in keys]
            buf = None
            for b in src.batches():
                b = _encode_unordered_lazy_keys(b, key_names)
                buf = first(b) if buf is None else step(buf, b)
            if buf is not None:
                yield buf
        return BatchSource(gen, src.names, src.types)

    def _compile_SortNode(self, node: P.SortNode) -> BatchSource:
        names, types = output_schema(node.source)
        keys = [(v.name, order) for v, order in node.ordering_scheme.orderings]

        def gen():
            merged = self._materialize_node(node.source)
            if merged is None:
                return
            merged = _encode_unordered_lazy_keys(
                merged, [k for k, _o in keys])
            yield _jits()[0](merged, tuple(keys))
        return BatchSource(gen, names, types)

    def _compile_UnionNode(self, node: P.UnionNode) -> BatchSource:
        """UNION ALL: concatenate the source streams.  Numeric/date columns
        stream straight through; string columns must first be re-encoded to
        one shared dictionary (downstream operators assume a batch-stable
        dictionary per column), which makes union a materialization point
        only when strings are involved."""
        srcs = [self._compile(s) for s in node.inputs]
        out_names = [v.name for v in node.outputs]
        out_types = [v.type for v in node.outputs]
        string_cols = [n for n, t in zip(out_names, out_types)
                       if isinstance(t, (VarcharType, CharType))]

        def gen():
            if not string_cols:
                for s in srcs:
                    yield from s.batches()
                return
            all_b = [b for s in srcs for b in s.batches()]
            if not all_b:
                return
            merged_dicts: Dict[str, list] = {n: [] for n in string_cols}
            index: Dict[str, dict] = {n: {} for n in string_cols}
            recoded = []
            for b in all_b:
                new_cols = {}
                for n in string_cols:
                    col = b.columns[n]
                    md, idx = merged_dicts[n], index[n]
                    if col.dictionary is not None:
                        lut = np.empty(len(col.dictionary), dtype=np.int64)
                        for i, sv in enumerate(col.dictionary):
                            if sv not in idx:
                                idx[sv] = len(md)
                                md.append(sv)
                            lut[i] = idx[sv]
                        newv = lut[np.asarray(col.values)]
                    elif col.lazy is not None:
                        cid, tbl, coln, sf = col.lazy
                        strings = catalog.generate_values_at(
                            tbl, coln, sf, np.asarray(col.values), cid)
                        newv = np.empty(len(strings), dtype=np.int64)
                        for i, sv in enumerate(strings):
                            if sv not in idx:
                                idx[sv] = len(md)
                                md.append(sv)
                            newv[i] = idx[sv]
                    else:
                        raise NotImplementedError(
                            f"varchar column {n} without dictionary")
                    new_cols[n] = Column(jnp.asarray(newv), col.nulls, None)
                recoded.append(b.with_columns(new_cols))
            final = []
            for b in recoded:
                cols = {n: (Column(c.values, c.nulls,
                                   tuple(merged_dicts[n]))
                            if n in string_cols else c)
                        for n, c in b.columns.items()}
                final.append(Batch(cols, b.mask))
            yield final[0] if len(final) == 1 \
                else _jit_concat(final)
        return BatchSource(gen, out_names, out_types)

    def _compile_WindowNode(self, node: P.WindowNode) -> BatchSource:
        """Materialize + one jitted segmented-scan pass (operators.window_batch);
        the reference streams partition-at-a-time (WindowOperator.java:69) but
        a single static-shape sort+scan is the XLA-friendly formulation."""
        src_names, src_types = output_schema(node.source)
        part_names = tuple(v.name for v in node.partition_by)
        orderings = tuple((v.name, o) for v, o in
                          node.ordering_scheme.orderings) \
            if node.ordering_scheme else ()
        from .lowering import constant_device_value
        specs = []
        for v, wf in node.window_functions.items():
            fname = canonical_name(wf.call.display_name)
            args = wf.call.arguments
            arg = None
            extra = ()
            if fname == "count" and not args:
                fname = "count_star"
            elif fname == "ntile":
                extra = (int(args[0].value),)
            elif args:
                arg = args[0].name
                consts = []
                for a in args[1:]:
                    consts.append(constant_device_value(a.value, a.type))
                extra = tuple(consts)
            frame = None
            if wf.frame:
                f = wf.frame
                frame = (f["type"], f["startKind"], f["startOffset"],
                         f["endKind"], f["endOffset"])
            is_float = isinstance(v.type, (DoubleType, RealType))
            specs.append(ops.WindowSpec(fname, v.name, arg, is_float,
                                        frame, extra))
        specs = tuple(specs)
        out_names = src_names + [v.name for v in node.window_functions]
        out_types = src_types + [v.type for v in node.window_functions]

        def gen():
            merged = self._materialize_node(node.source)
            if merged is None:
                return
            # late-materialized string keys: window_batch both SORTS by and
            # compares (partition identity / peer detection) every key, so a
            # lazy column's row ids must match the value order AND be
            # distinct per value; otherwise encode to whole-column
            # dictionaries on the host
            encode = []
            minmax_args = {s.arg for s in specs
                           if s.name in ("min", "max") and s.arg}
            key_cols = set(part_names) | {k for k, _ in orderings}
            for k in sorted(key_cols | minmax_args):
                col = merged.columns[k]
                if col.lazy is None:
                    continue
                _, tbl, coln, _sf = col.lazy
                # keys need row ids that sort like values AND are distinct
                # per value; min/max args only need the sort property
                ok = (tbl, coln) in catalog.ROWID_ORDERED and (
                    k not in key_cols
                    or (tbl, coln) in catalog.ROWID_DISTINCT)
                if not ok:
                    encode.append(k)
            if encode:
                merged = _encode_lazy_keys(merged, encode)
            cfg = self.ctx.config

            def _declined(reason: str) -> None:
                from .kernels.scan_kernel import KERNEL_METRICS
                KERNEL_METRICS.record_declined(reason)
                rs = self.ctx.runtime_stats
                if rs is not None:
                    rs.add(f"kernelDeclined{reason}", 1)

            if cfg.scan_kernel == "xla":
                _declined("Disabled")
            elif cfg.scan_kernel == "auto" \
                    and jax.default_backend() != "tpu":
                # same policy as the scan kernel gate: auto never pays
                # interpret-mode emulation off-TPU
                _declined("Backend")
            else:
                # Pallas prefix-scan window kernel (exec/kernels/window):
                # segments + running aggregates in one VMEM-resident
                # launch over the sorted run.  None -> metered decline,
                # fall through to the XLA segmented scans.
                from .kernels import try_window_kernel
                kres = try_window_kernel(
                    merged, part_names, orderings, specs,
                    declined=_declined,
                    runtime_stats=self.ctx.runtime_stats)
                if kres is not None:
                    yield kres
                    return
            yield _jits()[2](merged, part_names, orderings, specs)
        return BatchSource(gen, out_names, out_types)

    def _compile_DistinctLimitNode(self, node: P.DistinctLimitNode) -> BatchSource:
        agg = P.AggregationNode(node.id + ".agg", node.source, {},
                                node.distinct_variables, P.SINGLE)
        lim = P.LimitNode(node.id + ".limit", agg, node.count)
        return self._compile(lim)

    def _compile_GroupIdNode(self, node: P.GroupIdNode) -> BatchSource:
        """Grouping-set expansion (reference GroupIdOperator.java): lower
        to one ProjectNode per grouping set over the shared source (the
        compiler memoizes by node id, so the source executes once and its
        batches are teed), unioned.  The downstream aggregation groups by
        (grouping columns..., group_id), exactly the reference pairing."""
        from ..spi.expr import constant
        branches = []
        for i, gset in enumerate(node.grouping_sets):
            in_set = {v.name for v in gset}
            assigns = {}
            for out_v, in_v in node.grouping_columns.items():
                assigns[out_v] = (in_v if out_v.name in in_set
                                  else constant(None, out_v.type))
            for v in node.aggregation_arguments:
                assigns[v] = v
            assigns[node.group_id_variable] = \
                constant(i, node.group_id_variable.type)
            branches.append(P.ProjectNode(f"{node.id}.gid{i}", node.source,
                                          assigns))
        union = P.UnionNode(node.id + ".union", branches,
                            list(node.output_variables))
        return self._compile(union)

    def _compile_MarkDistinctNode(self, node: P.MarkDistinctNode) -> BatchSource:
        """Marker = first row of its distinct-key group (reference
        MarkDistinctOperator/MarkDistinctHash): row_number() partitioned by
        the distinct keys, marker = (rn == 1)."""
        from ..spi.expr import call, constant
        rn = VariableReferenceExpression(f"{node.marker.name}__rn", BIGINT)
        win = P.WindowNode(
            node.id + ".rn", node.source, list(node.distinct_variables),
            None, {rn: P.WindowFunction(
                CallExpression("row_number", BIGINT, []), None)})
        assigns = {v: v for v in node.source.output_variables}
        assigns[node.marker] = call("eq", BOOLEAN, rn, constant(1, BIGINT))
        proj = P.ProjectNode(node.id + ".mark", win, assigns)
        return self._compile(proj)

    # -- aggregation ------------------------------------------------------
    def _compile_AggregationNode(self, node: P.AggregationNode) -> BatchSource:
        node = _rewrite_agg_masks(node)
        src_node = node.source
        key_vars = node.grouping_keys
        key_names = tuple(v.name for v in key_vars)
        out_names = [v.name for v in key_vars] + [v.name for v in node.aggregations]
        out_types = ([v.type for v in key_vars]
                     + [v.type for v in node.aggregations])
        low = self.lowering

        specs = []
        input_exprs: Dict[str, Optional[RowExpression]] = {}
        input_exprs2: Dict[str, RowExpression] = {}
        for v, agg in node.aggregations.items():
            fname = canonical_name(agg.call.display_name)
            args = agg.call.arguments
            if fname == "count" and not args:
                fname = "count_star"
            is_float = isinstance(v.type, (DoubleType, RealType)) or (
                fname == "avg" and isinstance(v.type, (DoubleType,
                                                       RealType)))
            param = None
            if fname == "approx_percentile" and len(args) > 1:
                param = float(args[1].value)
                is_float = isinstance(args[0].type, (DoubleType, RealType))
            if fname in ops.HLL_AGGS:
                # optional max standard error -> register count (reference
                # approx_distinct(x, e), ApproximateCountDistinct
                # Aggregations.java)
                param = (ops.hll_buckets_for_error(float(args[1].value))
                         if len(args) > 1 else ops.HLL_DEFAULT_BUCKETS)

            if fname in ops.CORR_AGGS and len(args) > 1:
                input_exprs2[v.name] = args[1]
            specs.append(ops.AggSpec(fname, v.name, is_float, param))
            input_exprs[v.name] = args[0] if args else None
        specs = tuple(specs)
        basic_specs = all(s.name in ops.BASIC_AGGS for s in specs)
        sort_only_specs = any(s.name in ops.SORT_ONLY_AGGS for s in specs)

        cfg = self.ctx.config

        update_cache: Dict[Tuple, Callable] = {}

        def make_direct_update(G: int, strides: Tuple[int, ...]):
            fn = update_cache.get(("direct", G, strides))
            if fn is None:
                def fn(state, batch):
                    codes = None
                    for k, stride in zip(key_names, strides):
                        c = batch.columns[k].values.astype(jnp.int64)
                        codes = c * stride if codes is None \
                            else codes + c * stride
                    if codes is None:    # global aggregation: one group
                        codes = jnp.zeros(batch.capacity, dtype=jnp.int64)
                    agg_cols = {}
                    for out, expr in input_exprs.items():
                        agg_cols[out] = (low.eval(expr, batch)
                                         if expr is not None else None)
                    return ops.agg_direct_update(state, batch, codes,
                                                 agg_cols, specs, G)
                fn = self.shared_jit((node.id, "agg_direct", G, strides),
                                     fn)
                update_cache[("direct", G, strides)] = fn
            return fn

        def make_update(num_slots: int, salt: int):
            fn = update_cache.get((num_slots, salt))
            if fn is None:
                def fn(state, batch):
                    key_cols = [batch.columns[k] for k in key_names]
                    agg_cols = {}
                    for out, expr in input_exprs.items():
                        agg_cols[out] = (low.eval(expr, batch)
                                         if expr is not None else None)
                    agg_cols2 = {out: low.eval(expr, batch)
                                 for out, expr in input_exprs2.items()}
                    return ops.agg_update(state, batch, key_cols, agg_cols,
                                          specs, num_slots, salt, key_names,
                                          agg_cols2)
                fn = self.shared_jit((node.id, "agg_upd", num_slots, salt),
                                     fn)
                update_cache[(num_slots, salt)] = fn
            return fn

        def run_once(num_slots: int, salt: int, batches_fn=None,
                     allow_direct: bool = True):
            batches = (self._compile(src_node).batches()
                       if batches_fn is None else batches_fn())
            state = None
            key_dicts: Dict[str, Tuple[str, ...]] = {}
            key_lazy: Dict[str, Tuple] = {}
            encode_keys: List[str] = []
            update = make_update(num_slots, salt)

            direct = None        # (doms, dtypes) when small-domain mode
            hll_outs = {s.output for s in specs if s.name in ops.HLL_AGGS}
            for batch in batches:
                if state is None:
                    for k in key_names:
                        col = batch.columns[k]
                        if col.lazy is not None:
                            _, tbl, coln, _sf = col.lazy
                            if (tbl, coln) in catalog.ROWID_DISTINCT:
                                # row id IS the group identity; keep lazy tag
                                key_lazy[k] = col.lazy
                            else:
                                # small-pool column (orders.clerk): grouping
                                # by row id would split groups — encode to a
                                # real whole-column dictionary on the host
                                encode_keys.append(k)
                    # HLL sketches hash the device values: a lazy column's
                    # row ids are only distinct-faithful when the row id is
                    # unique per VALUE; otherwise encode to dictionary codes
                    for out in hll_outs:
                        expr = input_exprs[out]
                        if isinstance(expr, VariableReferenceExpression):
                            col = batch.columns.get(expr.name)
                            if col is not None and col.lazy is not None:
                                _, tbl, coln, _sf = col.lazy
                                if (tbl, coln) not in catalog.ROWID_DISTINCT \
                                        and expr.name not in encode_keys:
                                    encode_keys.append(expr.name)
                    if encode_keys:
                        batch = _encode_lazy_keys(batch, encode_keys)
                    key_cols = [batch.columns[k] for k in key_names]
                    key_dtypes = [c.values.dtype for c in key_cols]
                    for k, c in zip(key_names, key_cols):
                        if c.dictionary is not None:
                            key_dicts[k] = c.dictionary
                    # closed small domains: combined code IS the slot index
                    info = (_direct_mode_info(key_names, key_cols)
                            if basic_specs and allow_direct else None)
                    if info is not None:
                        doms, G, strides, kdts, _kd = info
                        direct = (doms, kdts)
                        update = make_direct_update(G, strides)
                        state = ops.agg_direct_init(G, specs)
                    else:
                        state = ops.agg_init(num_slots, specs, key_names,
                                             key_dtypes)
                elif encode_keys:
                    batch = _encode_lazy_keys(batch, encode_keys)
                if direct is not None and any(
                        batch.columns[k].nulls is not None
                        for k in key_names):
                    # direct mode was chosen on a null-free first batch,
                    # but this batch carries a NULL key (nullable storage
                    # connectors): the code grid has no null slot, so
                    # RESTART the whole aggregation on the hash path.
                    # Close the abandoned iterator FIRST — source
                    # generators release pool reservations in finally
                    # blocks.  The restart replays through the _share tee
                    # buffer like a collision retry does (same stats
                    # double-count caveat under EXPLAIN ANALYZE).
                    if hasattr(batches, "close"):
                        batches.close()
                    return run_once(num_slots, salt, batches_fn,
                                    allow_direct=False)
                state = update(state, batch)
            if state is None:
                key_dtypes = [jnp.int64] * len(key_names)
                state = ops.agg_init(num_slots, specs, key_names, key_dtypes)
            return state, key_dicts, key_lazy, direct

        fused_cache: dict = {}

        def _fusion_declined(reason: str) -> None:
            """The silent fusion refusals become per-scan RuntimeStats
            counters (fusionDeclined{Reason}), printed by EXPLAIN
            ANALYZE so an un-fused plan is diagnosable."""
            rs = self.ctx.runtime_stats
            if rs is not None:
                rs.add(f"fusionDeclined{reason}", 1)

        def _kernel_declined(reason: str) -> None:
            """Pallas scan-kernel refusals (exec/kernels), metered like
            the fusion ones: kernelDeclined{Reason} counters tell EXPLAIN
            ANALYZE why a fused scan ran the XLA chain instead."""
            from .kernels.scan_kernel import KERNEL_METRICS
            KERNEL_METRICS.record_declined(reason)
            rs = self.ctx.runtime_stats
            if rs is not None:
                rs.add(f"kernelDeclined{reason}", 1)

        def get_fused():
            """Whole-pipeline fusion: when the source is a
            (Filter|Project|Join|SemiJoin)* chain over a device-generated
            TableScan (exec/fused.py), compile scan → chain → agg-update
            into ONE jitted program with a fori_loop over split chunks.
            One dispatch per task instead of O(batches × operators) — on
            TPU the per-dispatch round-trip dominates wall-clock for these
            pipelines (all of TPC-H's heavy shapes).  Returns the compiled
            FusedChain or None; decision is cached.  EXPLAIN ANALYZE runs
            the fused chain too (per-operator row counters ride the jitted
            program) unless the analyze_unfused session knob asks for the
            old streaming profile."""
            if "chain" in fused_cache:
                return fused_cache["chain"]
            fused_cache["chain"] = None
            if not cfg.fuse_pipelines:
                _fusion_declined("Disabled")
                return None
            if self.ctx.stats is not None and cfg.analyze_unfused:
                _fusion_declined("AnalyzeUnfused")
                return None
            # masks were already lowered to IF-inputs by _rewrite_agg_masks
            if any(a.distinct for a in node.aggregations.values()):
                _fusion_declined("DistinctAgg")
                return None
            if any(s.name in ops.HLL_AGGS for s in specs):
                # HLL registers live in the scatter-hash table only; the
                # fused sort path has no register file
                _fusion_declined("HllAgg")
                return None
            from .fused import assemble_chain
            chain = assemble_chain(self, src_node)
            if chain is None:
                _fusion_declined("PlanShape")
            elif not chain.chunks:
                _fusion_declined("NoChunks")
                chain = None
            fused_cache["chain"] = chain
            return chain

        def _agg_exprs(b):
            return {out: (low.eval(expr, b) if expr is not None else None)
                    for out, expr in input_exprs.items()}

        def _agg_exprs2(b):
            return {out: low.eval(expr, b)
                    for out, expr in input_exprs2.items()}

        def run_fused(chain):
            """Analyze-aware front door for _run_fused_inner: under
            EXPLAIN ANALYZE it measures the REAL fused program's wall
            (block_until_ready on the finalized output) and folds the
            device-side per-operator row counters into ctx.stats."""
            analyzing = self.ctx.stats is not None
            counts_out: dict = {}
            if not analyzing:
                return _run_fused_inner(chain, counts_out)
            import time
            t0 = time.perf_counter()  # lint: allow-wall-clock
            out = _run_fused_inner(chain, counts_out)
            if out is None:
                return None
            out = jax.block_until_ready(out)
            wall = time.perf_counter() - t0  # lint: allow-wall-clock
            counts = counts_out.get("counts")
            if counts is None and "probe_args" in counts_out:
                # modes whose program cannot carry the counters in its
                # loop state (runtime span, sort-agg): one extra counting
                # dispatch over the same chain
                from .fused import chain_counts_fn
                p_arr, c_arr, p_aux, p_exp, p_cap = counts_out["probe_args"]
                counts = chain_counts_fn(
                    chain, p_exp, p_cap, fused_cache,
                    ("analyze_counts", p_exp))(p_arr, c_arr, p_aux)
            from .fused import record_chain_stats
            record_chain_stats(self.ctx.stats, chain, counts,
                               counts_out.get("n_chunks", 0), wall_s=wall)
            if self.ctx.runtime_stats is not None:
                self.ctx.runtime_stats.add("fusedProgramWallNanos",
                                           wall * 1e9, "NANO")
            return out

        def _run_fused_inner(chain, counts_out):
            """Execute a fused chain to a finalized output Batch, or None
            to fall back to the streaming executor.  Four modes by group-key
            shape: one-hot grid (G<=64, MXU-friendly), static span (closed
            dictionary domains), runtime span (single integer key — probe
            min/max, then collision-free scatter-direct), hash table."""
            analyzing = self.ctx.stats is not None
            pool = self.ctx.memory
            if pool.limited:
                # budgeted (or query.max-memory-limited) execution keeps
                # the streaming path: its build reservation / grace-spill
                # machinery owns memory discipline
                _fusion_declined("BudgetedPool")
                return None
            # build tables are deterministic per plan (generated connectors
            # are immutable; writes clear the runner's plan cache), so prep
            # results persist across re-executions — the warm path costs
            # zero host syncs for builds.  Parameterized BUILD subtrees are
            # the exception: their tables are a function of the bound
            # constants, so prep re-runs when the fingerprint moved.
            pfp = (self.ctx.params_fingerprint
                   if (chain.has_params or chain.build_params
                       or chain.params_pushdown) else None)
            prep_res = fused_cache.get("prep")
            if prep_res is not None and chain.build_params \
                    and fused_cache.get("prep_fp") != pfp:
                prep_res = None
            if prep_res is None:
                try:
                    prep_res = chain.prep()
                except QueryMemoryLimitExceededError:
                    raise   # typed user error: fail fast, never fall back
                except (NotImplementedError, MemoryExceededError):
                    _fusion_declined("PrepUnsupported")
                    return None
                if prep_res is None:
                    _fusion_declined("PrepFanout")
                    return None
                fused_cache["prep"] = prep_res
                fused_cache["prep_fp"] = pfp
            aux, expands, _deferred = prep_res
            if chain.has_params:
                # cached prep carries the FIRST execution's parameter
                # vector in the last aux slot — swap in the current one
                # (traced argument: no retrace)
                aux = aux[:-1] + (self.ctx.params,)
            leaf_cap = chain.leaf_cap(expands)
            chunks = chain.chunks_for(expands, meter=True)
            try:
                probe = jax.eval_shape(
                    lambda p, v: chain.make(p, v, aux, expands, leaf_cap),
                    jnp.int64(0), jnp.int64(1))
            except NotImplementedError:
                _fusion_declined("ProbeUnsupported")
                return None
            key_cols = [probe.columns.get(k) for k in key_names]
            if any(c is None for c in key_cols):
                _fusion_declined("KeyMissing")
                return None
            key_lazy: Dict[str, Tuple] = {}
            for k, c in zip(key_names, key_cols):
                if c.lazy is not None:
                    _, tbl, coln, _sf = c.lazy
                    if (tbl, coln) not in catalog.ROWID_DISTINCT:
                        _fusion_declined("KeyEncoding")
                        return None    # needs host dictionary encoding
                    key_lazy[k] = c.lazy
            key_dicts = {k: c.dictionary
                         for k, c in zip(key_names, key_cols)
                         if c.dictionary is not None}
            key_dtypes = tuple(c.values.dtype for c in key_cols)
            pos_arr = jnp.asarray([c0 for c0, _ in chunks],
                                  dtype=jnp.int64)
            cnt_arr = jnp.asarray([c1 for _, c1 in chunks],
                                  dtype=jnp.int64)
            counts_out["probe_args"] = (pos_arr, cnt_arr, aux, expands,
                                        leaf_cap)
            counts_out["n_chunks"] = len(chunks)

            def loop(key, update, init_state):
                """fori_loop over scan chunks; the jitted program is cached
                under `key` so re-executions of the plan skip retracing.
                Under EXPLAIN ANALYZE the per-operator row counters ride
                the SAME program as an extra loop-carry output."""
                key = key + (expands, analyzing)
                run_all = fused_cache.get(key)
                if run_all is None:
                    if analyzing:
                        @jax.jit
                        def run_all(pos_arr, cnt_arr, state, aux):
                            def body(i, carry):
                                st, cnts = carry
                                b, c = chain.make(
                                    pos_arr[i], cnt_arr[i], aux, expands,
                                    leaf_cap, with_counts=True)
                                return update(st, b), cnts + c
                            return jax.lax.fori_loop(
                                0, pos_arr.shape[0], body,
                                (state, jnp.zeros(1 + len(chain.steps),
                                                  dtype=jnp.int64)))
                    else:
                        @jax.jit
                        def run_all(pos_arr, cnt_arr, state, aux):
                            def body(i, st):
                                b = chain.make(pos_arr[i], cnt_arr[i], aux,
                                               expands, leaf_cap)
                                return update(st, b)
                            # chunk count from the traced shape, NOT a
                            # closure constant: param-aware pruning may
                            # change it between executions (shape change
                            # -> retrace)
                            return jax.lax.fori_loop(0, pos_arr.shape[0],
                                                     body, state)
                    fused_cache[key] = run_all
                out = run_all(pos_arr, cnt_arr, init_state, aux)
                if analyzing:
                    out, counts_out["counts"] = out
                return out

            def stride_codes(b, strides, G):
                codes = None
                for k, stride in zip(key_names, strides):
                    c = b.columns[k].values.astype(jnp.int64)
                    codes = (c * stride if codes is None
                             else codes + c * stride)
                if codes is None:
                    codes = jnp.zeros(b.capacity, dtype=jnp.int64)
                return codes

            basic = basic_specs
            sort_only = sort_only_specs
            info = (_direct_mode_info(key_names, key_cols)
                    if basic else None)
            if info is not None:
                doms, G, strides, kdts, kdicts = info
                if cfg.scan_kernel == "xla":
                    _kernel_declined("Disabled")
                elif cfg.scan_kernel == "auto" \
                        and jax.default_backend() != "tpu":
                    # auto is a performance decision: interpret-mode
                    # emulation never beats the XLA chain off-TPU
                    # (scan_kernel=pallas pins the kernel regardless)
                    _kernel_declined("Backend")
                else:
                    # Pallas fused scan kernel (exec/kernels): decode +
                    # filter + prefix-sum compaction + subtile partial
                    # agg in one grid pass over the surviving chunks.
                    # Its accumulator state and row counters are
                    # agg_direct-shaped, so finalize and the operator
                    # stats spine are shared with the XLA path below.
                    from .kernels import try_direct_scan_kernel
                    kres = try_direct_scan_kernel(
                        chain, aux, specs=specs,
                        key_names=key_names, strides=strides, G=G,
                        agg_exprs=_agg_exprs, lowering=low,
                        cache=fused_cache, declined=_kernel_declined,
                        runtime_stats=self.ctx.runtime_stats,
                        dma=cfg.scan_kernel_dma,
                        expands=expands, pool=pool)
                    if kres is not None:
                        state, kcounts, n_blocks = kres
                        counts_out["counts"] = kcounts
                        counts_out["n_chunks"] = n_blocks
                        return ops.agg_direct_finalize(
                            state, specs, key_names, doms, kdts, kdicts,
                            force_row=not key_names)

                def update(st, b):
                    return ops.agg_direct_update(
                        st, b, stride_codes(b, strides, G),
                        _agg_exprs(b), specs, G)
                state = loop(("direct",), update,
                             ops.agg_direct_init(G, specs))
                return ops.agg_direct_finalize(
                    state, specs, key_names, doms, kdts, kdicts,
                    force_row=not key_names)
            elif cfg.scan_kernel == "xla":
                _kernel_declined("Disabled")
            elif not basic:
                # non-basic aggregate functions (stddev/variance, corr,
                # percentiles, distinct forms) have no in-kernel
                # accumulator shape — the XLA chain keeps those
                _kernel_declined("AggFunctionShape")
            elif cfg.scan_kernel == "auto" \
                    and jax.default_backend() != "tpu":
                _kernel_declined("Backend")
            else:
                # grouped (G > 64) shapes run in-kernel too: span slot
                # addressing when the closed key domains fit the VMEM
                # accumulator gate, hashed open addressing otherwise
                # (exec/kernels/grouped.py).  A None return has already
                # metered its kernelDeclined{reason}; the XLA span /
                # sort / hash paths below take over.
                from .kernels import (KERNEL_SPAN_MAX_GROUPS,
                                      try_grouped_scan_kernel)
                span_info = _direct_mode_info(
                    key_names, key_cols, gmax=KERNEL_SPAN_MAX_GROUPS)
                kres = try_grouped_scan_kernel(
                    chain, aux, specs=specs, key_names=key_names,
                    key_dtypes=key_dtypes, key_dicts=key_dicts,
                    key_lazy=key_lazy, span_info=span_info,
                    est_slots=initial_slots, agg_exprs=_agg_exprs,
                    lowering=low, cache=fused_cache,
                    declined=_kernel_declined, pool=pool,
                    state_bytes=_agg_state_bytes,
                    runtime_stats=self.ctx.runtime_stats,
                    dma=cfg.scan_kernel_dma, expands=expands)
                if kres is not None:
                    out, kcounts, n_blocks = kres
                    counts_out["counts"] = kcounts
                    counts_out["n_chunks"] = n_blocks
                    return _maybe_compact(out)

            # static span: closed dictionary/bool domains beyond the grid
            # limit — combined stride code indexes accumulators directly
            info = (_direct_mode_info(key_names, key_cols,
                                      gmax=ops.SPAN_AGG_MAX_GROUPS)
                    if basic else None)
            if info is not None:
                doms, G, strides, kdts, kdicts = info
                if not pool.try_reserve(G * 24 * max(1, len(specs))):
                    return None
                try:
                    def update(st, b):
                        return ops.agg_span_update(
                            st, b, stride_codes(b, strides, G),
                            _agg_exprs(b), specs, G)
                    state = loop(("static_span",), update,
                                 ops.agg_span_init(G, specs))
                    slot = jnp.arange(G, dtype=jnp.int64)
                    key_arrays = {}
                    stride = G
                    for k, dom, dt in zip(key_names, doms, kdts):
                        stride //= dom
                        key_arrays[k] = ((slot // stride) % dom).astype(dt)
                    return _maybe_compact(ops.agg_span_finalize(
                        state, specs, key_names, key_arrays, kdicts,
                        key_lazy))
                finally:
                    pool.free(G * 24 * max(1, len(specs)))

            # runtime span: one integer ANCHOR key indexes the
            # accumulators directly (collision-free scatter-direct); any
            # OTHER grouping keys must be functionally dependent on the
            # anchor — verified at runtime by per-group min==max (+ null
            # uniformity), the TPC-H Q3/Q10/Q18 shape where order/customer
            # attributes are grouped alongside their key.  On violation
            # the run is discarded and the sort path below takes over.
            candidates = [i for i, c in enumerate(key_cols)
                          if c.nulls is None and c.values.dtype in
                          (jnp.int64, jnp.int32, jnp.int16)]
            if basic and candidates \
                    and all(c.values.ndim == 1 for c in key_cols):
                cand_names = tuple(key_names[i] for i in candidates)
                spanp = fused_cache.get(("span_probe", cand_names, expands))
                if spanp is None:
                    @jax.jit
                    def spanp(pos_arr, cnt_arr, aux):
                        def body(i, mm):
                            b = chain.make(pos_arr[i], cnt_arr[i], aux,
                                           expands, leaf_cap)
                            los, his = mm
                            vs = jnp.stack(
                                [b.columns[k].values.astype(jnp.int64)
                                 for k in cand_names])
                            los = jnp.minimum(los, jnp.min(jnp.where(
                                b.mask[None, :], vs, ops.INT64_MAX),
                                axis=1))
                            his = jnp.maximum(his, jnp.max(jnp.where(
                                b.mask[None, :], vs, ops.INT64_MIN),
                                axis=1))
                            return (los, his)
                        k = len(cand_names)
                        return jax.lax.fori_loop(
                            0, pos_arr.shape[0], body,
                            (jnp.full(k, ops.INT64_MAX, dtype=jnp.int64),
                             jnp.full(k, ops.INT64_MIN, dtype=jnp.int64)))
                    fused_cache[("span_probe", cand_names, expands)] = spanp
                # data-dependent (not shape-only) results are a function
                # of the bound parameters: key them by fingerprint
                span_key = ("span_range", cand_names, expands, pfp)
                if span_key in fused_cache:
                    ranges = fused_cache[span_key]
                else:
                    los, his = jax.device_get(spanp(pos_arr, cnt_arr, aux))  # lint: allow-host-sync
                    ranges = [(int(l), int(h)) for l, h in zip(los, his)]
                    fused_cache[span_key] = ranges
                # the anchor must be unique per group (verified below by
                # the dependency check).  Heuristic order: "key"-named
                # columns widest-span first (PK/FK naming convention, the
                # finest key is the likeliest group identity), then lazy
                # row-ids (row identity), then the rest; the first anchor
                # that verifies is cached for re-executions.
                viable = []
                for ci, (lo, hi) in zip(candidates, ranges):
                    span = hi - lo + 1
                    if hi >= lo and span <= ops.SPAN_AGG_MAX_GROUPS:
                        nm = key_names[ci].lower()
                        rank = (0 if "key" in nm
                                else 1 if key_cols[ci].lazy is not None
                                else 2)
                        viable.append((rank, -span, ci, span, lo))
                viable.sort()
                anchor_key = ("span_anchor", cand_names, expands, pfp)
                cached_anchor = fused_cache.get(anchor_key)
                if cached_anchor is not None:
                    # -1 = every candidate failed once; don't re-pay the
                    # wasted verification passes on re-execution
                    viable = [v for v in viable if v[2] == cached_anchor]
                attempts = [(v[2], v[3], v[4]) for v in viable[:2]]
                if not attempts and cached_anchor is None:
                    fused_cache[anchor_key] = -1
                for ci, span, lo in attempts:
                    dep_idx = [i for i in range(len(key_names)) if i != ci]
                    dep_names = tuple(key_names[i] for i in dep_idx)
                    kname = key_names[ci]
                    G = 1 << (span - 1).bit_length()
                    nacc = max(1, len(specs)) + len(dep_names)
                    if not pool.try_reserve(G * 24 * nacc):
                        return None
                    try:
                        base = jnp.int64(lo)

                        run = fused_cache.get(
                            ("span", G, kname, dep_names, expands))
                        if run is None:
                            @jax.jit
                            def run(pos_arr, cnt_arr, state, aux, base):
                                def body(i, st):
                                    b = chain.make(pos_arr[i], cnt_arr[i],
                                                   aux, expands, leaf_cap)
                                    codes = b.columns[kname].values \
                                        .astype(jnp.int64) - base
                                    st = ops.agg_span_update(
                                        st, b, codes, _agg_exprs(b),
                                        specs, G)
                                    return ops.depkey_update(
                                        st, b, codes,
                                        {k: b.columns[k]
                                         for k in dep_names}, G)
                                state = jax.lax.fori_loop(
                                    0, pos_arr.shape[0], body, state)
                                dep_ok = ops.depkey_verify(
                                    state, state["__seen"], dep_names)
                                return state, dep_ok
                            fused_cache[("span", G, kname, dep_names,
                                         expands)] = run
                        init = {**ops.agg_span_init(G, specs),
                                **ops.depkey_init(G, dep_names)}
                        state, dep_ok = run(pos_arr, cnt_arr, init,
                                            aux, base)
                        if dep_names and not bool(jax.device_get(dep_ok)):  # lint: allow-host-sync
                            # a grouping key varies within an anchor
                            # group: this anchor was not unique — try the
                            # next candidate, else the sort path below
                            continue
                        fused_cache[anchor_key] = ci
                        key_arrays = {kname: (
                            base + jnp.arange(G, dtype=jnp.int64))
                            .astype(key_dtypes[ci])}
                        key_nulls = {}
                        for i in dep_idx:
                            k = key_names[i]
                            key_arrays[k] = ops._depkey_restore(
                                state[f"__dep_{k}$min"], key_dtypes[i])
                            key_nulls[k] = state[f"__dep_{k}$nulls"] > 0
                        return _maybe_compact(ops.agg_span_finalize(
                            state, specs, key_names, key_arrays,
                            key_dicts, key_lazy, key_nulls))
                    finally:
                        pool.free(G * 24 * nacc)
                else:
                    if attempts and cached_anchor is None:
                        fused_cache[anchor_key] = -1

            # high-cardinality keys: SORT-based grouping (argsort +
            # segmented scans — no scatters, which cost ~100ms/M rows on
            # TPU) over the stacked chain output, when it fits in memory
            total = chain.total_rows
            kprod = 1
            for k in expands:
                kprod *= k
            width = len(key_names) + sum(
                1 for e in input_exprs.values() if e is not None)
            est_mat = total * kprod * width * 9
            if (est_mat <= SORT_AGG_MAX_BYTES or sort_only) \
                    and pool.try_reserve(est_mat):
                run = fused_cache.get(("sortagg", expands))
                if run is None:
                    @jax.jit
                    def run(pos_arr, cnt_arr, aux):
                        def step(pc):
                            b = chain.make(pc[0], pc[1], aux, expands,
                                           leaf_cap)
                            cols = {k: b.columns[k] for k in key_names}
                            for out, col in _agg_exprs(b).items():
                                if col is not None:
                                    cols["$in_" + out] = col
                            for out, col in _agg_exprs2(b).items():
                                cols["$in2_" + out] = col
                            return Batch(cols, b.mask)
                        stacked = jax.lax.map(step, (pos_arr, cnt_arr))
                        flat = jax.tree_util.tree_map(
                            lambda a: a.reshape((-1,) + a.shape[2:]),
                            stacked)
                        inputs = {s.output: flat.columns.get(
                            "$in_" + s.output) for s in specs}
                        inputs2 = {s.output: flat.columns["$in2_"
                                                          + s.output]
                                   for s in specs
                                   if s.name in ops.CORR_AGGS}
                        return ops.sort_group_aggregate(
                            Batch({k: flat.columns[k] for k in key_names},
                                  flat.mask),
                            key_names, inputs, specs, inputs2)
                    fused_cache[("sortagg", expands)] = run
                try:
                    return _maybe_compact(run(pos_arr, cnt_arr, aux))
                finally:
                    pool.free(est_mat)

            if sort_only:
                # percentile-class aggregates need value-ordered
                # segments; over the sort budget the streaming summary /
                # spilled-bucket paths in gen() take over
                return None

            # scatter hash table fallback, sized from the scan row count
            # so the common case completes without a doubling recompile
            # initial size from the pre-filter scan rows, capped so a
            # selective query doesn't over-allocate; collision retries
            # double from there when the group count really is huge
            num_slots = max(cfg.agg_slots,
                            1 << (min(2 * total, 1 << 22) - 1).bit_length())
            salt = 0
            for _attempt in range(cfg.max_agg_retries):
                est = _agg_state_bytes(num_slots, key_names, specs)
                if not pool.try_reserve(est):
                    return None
                try:
                    def update(st, b, _n=num_slots, _s=salt):
                        kc = [b.columns[k] for k in key_names]
                        return ops.agg_update(st, b, kc, _agg_exprs(b),
                                              specs, _n, _s, key_names,
                                              _agg_exprs2(b))
                    state = loop(("hash", num_slots, salt), update,
                                 ops.agg_init(num_slots, specs, key_names,
                                              key_dtypes))
                    if not bool(jax.device_get(state["__collision"])):  # lint: allow-host-sync
                        if not key_names \
                                and not bool(jnp.any(state["__occupied"])):  # lint: allow-host-sync
                            state["__occupied"] = \
                                state["__occupied"].at[0].set(True)
                        return _maybe_compact(ops.agg_finalize(
                            state, specs, key_names, key_dicts, key_lazy))
                finally:
                    pool.free(est)
                num_slots *= 2
                salt += 1
            raise RuntimeError("fused aggregation collision retries "
                               "exhausted")

        def run_retrying(batches_fn=None, start_slots=None):
            num_slots, salt = start_slots or initial_slots, 0
            for attempt in range(cfg.max_agg_retries):
                state, key_dicts, key_lazy, direct = run_once(
                    num_slots, salt, batches_fn)
                if direct is not None \
                        or not bool(state["__collision"]):
                    return state, key_dicts, key_lazy, direct
                num_slots *= 2
                salt += 1
            raise RuntimeError("aggregation collision retries exhausted")

        # size the scatter table from the optimizer's group-count estimate
        # so the common case never pays a collision retry (each retry
        # re-streams the ENTIRE source — 3 full passes for a 10k-group
        # aggregate started at 4096 slots, the q21 shape).  ~2x headroom
        # for probing; clamped so a wild overestimate cannot blow HBM.
        initial_slots = cfg.agg_slots
        if key_names and cfg.history_agg_groups:
            # history-based sizing (adaptive.history-sizing): the OBSERVED
            # group count from a prior run of this plan template beats any
            # estimate, and — being a measurement, not a guess — may size
            # BELOW agg_slots too (floored so a tiny group count cannot
            # degenerate the probe sequence)
            hist_based = 1 << max(0, (int(2 * cfg.history_agg_groups)
                                      - 1).bit_length())
            initial_slots = max(256, min(hist_based, 1 << 20))
        elif key_names:
            try:
                from ..sql.stats import StatsCalculator
                est_groups = StatsCalculator().rows(node)
            except Exception:   # noqa: BLE001 — estimate only
                est_groups = None
            if est_groups:
                # clamp only the ESTIMATE term: a user-configured
                # agg_slots above the clamp must never be reduced
                est_based = 1 << max(0, (int(2 * est_groups)
                                         - 1).bit_length())
                initial_slots = max(initial_slots,
                                    min(est_based, 1 << 20))

        # rough accumulator footprint for the budget check (hash + occupied
        # + per-key value/null + per-aggregate state columns)
        est_state_bytes = _agg_state_bytes(initial_slots, key_names, specs)

        def _sortagg_fn():
            low2 = self.lowering
            key = ("sortagg_fallback", node.id)
            fn = self._jit_cache.get(key)
            if fn is None:
                @jax.jit
                def fn(b):
                    inputs = {out: (low2.eval(e, b) if e is not None
                                    else None)
                              for out, e in input_exprs.items()}
                    inputs2 = {out: low2.eval(e, b)
                               for out, e in input_exprs2.items()}
                    return ops.sort_group_aggregate(b, key_names, inputs,
                                                    specs, inputs2)
                self._jit_cache[key] = fn
            return fn

        def drain_sort_input():
            """Drain the source once under per-batch reservation.
            Returns (merged, None) when the whole input fit the budget;
            else (None, stream) where the stream replays the collected
            (still-reserved) batches and then continues the SAME source
            iterator — the over-budget paths never re-execute the source
            and device bytes stay accounted until consumed."""
            pool = self.ctx.memory
            collected, reserved = [], 0
            it = self._compile(src_node).batches()
            over_batch = None
            for b in it:
                nb = batch_bytes(b)
                if pool.try_reserve(nb):
                    collected.append(b)
                    reserved += nb
                else:
                    over_batch = b
                    break
            if over_batch is None:
                merged = (_compact_concat(collected) if collected
                          else None)
                pool.free(reserved)
                if merged is None:
                    # zero-batch source: an all-masked schema-shaped
                    # batch so a global aggregate still yields its row
                    from .fused import _empty_build_batch
                    merged = _empty_build_batch(src_node)
                return merged, None

            def stream():
                try:
                    yield from collected
                    yield over_batch
                    yield from it
                finally:
                    pool.free(reserved)
            return None, stream()

        def run_global_percentile_stream(batches):
            """Global approx_percentile over a budget-exceeding input:
            one streaming pass keeping only an m-point mergeable quantile
            summary per batch (operators.percentile_batch_summary — the
            t-digest-state analog of
            ApproximateLongPercentileAggregations.java), plus the running
            scatter state for any sibling aggregates.  Rank error <=
            1/(2m) (m=8192 -> 0.006%); memory = O(batches * m) floats on
            the host, never the input."""
            m = ops.PERCENTILE_SKETCH_POINTS
            pct_specs = tuple(s for s in specs
                              if s.name == "approx_percentile")
            other_specs = tuple(s for s in specs
                                if s.name != "approx_percentile")
            low2 = self.lowering
            key = ("pctsketch", node.id)
            fns = self._jit_cache.get(key)
            if fns is None:
                @jax.jit
                def summarize(b):
                    out = {}
                    for s in pct_specs:
                        col = low2.eval(input_exprs[s.output], b)
                        alive = b.mask & ~col.null_mask()
                        out[s.output] = ops.percentile_batch_summary(
                            col.values, alive, m)
                    return out

                @jax.jit
                def update_others(state, b):
                    agg_cols = {s.output: low2.eval(
                        input_exprs[s.output], b)
                        if input_exprs[s.output] is not None else None
                        for s in other_specs}
                    agg_cols2 = {s.output: low2.eval(
                        input_exprs2[s.output], b)
                        for s in other_specs if s.name in ops.CORR_AGGS}
                    return ops.agg_update(state, b, [], agg_cols,
                                          other_specs, 256, 0, (),
                                          agg_cols2)
                self._jit_cache[key] = fns = (summarize, update_others)
            summarize, update_others = fns
            state = (ops.agg_init(256, other_specs, (), ())
                     if other_specs else None)
            summaries = {s.output: [] for s in pct_specs}
            for b in batches:
                for out, (pts, cnt) in summarize(b).items():
                    summaries[out].append((pts, cnt))
                if state is not None:
                    state = update_others(state, b)
            if state is not None:
                if not bool(jnp.any(state["__occupied"])):  # lint: allow-host-sync
                    state["__occupied"] = \
                        state["__occupied"].at[0].set(True)
                row = ops.agg_finalize(state, other_specs, (), {}, {})
            else:
                row = Batch({}, jnp.ones(1, dtype=bool))
            cols = dict(row.columns)
            for s in pct_specs:
                chunks = summaries[s.output]
                if chunks:
                    pts = jnp.stack([c[0] for c in chunks])
                    cnts = jnp.stack([c[1] for c in chunks])
                else:
                    pts = jnp.full((1, m), jnp.nan)
                    cnts = jnp.zeros(1, dtype=jnp.int64)
                p = float(s.param if s.param is not None else 0.5)
                val, is_null = ops.percentile_union_value(pts, cnts, p)
                if not s.is_float:
                    val = val.astype(jnp.int64)
                # broadcast to the finalize batch's capacity: every
                # column of a Batch must share one shape (the sibling
                # aggregate columns are full hash-table slots)
                cap = row.capacity
                cols[s.output] = Column(
                    jnp.broadcast_to(val[None], (cap,)),
                    jnp.broadcast_to(is_null[None], (cap,)))
            order = [v.name for v in node.aggregations]
            return Batch({o: cols[o] for o in order}, row.mask)

        def subdivide_bucket(bstore, p, depth, work):
            """K-way sub-partition of an over-budget bucket with a fresh
            salt (recursive grouped execution, same shape as the grace
            join's re-partition), shared by the sorted- and hash-spill
            paths.  The callers' depth caps differ DELIBERATELY: the
            sort path stops at 2 — beyond that only single-key skew
            remains, handled by the per-key summary path — while the
            hash path splits to 4 because its per-KEY state always
            shrinks with more partitions."""
            salt2 = bstore.salt * 33 + 0x9E37
            sub = self._new_spill_store(salt2)
            for bb in bstore.bucket_batches(p, cfg.batch_rows):
                sub.add(bb, list(key_names))
            work.extend((sub, q, depth + 1)
                        for q in range(cfg.spill_partitions))

        def fill_spill_store(batches=None):
            """Stream the source into a key-partitioned host store.
            Lazy open-domain key columns are whole-column encoded FIRST
            (row ids for non-ROWID_DISTINCT columns would split value
            groups across buckets) — shared by the hash-spill and
            sorted-spill paths."""
            store = self._new_spill_store()
            encode_keys = None
            if batches is None:
                batches = self._compile(src_node).batches()
            for batch in batches:
                if encode_keys is None:
                    encode_keys = []
                    for k in key_names:
                        col = batch.columns[k]
                        if col.lazy is not None:
                            _, tbl, coln, _sf = col.lazy
                            if (tbl, coln) not in catalog.ROWID_DISTINCT:
                                encode_keys.append(k)
                if encode_keys:
                    batch = _encode_lazy_keys(batch, encode_keys)
                store.add(batch, list(key_names))
            return store

        def run_sorted_spilled(batches):
            """Grouped percentile-class aggregation over budget: hash-
            partition rows by group key into host buckets (disjoint key
            sets), then run the exact sort aggregation bucket-by-bucket —
            the grouped-execution Lifespan model, same store the hash
            path spills through."""
            store = fill_spill_store(batches)
            fn = _sortagg_fn()
            pool = self.ctx.memory
            work = [(store, p, 0) for p in range(cfg.spill_partitions)]
            while work:
                bstore, p, depth = work.pop()
                rows_p = bstore.bucket_rows(p)
                if rows_p == 0:
                    continue
                bcap = 1 << max(0, rows_p - 1).bit_length()
                nb = bstore.bucket_bytes(p) * bcap // max(1, rows_p)
                if not pool.try_reserve(nb):
                    if depth >= 2:
                        # the bucket stopped shrinking: one (or a few)
                        # keys own more rows than the budget — no
                        # partitioning can split a single key's rows for
                        # the sort.  Per-key streaming summaries instead.
                        yield self._skewed_percentile_bucket(
                            bstore, p, key_names, specs, input_exprs,
                            input_exprs2)
                        continue
                    subdivide_bucket(bstore, p, depth, work)
                    continue
                try:
                    bucket = list(bstore.bucket_batches(p, bcap))[0]
                    yield _maybe_compact(fn(bucket))
                finally:
                    pool.free(nb)

        def gen():
            pool = self.ctx.memory
            fused = get_fused()
            grouped = None
            # EXPLAIN ANALYZE keeps the single-program fused path (its
            # row counters are per plan node); the grouped runner's
            # per-lifespan walls already land in runtime_stats
            if fused is not None and self.ctx.stats is None:
                grouped = fused_cache.get("grouped", False)
                if grouped is not False and grouped is not None \
                        and fused.build_params \
                        and grouped.params_fp != self.ctx.params_fingerprint:
                    # parameterized build tables (shared builds, bucket-0
                    # fanout probe) were sized under the old constants —
                    # rebuild the runner for this fingerprint
                    grouped = False
                if grouped is False:
                    from .grouped import make_grouped_runner
                    grouped = make_grouped_runner(
                        self, node, fused, key_names, specs, _agg_exprs,
                        basic_specs, bool(input_exprs2), cfg)
                    fused_cache["grouped"] = grouped
                if grouped is not None:
                    yield from grouped.run()
                    return
            shard = self.ctx.grouped_shard
            if shard is not None and shard[0] != 0:
                # the scheduler promised this stage disjoint lifespan
                # subsets over FULL splits, but grouped execution did not
                # engage at runtime: shard 0 alone runs the fallback over
                # everything; the other shards contribute nothing, so no
                # group is double-counted
                return
            if fused is not None:
                out = run_fused(fused)
                if out is not None:
                    yield out
                    return
            if sort_only_specs:
                if any(s.name in ops.HLL_AGGS for s in specs):
                    # percentile needs value-ordered segments (sort path),
                    # HLL needs the register file (hash path) — one
                    # aggregation node can't run both executors
                    raise NotImplementedError(
                        "approx_percentile and approx_distinct in the "
                        "same aggregation are not supported; split the "
                        "query into two aggregations")
                merged, stream = drain_sort_input()
                if stream is None:
                    yield _maybe_compact(_sortagg_fn()(merged))
                    return
                if not cfg.spill_enabled:
                    raise MemoryExceededError(
                        f"sort-aggregation input exceeds memory budget "
                        f"{pool.budget} bytes and spill is disabled")
                if key_names:
                    yield from run_sorted_spilled(stream)
                else:
                    yield run_global_percentile_stream(stream)
                return
            # grouped aggregation state is registered as a revocable
            # holder so arbitration/admission see it, but its callback
            # DECLINES (returns 0): a device hash table mid-scatter cannot
            # be spilled consistently, so the arbitrator moves on to the
            # next-largest victim and this operator self-spills below only
            # when its own reservation misses
            agg_holder = (pool.register_revocable("agg-state", lambda: 0)
                          if key_names else None)
            got = agg_holder is None \
                or agg_holder.try_reserve(est_state_bytes)
            if not got:
                agg_holder.close()
            if got:
                try:
                    state, key_dicts, key_lazy, direct = run_retrying()
                    if direct is not None:
                        yield ops.agg_direct_finalize(
                            state, specs, key_names, direct[0], direct[1],
                            key_dicts, force_row=not key_names)
                        return
                    if not key_names \
                            and not bool(jnp.any(state["__occupied"])):  # lint: allow-host-sync
                        # global aggregation over empty input: one row
                        state["__occupied"] = \
                            state["__occupied"].at[0].set(True)
                    yield ops.agg_finalize(state, specs, key_names,
                                           key_dicts, key_lazy)
                finally:
                    if agg_holder is not None:
                        agg_holder.close()
                return
            if not cfg.spill_enabled:
                raise MemoryExceededError(
                    f"aggregation table exceeds memory budget "
                    f"{pool.budget} bytes and spill is disabled")
            # budget too small for one table: hash-partition the input by
            # group keys into host-staged buckets and aggregate per bucket
            # (buckets hold disjoint key sets, so each finalize is exact)
            store = fill_spill_store()
            # each bucket sees ~1/K of the keys: start with a
            # proportionally smaller table, and account for it.  A bucket
            # never holds more distinct keys than rows, so cap by the
            # bucket's actual row count; if even that over-runs the pool,
            # halve the table until the reservation fits (more retry
            # passes instead of failure, mirroring the reference's
            # spill-don't-throw behavior, HashBuilderOperator.java:56).
            # Only when even the 256-slot minimum exceeds the remaining
            # budget does reserve() raise — no smaller table exists.
            per_slot = max(1, est_state_bytes // max(1, initial_slots))
            work = [(store, pp, 0) for pp in range(cfg.spill_partitions)]
            while work:
                bstore, p, depth = work.pop()
                rows_p = bstore.bucket_rows(p)
                if rows_p == 0:
                    continue

                bucket_slots = max(
                    256, min(initial_slots // cfg.spill_partitions,
                             1 << (2 * rows_p - 1).bit_length()))
                held = 0
                while True:
                    bucket_bytes = bucket_slots * per_slot
                    if pool.try_reserve(bucket_bytes):
                        held = bucket_bytes
                        break
                    if bucket_slots <= 256:
                        break
                    bucket_slots = max(256, bucket_slots // 2)
                if not held:
                    if depth < 4:
                        subdivide_bucket(bstore, p, depth, work)
                        continue
                    # even the minimum table exceeds the remaining
                    # budget after 4 re-partitions: raise the engine's
                    # exceeded-limit error
                    pool.reserve(bucket_bytes)
                # collision retries double the table — each growth is
                # re-reserved so device bytes never silently exceed the
                # budget; when the needed table cannot fit, sub-partition
                # instead of over-reserving
                num_slots, salt = bucket_slots, 0
                done = False
                try:
                    for _attempt in range(cfg.max_agg_retries):
                        state, key_dicts, key_lazy, direct = run_once(
                            num_slots, salt,
                            lambda b=bstore, p=p: b.bucket_batches(
                                p, cfg.batch_rows))
                        if direct is not None:
                            yield ops.agg_direct_finalize(
                                state, specs, key_names, direct[0],
                                direct[1], key_dicts)
                            done = True
                            break
                        if not bool(state["__collision"]):
                            yield ops.agg_finalize(state, specs,
                                                   key_names, key_dicts,
                                                   key_lazy)
                            done = True
                            break
                        grown = 2 * num_slots * per_slot
                        pool.free(held)
                        held = 0
                        if not pool.try_reserve(grown):
                            if depth < 4:
                                subdivide_bucket(bstore, p, depth, work)
                                done = True   # handled via sub-buckets
                                break
                            raise MemoryExceededError(
                                f"aggregation table of {grown} bytes "
                                f"exceeds memory budget {pool.budget} "
                                f"after {depth} re-partitions")
                        held = grown
                        num_slots *= 2
                        salt += 1
                    if not done:
                        raise RuntimeError(
                            "aggregation collision retries exhausted")
                finally:
                    pool.free(held)
        return BatchSource(gen, out_names, out_types)

    def _skewed_percentile_bucket(self, bstore, p, key_names, specs,
                                  input_exprs, input_exprs2) -> Batch:
        """Percentile aggregation over a spill bucket whose rows exceed
        the memory budget even after re-partitioning — i.e. single keys
        own more rows than fit (no key-hash split can help a sort).

        Split the work: percentile outputs come from per-key mergeable
        quantile summaries computed chunk-by-chunk over the HOST-resident
        spill rows (the summaries are the same m-point construction as
        operators.percentile_batch_summary, so rank error <= 1/(2m));
        every other aggregate runs exactly through the engine's scatter
        hash path over the same bucket (its state is per-KEY, tiny under
        skew).  The two result sets join on the grouping keys."""
        cfg = self.ctx.config
        pool = self.ctx.memory
        low = self.lowering
        pct_specs = [s for s in specs if s.name == "approx_percentile"]
        other_specs = tuple(s for s in specs
                            if s.name != "approx_percentile")
        for s in pct_specs:
            if not isinstance(input_exprs[s.output],
                              VariableReferenceExpression):
                raise NotImplementedError(
                    "approx_percentile over a computed expression on a "
                    "skew-spilled bucket")

        # --- per-key percentile summaries over host chunks (numpy,
        # vectorized grouping; summaries carry min(m, cnt) points so a
        # key contributing few rows to a chunk costs those rows only) ---
        m = ops.PERCENTILE_SKETCH_POINTS
        per_key: Dict[tuple, Dict[str, list]] = {}

        for rows in bstore.buckets[p]:
            n = len(next(iter(rows.values()))[0])
            arrs = []
            for k in key_names:
                vals, nulls = rows[k]
                arrs.append(vals)
                arrs.append(nulls if nulls is not None
                            else np.zeros(n, dtype=bool))
            rec = np.rec.fromarrays(arrs)
            uniq, inverse = np.unique(rec, return_inverse=True)
            order = np.argsort(inverse, kind="stable")
            bounds = np.searchsorted(inverse[order],
                                     np.arange(len(uniq) + 1))
            for g in range(len(uniq)):
                t = tuple(None if uniq[g][2 * j + 1] else
                          uniq[g][2 * j].item()  # lint: allow-host-sync
                          for j in range(len(key_names)))
                idxs = order[bounds[g]:bounds[g + 1]]
                ent = per_key.setdefault(
                    t, {s.output: [] for s in pct_specs})
                for s in pct_specs:
                    arg = input_exprs[s.output].name
                    vals, nulls = rows[arg]
                    v = vals[idxs]
                    if nulls is not None:
                        v = v[~nulls[idxs]]
                    cnt = len(v)
                    if cnt == 0:
                        continue
                    v = np.sort(v.astype(np.float64))
                    k_pts = min(m, cnt)
                    if k_pts < cnt:
                        pos = np.floor(np.arange(k_pts) * (cnt - 1)
                                       / (k_pts - 1) + 0.5) \
                            .astype(np.int64)
                        v = v[np.clip(pos, 0, cnt - 1)]
                    ent[s.output].append((v, cnt))

        def _pct_value(chunks, frac):
            if not chunks:
                return 0.0, True
            pts = np.concatenate([c[0] for c in chunks])
            w = np.concatenate([np.full(len(c[0]), c[1] / len(c[0]))
                                for c in chunks])
            order = np.argsort(pts, kind="stable")
            cum = np.cumsum(w[order])
            total = sum(c[1] for c in chunks)
            target = np.floor(frac * max(total - 1, 0) + 0.5)
            idx = int(np.searchsorted(cum, target, side="right"))
            return float(pts[order][min(idx, len(pts) - 1)]), False

        # --- non-percentile aggregates: exact scatter hash over the
        # bucket (keys are few, so a small table suffices) ---
        key_batch0 = next(iter(bstore.bucket_batches(p, cfg.batch_rows)))
        key_dtypes = [key_batch0.columns[k].values.dtype
                      for k in key_names]
        key_dicts = {k: key_batch0.columns[k].dictionary
                     for k in key_names
                     if key_batch0.columns[k].dictionary is not None}
        key_lazy = {k: key_batch0.columns[k].lazy for k in key_names
                    if key_batch0.columns[k].lazy is not None}
        out_batch = None
        if other_specs:
            num_slots, salt = 256, 0
            for _attempt in range(cfg.max_agg_retries):
                est = _agg_state_bytes(num_slots, key_names, other_specs)
                pool.reserve(est)
                try:
                    jk = ("skewagg", tuple(key_names), other_specs,
                          num_slots, salt)
                    upd = self._jit_cache.get(jk)
                    if upd is None:
                        @jax.jit
                        def upd(state, b):
                            kc = [b.columns[k] for k in key_names]
                            ac = {s.output: (low.eval(
                                input_exprs[s.output], b)
                                if input_exprs[s.output] is not None
                                else None) for s in other_specs}
                            ac2 = {s.output: low.eval(
                                input_exprs2[s.output], b)
                                for s in other_specs
                                if s.name in ops.CORR_AGGS}
                            return ops.agg_update(
                                state, b, kc, ac, other_specs,
                                num_slots, salt, tuple(key_names), ac2)
                        self._jit_cache[jk] = upd
                    state = ops.agg_init(num_slots, other_specs,
                                         tuple(key_names), key_dtypes)
                    for b in bstore.bucket_batches(p, cfg.batch_rows):
                        state = upd(state, b)
                    if not bool(jax.device_get(state["__collision"])):  # lint: allow-host-sync
                        out_batch = ops.agg_finalize(
                            state, other_specs, tuple(key_names),
                            key_dicts, key_lazy)
                        break
                finally:
                    pool.free(est)
                num_slots *= 2
                salt += 1
            if out_batch is None:
                raise RuntimeError(
                    "skewed-bucket aggregation collision retries "
                    "exhausted")
            # attach percentile columns by key lookup on the host
            kcols = [np.asarray(out_batch.columns[k].values)
                     for k in key_names]
            knulls = [None if out_batch.columns[k].nulls is None
                      else np.asarray(out_batch.columns[k].nulls)
                      for k in key_names]
            mask = np.asarray(out_batch.mask)
            cap = out_batch.capacity
            new_cols = dict(out_batch.columns)
            for s in pct_specs:
                vals = np.zeros(cap, dtype=np.float64)
                nulls = np.ones(cap, dtype=bool)
                for i in range(cap):
                    if not mask[i]:
                        continue
                    t = tuple(
                        (None if (knulls[j] is not None and knulls[j][i])
                         else kcols[j].item(i))
                        for j in range(len(key_names)))
                    ent = per_key.get(t)
                    if ent is None:
                        continue
                    frac = float(s.param if s.param is not None else 0.5)
                    v, isnull = _pct_value(ent[s.output], frac)
                    vals[i], nulls[i] = v, isnull
                arr = (jnp.asarray(vals) if s.is_float
                       else jnp.asarray(vals).astype(jnp.int64))
                new_cols[s.output] = Column(arr, jnp.asarray(nulls))
            return Batch(new_cols, out_batch.mask)

        # percentile-only aggregation: build the output from the host map
        keys = sorted(per_key, key=lambda t: tuple(
            (v is None, v) for v in t))
        cap = max(1, len(keys))
        cols: Dict[str, Column] = {}
        for j, k in enumerate(key_names):
            kv = np.zeros(cap, dtype=key_dtypes[j])
            kn = np.zeros(cap, dtype=bool)
            for i, t in enumerate(keys):
                if t[j] is None:
                    kn[i] = True
                else:
                    kv[i] = t[j]
            cols[k] = Column(jnp.asarray(kv),
                             jnp.asarray(kn) if kn.any() else None,
                             key_dicts.get(k), key_lazy.get(k))
        for s in pct_specs:
            frac = float(s.param if s.param is not None else 0.5)
            vals = np.zeros(cap, dtype=np.float64)
            nulls = np.ones(cap, dtype=bool)
            for i, t in enumerate(keys):
                vals[i], nulls[i] = _pct_value(per_key[t][s.output], frac)
            arr = (jnp.asarray(vals) if s.is_float
                   else jnp.asarray(vals).astype(jnp.int64))
            cols[s.output] = Column(arr, jnp.asarray(nulls))
        mask = np.zeros(cap, dtype=bool)
        mask[:len(keys)] = True
        return Batch(cols, jnp.asarray(mask))

    # -- joins ------------------------------------------------------------
    def _splits_fingerprint(self, node: P.PlanNode) -> str:
        """Task-assigned splits under a subtree, in walk order — part of
        the structural result-cache key: two structurally equal subtrees
        only share data when their scans cover the same splits."""
        parts = []
        for n in P.walk_plan(node):
            if isinstance(n, P.TableScanNode):
                sp = self.ctx.splits.get(n.id)
                parts.append("-" if sp is None else json.dumps(
                    [s.to_dict() for s in sp], sort_keys=True))
        return "|".join(parts)

    def _materialize(self, src: BatchSource) -> Optional[Batch]:
        batches = list(src.batches())
        if not batches:
            return None
        if len(batches) == 1:
            return batches[0]
        return _compact_concat(batches)

    def _materialize_node(self, node: P.PlanNode,
                          cache: bool = False) -> Optional[Batch]:
        """Materialize a subtree's full output as one batch, via the fused
        single-program path when the subtree is a fusible chain (zero host
        syncs), else by draining the streaming source.  cache=True keeps
        the result HBM-resident across re-executions (join build sides)
        and across structurally identical replays of the subtree (scalar-
        subquery re-plans, decorrelated copies)."""
        from .fused import _fmat_reserve, _renamed_batch, fused_materialize
        b = fused_materialize(self, node, cache=cache)
        if b is not None:
            return b
        skey = None
        if cache and not self.ctx.memory.limited:
            sk = P.structural_key(node)
            skey = ("mat_result", sk, self._splits_fingerprint(node))
            if '"@type": "parameter"' in sk:
                # parameterized subtree (an optimizer rule moved a probe
                # side into a build): the structural key is value-free, so
                # the cached result must be pinned to this execution's
                # bound values
                skey += (self.ctx.params_fingerprint,)
            ent = self._jit_cache.get(skey)
            if ent is not None:
                cached, names = ent
                return (None if cached is None else _renamed_batch(
                    cached, names, [v.name for v in node.output_variables]))
        out = self._materialize(self._compile(node))
        if skey is not None:
            from .memory import batch_bytes
            nb = 0 if out is None else batch_bytes(out)
            if _fmat_reserve(self, nb):
                self._jit_cache[skey] = \
                    (out, [] if out is None
                     else [v.name for v in node.output_variables])
        return out

    def _compile_JoinNode(self, node: P.JoinNode) -> BatchSource:
        if node.join_type not in (P.INNER, P.LEFT, P.FULL):
            raise NotImplementedError(f"join type {node.join_type}")
        full = node.join_type == P.FULL
        probe_src_node, build_src_node = node.left, node.right
        probe_keys = [l.name for l, r in node.criteria]
        build_keys = [r.name for l, r in node.criteria]
        out_names = [v.name for v in node.outputs]
        out_types = [v.type for v in node.outputs]
        from .fused import _join_build_cols
        build_names = [v.name for v in build_src_node.output_variables]
        # join outputs plus ON-filter-referenced build columns (pruning
        # may have dropped the latter from the output list)
        build_out = _join_build_cols(node, out_names, set(build_names))
        cfg = self.ctx.config
        low = self.lowering
        filter_expr = node.filter

        from .lowering import _jnp_dtype
        build_types = {v.name: v.type
                       for v in build_src_node.output_variables}

        def null_extended(batch):
            # LEFT join rows with no build match
            cols = dict(batch.columns)
            for name in build_out:
                t = build_types[name]
                if isinstance(t, (VarcharType, CharType)):
                    col = Column(
                        jnp.zeros(batch.capacity, dtype=jnp.int32),
                        jnp.ones(batch.capacity, dtype=bool), ("",))
                else:
                    col = Column(
                        jnp.zeros(batch.capacity, dtype=_jnp_dtype(t)),
                        jnp.ones(batch.capacity, dtype=bool))
                cols[name] = col
            return Batch(cols, batch.mask).select(out_names)

        filter_fn = (None if filter_expr is None
                     else (lambda pairs: low.eval(filter_expr, pairs)))

        def _jstep(batch, table, matched=None):
            joined, overflow, total, matched = ops.probe_join(
                batch, table, probe_keys, build_out,
                cfg.join_out_capacity,
                join_type="LEFT" if full else node.join_type,
                filter_fn=filter_fn, matched=matched)
            return joined, overflow, total, matched

        step = self.shared_jit((node.id, "join_step"), _jstep)

        def shrink(joined, live):
            """Compact a joined batch whose out_capacity padding dominates:
            downstream per-batch work (hash-agg scatter rounds, further
            probes) scales with CAPACITY, so selective joins would
            otherwise pay 2M-row costs for a few thousand live rows."""
            live = int(live)
            bucket = _bucket_for(live)
            if bucket is None or bucket * 4 > joined.capacity:
                return joined
            return _jit_compact(joined, bucket)

        probe_names = [n for n in out_names if n not in build_out]

        def unmatched_build(build_batch, matched):
            """FULL: build rows no probe row matched, probe side nulled."""
            from .lowering import _jnp_dtype
            probe_types = {v.name: v.type
                           for v in node.left.output_variables}
            cap = build_batch.capacity
            cols = {}
            for name in build_out:
                cols[name] = build_batch.columns[name]
            for name in probe_names:
                t = probe_types[name]
                if isinstance(t, (VarcharType, CharType)):
                    cols[name] = Column(jnp.zeros(cap, dtype=jnp.int32),
                                        jnp.ones(cap, dtype=bool), ("",))
                else:
                    cols[name] = Column(jnp.zeros(cap, dtype=_jnp_dtype(t)),
                                        jnp.ones(cap, dtype=bool))
            return Batch(cols, build_batch.mask & ~matched) \
                .select(out_names)

        # dynamic filtering (reference DynamicFilterSourceOperator): once
        # the build side is materialized, its per-key min/max narrows the
        # probe stream before the (more expensive) probe step; counted in
        # EXPLAIN ANALYZE stats as dynamicFilterRowsDropped
        df_cache: dict = {}

        def make_dynamic_filter(build_batch):
            # INNER only: LEFT joins carry dynamic_filters keyed by their
            # BUILD variables (the probe is preserved and must never be
            # narrowed — see plan_dynamic_filters' direction convention)
            if node.join_type != P.INNER or not node.dynamic_filters \
                    or build_batch is None:
                return None
            pairs = [(l.name, r.name) for l, r in node.criteria]
            numeric = [(ln, rn) for ln, rn in pairs
                       if build_batch.columns[rn].dictionary is None
                       and build_batch.columns[rn].lazy is None
                       and jnp.issubdtype(
                           build_batch.columns[rn].values.dtype,
                           jnp.integer)]
            if not numeric:
                return None
            if "fn" not in df_cache:
                names = tuple(rn for _ln, rn in numeric)
                probe_names = tuple(ln for ln, _rn in numeric)

                def _bounds(bb):
                    out = []
                    for rn in names:
                        c = bb.columns[rn]
                        m = bb.mask if c.nulls is None                             else bb.mask & ~c.nulls
                        v = c.values
                        out.append((
                            jnp.min(jnp.where(m, v, jnp.iinfo(v.dtype).max)),
                            jnp.max(jnp.where(m, v, jnp.iinfo(v.dtype).min))))
                    return out

                def _apply(batch, bnds):
                    keep = batch.mask
                    for (ln, lohis) in zip(probe_names, bnds):
                        lo, hi = lohis
                        v = batch.columns[ln].values
                        keep = keep & (v >= lo) & (v <= hi)
                    return batch.with_mask(keep)

                df_cache["fn"] = (
                    self.shared_jit((node.id, "df_bounds"), _bounds),
                    self.shared_jit((node.id, "df_apply"), _apply))
            bounds, apply = df_cache["fn"]
            bnds = bounds(build_batch)
            return lambda batch: apply(batch, bnds)

        def gen():
            pool = self.ctx.memory
            from .fused import fused_stream
            fs = fused_stream(self, node)
            if fs is not None:
                for b in fs:
                    yield b.select(out_names)
                return

            def probe_stream(table, batches, build_batch=None,
                             dyn_filter=None):
                stats_ent = None
                if dyn_filter is not None and self.ctx.stats is not None:
                    stats_ent = self.ctx.stats.setdefault(
                        node.id, {"rows": 0, "wall_s": 0.0, "batches": 0})
                    stats_ent.setdefault("dynamicFilterRowsDropped", 0)
                batches = iter(batches)
                batches = _apply_dyn_filter(batches, dyn_filter, stats_ent)
                yield from _probe_stream_inner(table, batches, build_batch)

            def _jdirect(batch, dt, matched):
                return ops.probe_join_direct(
                    batch, dt, probe_keys[0], build_out,
                    join_type="LEFT" if full else node.join_type,
                    filter_fn=filter_fn, matched=matched)

            step_direct = self.shared_jit((node.id, "join_direct"),
                                          _jdirect)

            def probe_stream_direct(dt, batches, build_batch,
                                    dyn_filter=None):
                stats_ent = None
                if dyn_filter is not None and self.ctx.stats is not None:
                    stats_ent = self.ctx.stats.setdefault(
                        node.id, {"rows": 0, "wall_s": 0.0, "batches": 0})
                    stats_ent.setdefault("dynamicFilterRowsDropped", 0)
                batches = _apply_dyn_filter(iter(batches), dyn_filter,
                                            stats_ent)
                matched = (jnp.zeros(build_batch.capacity, dtype=bool)
                           if full else None)
                for b in batches:
                    out, matched = step_direct(b, dt, matched)
                    yield out.select(out_names)
                if full:
                    yield unmatched_build(build_batch, matched)

            def _probe_stream_inner(table, batches, build_batch=None):
                # matched is threaded through for FULL joins; the build
                # rows nobody matched are emitted null-extended at the end
                matched = (jnp.zeros(build_batch.capacity, dtype=bool)
                           if full else None)
                # windowed drains: dispatch up to K probe batches, then
                # fetch ALL their (overflow, live) scalars in ONE
                # device_get — one tunnel round trip (~100ms on the axon
                # link) per K batches instead of per batch.  K shrinks as
                # join_out_capacity grows so the in-flight padded join
                # outputs stay bounded in HBM.
                from collections import deque
                work = deque()
                inflight = deque()   # (piece, joined, overflow, total)
                K = max(2, min(8, (1 << 22) // max(1,
                                                   cfg.join_out_capacity)))

                def submit(piece):
                    nonlocal matched
                    joined, overflow, total, matched = step(piece, table,
                                                            matched)
                    inflight.append((piece, joined, overflow, total))

                batches = iter(batches)
                exhausted = False
                while True:
                    # overflow-split pieces (work) refill regardless of
                    # iterator exhaustion — only NEW batches stop coming
                    while len(inflight) < K:
                        if work:
                            submit(work.popleft())
                            continue
                        if exhausted:
                            break
                        nxt = next(batches, None)
                        if nxt is None:
                            exhausted = True
                            break
                        submit(nxt)
                    if not inflight:
                        break
                    metas = jax.device_get(  # lint: allow-host-sync
                        [(ov, tot) for _p, _j, ov, tot in inflight])
                    window = list(inflight)
                    inflight.clear()
                    for (piece, joined, _o, _t), (ovv, livev) in zip(
                            window, metas):
                        if bool(ovv):
                            # recursive halving on output overflow: high-
                            # fanout probes (worst case a constant-key
                            # cross join) split until each piece fits
                            if piece.capacity <= 1:
                                raise RuntimeError(
                                    "join output overflow on a single "
                                    "probe row: raise join_out_capacity")
                            work.extendleft(reversed(_split_batch(piece)))
                            continue
                        yield shrink(joined, livev).select(out_names)
                if full:
                    yield unmatched_build(build_batch, matched)

            # materialize the build side under the memory budget; the
            # staging reservation is REVOCABLE — either this loop's own
            # budget miss or the arbitrator (another operator starving)
            # converts it into a grace hash join's partitioned host store
            # (reference: HashBuilderOperator.java:56 revocable memory +
            # partitioned spilling)
            buf = _RevocableBuildBuffer(self, build_keys, cfg.spill_enabled)
            try:
                from .fused import fused_materialize
                fb = fused_materialize(self, build_src_node, cache=True)
                if fb is not None:
                    # fused single-program build materialization (only when
                    # memory is unbudgeted, so no reservation bookkeeping)
                    buf.seed([fb])
                else:
                    for b in self._compile(build_src_node).batches():
                        buf.add(b)
                collected, spill = buf.finish()
                if spill is None:
                    build_batch = (
                        None if not collected else collected[0]
                        if len(collected) == 1
                        else _compact_concat(collected))
                    if build_batch is not None \
                            and self.ctx.shared_jits is not None:
                        # stage-shared tracing: sibling tasks' build sides
                        # differ by a few rows, which would retrace every
                        # shared join program per task — normalize to a
                        # power-of-two bucket so the stage converges on
                        # one build shape (costs one live-count sync)
                        live = int(jax.device_get(  # lint: allow-host-sync
                            build_batch.mask.sum()))
                        bucket = _bucket_for(live) \
                            or 1 << max(0, live - 1).bit_length()
                        if bucket != build_batch.capacity:
                            build_batch = _jit_compact(build_batch, bucket)
                    probe = self._compile(probe_src_node)
                    if build_batch is None:
                        if node.join_type == P.INNER:
                            return
                        for batch in probe.batches():
                            yield null_extended(batch)
                        return
                    from .fused import _drop_null_keys, try_direct_table
                    dropped = _drop_null_keys(build_batch,
                                              tuple(build_keys))
                    dt = (try_direct_table(dropped, build_keys[0],
                                           allow_dup=False)
                          if len(build_keys) == 1 else None)
                    if dt is not None:
                        # dense unique integer key: fanout-1 direct probe,
                        # zero per-batch host syncs (no overflow/live
                        # fetch — output capacity == probe capacity)
                        yield from probe_stream_direct(
                            dt, probe.batches(), build_batch,
                            dyn_filter=make_dynamic_filter(build_batch))
                        return
                    table = _jits()[1](dropped, tuple(build_keys))
                    yield from probe_stream(
                        table, probe.batches(), build_batch,
                        dyn_filter=make_dynamic_filter(build_batch))
                    return
                # grace path: partition the probe the same way, join
                # bucket-by-bucket (each bucket is a Lifespan).  A bucket
                # whose build side still exceeds the budget is RE-partitioned
                # with a fresh hash salt (recursive grace join); only a
                # bucket that stops shrinking — single-key skew — fails.
                probe_store = self._new_spill_store()
                for b in self._compile(probe_src_node).batches():
                    probe_store.add(b, probe_keys)
                work = [(spill, probe_store, p, 0)
                        for p in range(cfg.spill_partitions)]
                while work:
                    bstore, pstore, p, depth = work.pop()
                    p_rows = pstore.bucket_rows(p)
                    b_rows = bstore.bucket_rows(p)
                    # FULL still visits probe-empty buckets: their build
                    # rows must be emitted null-extended
                    if p_rows == 0 and (not full or b_rows == 0):
                        continue
                    if b_rows == 0:
                        if node.join_type == P.INNER:
                            continue
                        yield from map(null_extended,
                                       pstore.bucket_batches(
                                           p, cfg.batch_rows))
                        continue
                    # power-of-two build capacity bounds jit recompiles;
                    # the bucket goes back on device, so account for it
                    bcap = 1 << max(0, b_rows - 1).bit_length()
                    bucket_bytes = bstore.bucket_bytes(p) * bcap \
                        // max(1, b_rows)
                    if not pool.try_reserve(bucket_bytes):
                        if depth >= 4:
                            raise MemoryExceededError(
                                f"join build bucket of {bucket_bytes} bytes "
                                f"exceeds memory budget {pool.budget} after "
                                f"{depth} re-partitions (key skew)")
                        salt2 = bstore.salt * 33 + 0x9E37
                        sub_b = self._new_spill_store(salt2)
                        for bb in bstore.bucket_batches(p, cfg.batch_rows):
                            sub_b.add(bb, build_keys)
                        sub_p = self._new_spill_store(salt2)
                        for pb in pstore.bucket_batches(p, cfg.batch_rows):
                            sub_p.add(pb, probe_keys)
                        work.extend((sub_b, sub_p, q, depth + 1)
                                    for q in range(cfg.spill_partitions))
                        continue
                    try:
                        from .fused import _drop_null_keys
                        bucket = list(bstore.bucket_batches(p, bcap))[0]
                        table = _jits()[1](
                            _drop_null_keys(bucket, tuple(build_keys)),
                            tuple(build_keys))
                        yield from probe_stream(
                            table,
                            pstore.bucket_batches(p, cfg.batch_rows),
                            bucket)
                    finally:
                        pool.free(bucket_bytes)
            finally:
                buf.close()
        return BatchSource(gen, out_names, out_types)

    def _compile_SemiJoinNode(self, node: P.SemiJoinNode) -> BatchSource:
        src = self._compile(node.source)
        names = src.names + [node.semi_join_output.name]
        types = src.types + [BOOLEAN]
        key = node.source_join_variable.name
        fkey = node.filtering_source_join_variable.name

        @partial(jax.jit, static_argnames=("build_has_null",))
        def step(batch, table, build_has_null):
            marker = ops.semi_join_mark(batch, table, [key],
                                        build_has_null=build_has_null)
            return batch.with_columns({node.semi_join_output.name: marker})

        @partial(jax.jit, static_argnames=("build_has_null",))
        def step_direct(batch, dt, build_has_null):
            marker = ops.semi_join_mark_direct(
                batch, dt, key, build_has_null=build_has_null)
            return batch.with_columns({node.semi_join_output.name: marker})

        def gen():
            from .fused import fused_stream
            fs = fused_stream(self, node)
            if fs is not None:
                yield from (b.select(names) for b in fs)
                return
            build_batch = self._materialize_node(node.filtering_source,
                                                 cache=True)
            if build_batch is None:
                for b in src.batches():
                    yield b.with_columns({node.semi_join_output.name: Column(
                        jnp.zeros(b.capacity, dtype=bool), None)})
                return
            from .fused import (_build_has_null_key, _drop_null_keys,
                                try_direct_table)
            has_null = _build_has_null_key(build_batch, (fkey,))
            dropped = _drop_null_keys(build_batch, (fkey,))
            dt = try_direct_table(dropped, fkey, allow_dup=True)
            if dt is not None:
                for b in src.batches():
                    yield step_direct(b, dt, has_null)
                return
            table = _jits()[1](dropped, (fkey,))
            for b in src.batches():
                yield step(b, table, has_null)
        return BatchSource(gen, names, types)

    def _compile_AssignUniqueIdNode(self, node: P.AssignUniqueIdNode) -> BatchSource:
        """Row ids unique within the query (reference
        AssignUniqueIdOperator): task index in the high bits, a running
        per-task offset below.  Deterministic for a fixed split assignment,
        so a deep-copied subtree replays identical ids (the decorrelated
        EXISTS plan relies on this)."""
        src = self._compile(node.source)
        names = src.names + [node.id_variable.name]
        types = src.types + [v.type for v in [node.id_variable]]
        base = self.ctx.task_index << 40
        id_name = node.id_variable.name

        def gen():
            offset = 0
            for b in src.batches():
                ids = jnp.arange(b.capacity, dtype=jnp.int64) + (base + offset)
                offset += b.capacity
                yield b.with_columns({id_name: Column(ids)})
        return BatchSource(gen, names, types)

    def _compile_EnforceSingleRowNode(self, node) -> BatchSource:
        src = self._compile(node.source)

        def gen():
            seen = 0
            for b in src.batches():
                seen += int(b.mask.sum())
                if seen > 1:
                    raise RuntimeError(
                        "scalar subquery produced more than one row")
                yield b
        return BatchSource(gen, src.names, src.types)

    # -- local exchange is a no-op in the single-task pipeline ------------
    def _compile_ExchangeNode(self, node: P.ExchangeNode) -> BatchSource:
        if len(node.exchange_sources) == 1 and not node.inputs:
            return self._compile(node.exchange_sources[0])
        sources = [self._compile(s) for s in node.exchange_sources]
        out_vars = node.partitioning_scheme.output_layout
        names = [v.name for v in out_vars]
        types = [v.type for v in out_vars]

        def gen():
            for i, s in enumerate(sources):
                in_names = ([v.name for v in node.inputs[i]]
                            if node.inputs else s.names)
                for b in s.batches():
                    cols = {o: b.columns[n] for o, n in zip(names, in_names)}
                    yield Batch(cols, b.mask)
        return BatchSource(gen, names, types)


# ---------------------------------------------------------------------------
# host hoisting of string functions over late-materialized columns
#
# like()/substr() over open-domain columns (tpch.OPEN_DOMAIN) cannot run
# inside jit: the column holds row ids, the strings exist only in the
# generator.  The compiler rewrites such calls into synthetic variables and
# computes them per batch on the host before the jitted step — the TPU
# analog of the reference's ScanFilterAndProjectOperator evaluating
# non-vectorizable functions row-wise during the scan.
# ---------------------------------------------------------------------------


def _agg_state_bytes(num_slots: int, key_names, specs) -> int:
    """Accumulator footprint estimate shared by every aggregation budget
    check (hash + occupied + per-key value/null + per-aggregate state
    columns) — ONE formula so a state-layout change cannot drift the
    reservation paths apart."""
    return num_slots * (16 + 12 * len(key_names)
                        + 24 * max(1, len(specs))
                        + ops.hll_state_bytes(specs))


def _rewrite_agg_masks(node: P.AggregationNode) -> P.AggregationNode:
    """Lower Aggregation.mask (the reference's FILTER-WHERE / mask channel,
    AggregationNode.java Aggregation) into masked inputs: every aggregate
    in the engine ignores NULL inputs, so  agg(x) MASK m  ==
    agg(IF(m, x, NULL))  and  count(*) MASK m == count(IF(m, 1, NULL))."""
    if not any(a.mask is not None for a in node.aggregations.values()):
        return node
    from ..spi.expr import ConstantExpression, special
    aggs = {}
    for v, a in node.aggregations.items():
        if a.mask is None:
            aggs[v] = a
            continue
        call_ = a.call
        if call_.arguments:
            arg0 = call_.arguments[0]
            masked = special("IF", arg0.type, a.mask, arg0,
                             ConstantExpression(None, arg0.type))
            call_ = CallExpression(call_.display_name, call_.type,
                                   [masked] + list(call_.arguments[1:]))
        else:                       # count(*)
            masked = special("IF", BIGINT, a.mask,
                             ConstantExpression(1, BIGINT),
                             ConstantExpression(None, BIGINT))
            call_ = CallExpression(call_.display_name, call_.type, [masked])
        aggs[v] = P.Aggregation(call_, a.distinct, None)
    return P.AggregationNode(node.id, node.source, aggs,
                             node.grouping_keys, node.step)


def _direct_mode_info(key_names, key_cols,
                      gmax: int = ops.DIRECT_AGG_MAX_GROUPS):
    """Closed-small-domain eligibility for direct aggregation, shared by the
    streaming (run_once) and fused (get_fused) paths — must stay consistent
    with ops.agg_direct_finalize's slot decode.  key_cols may be real Columns
    or jax.eval_shape results (only dtype/nulls/dictionary/lazy are read).
    Returns None when ineligible, else
    (doms, G, strides, key_dtypes, key_dicts)."""
    doms = []
    for c in key_cols:
        if c.nulls is not None or c.lazy is not None:
            return None
        if c.dictionary is not None:
            doms.append(len(c.dictionary))
        elif c.values.dtype == jnp.bool_:
            doms.append(2)
        else:
            return None
    G = 1
    for d in doms:
        G *= max(1, d)
    if key_names and G > gmax:
        return None
    G = max(1, G)
    doms = tuple(max(1, d) for d in doms)
    strides, s = [], G
    for d in doms:
        s //= d
        strides.append(s)
    key_dicts = {k: c.dictionary for k, c in zip(key_names, key_cols)
                 if c.dictionary is not None}
    return (doms, G, tuple(strides),
            tuple(c.values.dtype for c in key_cols), key_dicts)


class _StringHoister:
    """Finds like/substr calls rooted at a variable, and — once the first
    batch shows which of those variables are late-materialized — rewrites
    them into host-computed columns."""

    def __init__(self, exprs):
        self.exprs = list(exprs)
        self.candidates: Dict[str, CallExpression] = {}
        for e in self.exprs:
            _find_string_calls(e, self.candidates)

    def resolve(self, first_batch: Batch):
        active: Dict[str, Tuple] = {}
        for key, c in self.candidates.items():
            col = first_batch.columns.get(_hoistable_var(c).name)
            if col is not None and col.lazy is not None:
                var = VariableReferenceExpression(
                    f"__hoist_{len(active)}_{abs(hash(key)) % 10**8}", c.type)
                active[key] = (var, c)
        if not active:
            return self.exprs, {}
        table = {k: v for k, (v, _) in active.items()}
        rewritten = [_rewrite_expr(e, table) for e in self.exprs]
        hoisted = {v.name: c for v, c in active.values()}
        return rewritten, hoisted


def _hoist_key(e: RowExpression) -> str:
    return json.dumps(e.to_dict(), sort_keys=True, default=str)


# lazy-column-hoistable string-breadth functions: column first, constant
# extras, never-NULL results (the xform caches carry no null channel)
_HOIST_XFORM = ("regexp_replace",)
_HOIST_PRED = ("regexp_like", "starts_with", "ends_with")


def _hoistable_var(e: CallExpression):
    """The single column argument of a host-hoistable string call, or
    None.  like/substr take the column first; concat takes one column
    anywhere among constant parts."""
    name = canonical_name(e.display_name)
    if name in ("like", "substr") + _HOIST_XFORM + _HOIST_PRED \
            and e.arguments and isinstance(
                e.arguments[0], VariableReferenceExpression) \
            and all(isinstance(a, ConstantExpression)
                    for a in e.arguments[1:]):
        return e.arguments[0]
    if name == "concat":
        var_args = [a for a in e.arguments
                    if isinstance(a, VariableReferenceExpression)]
        from ..spi.expr import ConstantExpression as _CE
        # a NULL constant part makes every result NULL — not hoistable
        # as a string transform (str(None) would bake the text "None")
        if len(var_args) == 1 and all(
                isinstance(a, _CE) and a.value is not None
                for a in e.arguments
                if not isinstance(a, VariableReferenceExpression)):
            return var_args[0]
    return None


def _find_string_calls(e: RowExpression, out: Dict[str, CallExpression]):
    if isinstance(e, CallExpression) and _hoistable_var(e) is not None:
        out[_hoist_key(e)] = e
        return
    for a in getattr(e, "arguments", None) or []:
        _find_string_calls(a, out)


def _rewrite_expr(e: RowExpression, table: Dict[str, RowExpression]):
    if isinstance(e, CallExpression):
        k = _hoist_key(e)
        if k in table:
            return table[k]
        return CallExpression(e.display_name, e.type,
                              [_rewrite_expr(a, table) for a in e.arguments])
    from ..spi.expr import SpecialFormExpression
    if isinstance(e, SpecialFormExpression):
        return SpecialFormExpression(
            e.form, e.type, [_rewrite_expr(a, table) for a in e.arguments])
    return e


_SUBSTR_DICT_CACHE: Dict[Tuple, Tuple[str, ...]] = {}
# whole-column substr codes / LIKE masks, indexed by row id: computed ONCE
# per (column, call) then every batch is a vectorized gather — re-running
# the Python string generator per batch per call site dominated q22-class
# queries (three substr sites over customer.phone cost ~10s each per run)
_SUBSTR_CODES_CACHE: Dict[Tuple, np.ndarray] = {}
_LIKE_MASK_CACHE: Dict[Tuple, np.ndarray] = {}
# entries are O(table rows): bound both caches (FIFO evict) so a
# long-lived worker serving varied patterns/scale factors cannot grow
# host memory without limit
_COLUMN_CACHE_MAX_ENTRIES = 64


def _cache_put(cache: Dict[Tuple, np.ndarray], key, value) -> None:
    if len(cache) >= _COLUMN_CACHE_MAX_ENTRIES:
        cache.pop(next(iter(cache)))
    cache[key] = value


def _canonical_substr_dict(cid: str, table: str, column: str, sf: float,
                           start: int, length) -> Tuple[str, ...]:
    """Batch-independent (whole-column) dictionary for substr over an
    open-domain column, so codes are stable across batches and sorted-rank
    ordering holds for ORDER BY / GROUP BY consumers."""
    key = (cid, table, column, sf, start, length)
    if key not in _SUBSTR_DICT_CACHE:
        n = catalog.table_row_count(table, sf, cid)
        uniq = set()
        for pos in range(0, n, 1 << 18):
            cnt = min(1 << 18, n - pos)
            strings = catalog.generate_values_at(
                table, column, sf, np.arange(pos, pos + cnt, dtype=np.int64),
                cid)
            uniq.update(_py_substr(s, start, length) for s in strings)
        _SUBSTR_DICT_CACHE[key] = tuple(sorted(uniq))
    return _SUBSTR_DICT_CACHE[key]


def _column_substr_codes(cid: str, table: str, column: str, sf: float,
                         start: int, length) -> np.ndarray:
    """int32 substr dictionary codes for EVERY row of the column."""
    from .. import native
    key = (cid, table, column, sf, start, length)
    codes_all = _SUBSTR_CODES_CACHE.get(key)
    if codes_all is None:
        cdict = _canonical_substr_dict(cid, table, column, sf, start,
                                       length)
        n = catalog.table_row_count(table, sf, cid)
        codes_all = np.empty(n, dtype=np.int32)
        index = None
        for pos in range(0, n, 1 << 18):
            cnt = min(1 << 18, n - pos)
            strings = catalog.generate_values_at(
                table, column, sf,
                np.arange(pos, pos + cnt, dtype=np.int64), cid)
            chunk = native.substr_dict_encode(strings, start, length, cdict)
            if chunk is None:
                if index is None:
                    index = {s: i for i, s in enumerate(cdict)}
                chunk = np.fromiter(
                    (index[_py_substr(s, start, length)] for s in strings),
                    dtype=np.int32, count=cnt)
            codes_all[pos:pos + cnt] = chunk
        _cache_put(_SUBSTR_CODES_CACHE, key, codes_all)
    return codes_all


def _column_like_mask(cid: str, table: str, column: str, sf: float,
                      pattern: str) -> np.ndarray:
    """LIKE match results for EVERY row of the column."""
    from .lowering import like_matcher
    from .. import native
    key = (cid, table, column, sf, pattern)
    mask_all = _LIKE_MASK_CACHE.get(key)
    if mask_all is None:
        n = catalog.table_row_count(table, sf, cid)
        mask_all = np.empty(n, dtype=bool)
        match = None
        for pos in range(0, n, 1 << 18):
            cnt = min(1 << 18, n - pos)
            strings = catalog.generate_values_at(
                table, column, sf,
                np.arange(pos, pos + cnt, dtype=np.int64), cid)
            chunk = native.like_match(strings, pattern)
            if chunk is None:
                if match is None:
                    match = like_matcher(pattern)
                chunk = np.fromiter((match(s) for s in strings),
                                    dtype=bool, count=cnt)
            mask_all[pos:pos + cnt] = chunk
        _cache_put(_LIKE_MASK_CACHE, key, mask_all)
    return mask_all


def _py_substr(s: str, start: int, length) -> str:
    i = start - 1 if start > 0 else len(s) + start
    return s[i:i + length] if length is not None else s[i:]


# whole-column codes for arbitrary per-string transforms (concat with
# constant parts etc.), sharing the bounded-cache discipline
_XFORM_DICT_CACHE: Dict[Tuple, Tuple[str, ...]] = {}
_XFORM_CODES_CACHE: Dict[Tuple, np.ndarray] = {}


def _column_xform_codes(cid, table, column, sf, tag, fn):
    key = (cid, table, column, sf, tag)
    cdict = _XFORM_DICT_CACHE.get(key)
    codes_all = _XFORM_CODES_CACHE.get(key)
    if cdict is None or codes_all is None:
        n = catalog.table_row_count(table, sf, cid)
        uniq = set()
        for pos in range(0, n, 1 << 18):
            cnt = min(1 << 18, n - pos)
            strings = catalog.generate_values_at(
                table, column, sf,
                np.arange(pos, pos + cnt, dtype=np.int64), cid)
            uniq.update(fn(x) for x in strings)
        cdict = tuple(sorted(uniq))
        index = {x: i for i, x in enumerate(cdict)}
        codes_all = np.empty(n, dtype=np.int32)
        for pos in range(0, n, 1 << 18):
            cnt = min(1 << 18, n - pos)
            strings = catalog.generate_values_at(
                table, column, sf,
                np.arange(pos, pos + cnt, dtype=np.int64), cid)
            codes_all[pos:pos + cnt] = np.fromiter(
                (index[fn(x)] for x in strings), dtype=np.int32, count=cnt)
        _cache_put(_XFORM_DICT_CACHE, key, cdict)
        _cache_put(_XFORM_CODES_CACHE, key, codes_all)
    return cdict, codes_all


_PRED_VALUE_CACHE: Dict[Tuple, np.ndarray] = {}


def _column_pred_values(cid, table, column, sf, tag, fn, dtype):
    """Per-row results of a value-returning string kernel over the whole
    column (the _column_like_mask pattern, generalized)."""
    key = (cid, table, column, sf, tag)
    out = _PRED_VALUE_CACHE.get(key)
    if out is None:
        n = catalog.table_row_count(table, sf, cid)
        out = np.empty(n, dtype=dtype)
        for pos in range(0, n, 1 << 18):
            cnt = min(1 << 18, n - pos)
            strings = catalog.generate_values_at(
                table, column, sf,
                np.arange(pos, pos + cnt, dtype=np.int64), cid)
            out[pos:pos + cnt] = np.fromiter(
                (fn(x) for x in strings), dtype=dtype, count=cnt)
        _cache_put(_PRED_VALUE_CACHE, key, out)
    return out


def _host_string_column(call_expr: CallExpression, batch: Batch) -> Column:
    arg = _hoistable_var(call_expr)
    col = batch.columns[arg.name]
    cid, table, column, sf = col.lazy
    name = canonical_name(call_expr.display_name)
    from .lowering import _STRING_TO_STRING, _STRING_TO_VALUE
    if name in _HOIST_XFORM:
        extra = tuple(a.value for a in call_expr.arguments[1:])
        kern = _STRING_TO_STRING[name]
        cdict, codes_all = _column_xform_codes(
            cid, table, column, sf, (name,) + extra,
            lambda x, _k=kern, _e=extra: _k(x, *_e))
        ids = np.clip(np.asarray(col.values), 0, len(codes_all) - 1)
        return Column(jnp.asarray(codes_all[ids]), col.nulls, cdict)
    if name in _HOIST_PRED:
        extra = tuple(a.value for a in call_expr.arguments[1:])
        kern, dtype = _STRING_TO_VALUE[name]
        vals_all = _column_pred_values(
            cid, table, column, sf, (name,) + extra,
            lambda x, _k=kern, _e=extra: _k(x, *_e), dtype)
        ids = np.clip(np.asarray(col.values), 0, len(vals_all) - 1)
        return Column(jnp.asarray(vals_all[ids]), col.nulls)
    if name == "concat":
        parts = tuple(None if isinstance(a, VariableReferenceExpression)
                      else str(a.value) for a in call_expr.arguments)
        fn = (lambda x, _p=parts: "".join(
            x if p is None else p for p in _p))
        cdict, codes_all = _column_xform_codes(
            cid, table, column, sf, ("concat", parts), fn)
        ids = np.clip(np.asarray(col.values), 0, len(codes_all) - 1)
        return Column(jnp.asarray(codes_all[ids]), col.nulls, cdict)
    if name == "like":
        pattern = str(call_expr.arguments[1].value)
        mask_all = _column_like_mask(cid, table, column, sf, pattern)
        # masked-out lanes may hold arbitrary ids: clamp for the gather
        ids = np.clip(np.asarray(col.values), 0, len(mask_all) - 1)
        return Column(jnp.asarray(mask_all[ids]), col.nulls)
    start = int(call_expr.arguments[1].value)
    length = (int(call_expr.arguments[2].value)
              if len(call_expr.arguments) > 2 else None)
    cdict = _canonical_substr_dict(cid, table, column, sf, start, length)
    codes_all = _column_substr_codes(cid, table, column, sf, start, length)
    ids = np.clip(np.asarray(col.values), 0, len(codes_all) - 1)
    return Column(jnp.asarray(codes_all[ids]), col.nulls, cdict)


def _add_hoisted(batch: Batch, hoisted: Dict[str, CallExpression]) -> Batch:
    if not hoisted:
        return batch
    return batch.with_columns({name: _host_string_column(c, batch)
                               for name, c in hoisted.items()})


_DEV_CODES_CACHE: Dict[Tuple, "jnp.ndarray"] = {}


def _encode_unordered_lazy_keys(batch: Batch, keys: List[str]) -> Batch:
    """Whole-column dictionary-encode any SORT-KEY column whose lazy row
    ids do not already sort like values (sort_indices requires id order ==
    lex order; see catalog.ROWID_ORDERED) — q30/q65-class ORDER BY over
    open-domain strings.  The codes table is uploaded to the device once
    and each batch is a device gather, so a STREAMED consumer (TopN) adds
    no per-batch host sync."""
    new_cols = {}
    for k in keys:
        col = batch.columns.get(k)
        if col is None or col.lazy is None:
            continue
        cid, tbl, coln, sf = col.lazy
        if (tbl, coln) in catalog.ROWID_ORDERED:
            continue
        cdict = _canonical_substr_dict(cid, tbl, coln, sf, 1, None)
        ck = (cid, tbl, coln, sf)
        codes_dev = _DEV_CODES_CACHE.get(ck)
        if codes_dev is None:
            codes_dev = jnp.asarray(
                _column_substr_codes(cid, tbl, coln, sf, 1, None))
            _cache_put(_DEV_CODES_CACHE, ck, codes_dev)
        ids = jnp.clip(col.values, 0, codes_dev.shape[0] - 1)
        new_cols[k] = Column(codes_dev[ids], col.nulls, cdict)
    return batch.with_columns(new_cols) if new_cols else batch


def _encode_lazy_keys(batch: Batch, keys: List[str]) -> Batch:
    """Replace late-materialized key columns by whole-column dictionary
    codes (for GROUP BY on small-pool open-domain columns, where row ids
    would split value groups)."""
    new_cols = {}
    for k in keys:
        col = batch.columns[k]
        cid, table, column, sf = col.lazy
        cdict = _canonical_substr_dict(cid, table, column, sf, 1, None)
        codes_all = _column_substr_codes(cid, table, column, sf, 1, None)
        # masked-out lanes may hold arbitrary ids: clamp for the gather
        ids = np.clip(np.asarray(col.values), 0, len(codes_all) - 1)
        new_cols[k] = Column(jnp.asarray(codes_all[ids]), col.nulls, cdict)
    return batch.with_columns(new_cols)


# ---------------------------------------------------------------------------
# batch utilities
# ---------------------------------------------------------------------------

def _concat_batches(batches: List[Batch]) -> Batch:
    names = list(batches[0].columns)
    cols = {}
    for n in names:
        first = batches[0].columns[n]
        values = jnp.concatenate([b.columns[n].values for b in batches])
        if any(b.columns[n].nulls is not None for b in batches):
            nulls = jnp.concatenate([b.columns[n].null_mask() for b in batches])
        else:
            nulls = None
        # ARRAY columns: lengths ride along like nulls (all batches of a
        # stream share a column's representation, so lengths are either
        # present everywhere or nowhere)
        if first.lengths is not None:
            lengths = jnp.concatenate([b.columns[n].lengths
                                       for b in batches])
        else:
            lengths = None
        # dictionaries must agree (scan layer guarantees table-stable dicts)
        cols[n] = Column(values, nulls, first.dictionary, first.lazy,
                         lengths)
    mask = jnp.concatenate([b.mask for b in batches])
    return Batch(cols, mask)


def _apply_dyn_filter(batches, dyn_filter, stats_ent):
    """Apply a dynamic filter to a probe stream, tracking dropped rows
    when EXPLAIN ANALYZE stats are enabled."""
    for b in batches:
        if dyn_filter is None:
            yield b
            continue
        nb = dyn_filter(b)
        if stats_ent is not None:
            before, after = jax.device_get((b.mask.sum(), nb.mask.sum()))  # lint: allow-host-sync
            stats_ent["dynamicFilterRowsDropped"] += int(before) - int(after)
        yield nb


def _split_batch(batch: Batch) -> List[Batch]:
    cap = batch.capacity
    half = cap // 2
    out = []
    for lo, hi in ((0, half), (half, cap)):
        cols = {n: c.slice_rows(lo, hi) for n, c in batch.columns.items()}
        out.append(Batch(cols, batch.mask[lo:hi]))
    return out
