"""Grouped (lifespan) execution: run a fused join+aggregation pipeline
bucket-by-bucket so peak HBM is ~1/K of the whole-table footprint.

The reference mechanism: when the tables under a join are bucketed on the
join key, a stage executes one bucket Lifespan at a time instead of
building the whole hash table at once (Lifespan.java:30-37,
GroupedExecutionTagger.java, session grouped_execution —
SystemSessionProperties.java:105); this is how Presto bounds memory for
huge joins without spilling.  TPU-first re-design:

  * Buckets come from the connector's co-bucketed layout
    (connectors/catalog.py bucket_layout): a key range maps to contiguous
    ROW RANGES in every co-bucketed table, so "repartitioning" is just
    split arithmetic — no shuffle pass, no partitioned spill files.
  * One bucket = one XLA program invocation.  All buckets share the SAME
    jitted program (pos/cnt arrays, build tables, and the key base are
    dynamic arguments; equal-sized buckets keep every shape static), so
    the host loop over K lifespans costs K dispatches, not K compiles.
  * Per-bucket aggregation is SORT-based (operators.sort_group_aggregate
    over the bucket's stacked chain output): measured fastest on chip
    against both the scatter table (~100ms per scattered million rows on
    TPU) and a streaming pre-grouped formulation whose extra segment
    gathers outweighed the argsort it avoided.  It is also fully general
    over grouping keys — no functional-dependency requirement.

Correctness argument: the anchor group key IS the bucket key, so every
output group lives in exactly one bucket; bucketed builds are restricted
to the bucket's key range, which drops only build rows that could never
match a probe row of this bucket; non-bucketed builds are replicated
across buckets (the reference broadcasts un-bucketed join sides under
grouped execution the same way).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..connectors import catalog
from ..spi import plan as P
from ..spi.expr import VariableReferenceExpression
from . import operators as ops
from .batch import Batch

# keyspace span above which auto mode engages, and the per-bucket span it
# targets (accumulator footprint and build-table size scale with the span)
AUTO_SPAN_THRESHOLD = 1 << 24
TARGET_BUCKET_SPAN = 1 << 22


def _resolve_to_scan(node: P.PlanNode, var_name: str):
    """Walk pass-through nodes to the TableScan column `var_name` reads, or
    None when the variable is computed (the PrestoToVeloxQueryPlan-style
    identity-lineage check a bucketing decision needs)."""
    while True:
        if isinstance(node, P.ProjectNode):
            expr = next((e for v, e in node.assignments.items()
                         if v.name == var_name), None)
            if not isinstance(expr, VariableReferenceExpression):
                return None
            var_name = expr.name
            node = node.source
        elif isinstance(node, P.FilterNode):
            node = node.source
        elif isinstance(node, P.ExchangeNode) and not node.inputs \
                and len(node.exchange_sources) == 1:
            src = node.exchange_sources[0]
            outer = [v.name for v in node.partitioning_scheme.output_layout]
            inner = [v.name for v in src.output_variables]
            try:
                var_name = inner[outer.index(var_name)]
            except ValueError:
                return None
            node = src
        elif isinstance(node, P.JoinNode):
            left_names = {v.name for v in node.left.output_variables}
            node = node.left if var_name in left_names else node.right
        elif isinstance(node, P.SemiJoinNode):
            if var_name == node.semi_join_output.name:
                return None
            node = node.source
        elif isinstance(node, P.TableScanNode):
            for v, col in node.assignments.items():
                if v.name == var_name:
                    return node, col.name
            return None
        else:
            return None


def _materialize_bucket_build(compiler, jn, scan_node, btable: str,
                              rows: Tuple[int, int]):
    """Materialize a join's build subtree restricted to one bucket's row
    range of its bucketed scan, through the FUSED path.

    The compiler memoizes BatchSources per node id, so the scan's cached
    source (which baked the previous bucket's splits into its fused_scan
    metadata) is evicted around the materialization and restored after —
    other consumers of the same node id keep their view, and the jitted
    fmat program is reused across buckets (its chunk arrays are dynamic
    arguments)."""
    from .fused import _empty_build_batch, fused_materialize
    cid = scan_node.table.connector_id
    sf = dict(scan_node.table.extra).get("scaleFactor", 0.01)
    ctx = compiler.ctx
    saved_split = ctx.splits.get(scan_node.id)
    saved_src = compiler._sources.pop(scan_node.id, None)
    ctx.splits[scan_node.id] = [catalog.TableSplit(
        cid, btable, sf, rows[0], rows[1])]
    try:
        b = fused_materialize(compiler, jn.right, cache=False)
    finally:
        if saved_split is None:
            ctx.splits.pop(scan_node.id, None)
        else:
            ctx.splits[scan_node.id] = saved_split
        if saved_src is None:
            compiler._sources.pop(scan_node.id, None)
        else:
            compiler._sources[scan_node.id] = saved_src
    if b is None:
        b = _empty_build_batch(jn.right)
    return b


def _full_coverage(splits, table: str, sf: float, cid: str) -> bool:
    """Whether the scan's splits cover the whole table contiguously (a
    distributed task owning a split subset must not re-bucket it)."""
    total = catalog.table_row_count(table, sf, cid)
    ranges = sorted((s.start, s.end) for s in splits)
    pos = 0
    for lo, hi in ranges:
        if lo != pos:
            return False
        pos = hi
    return pos == total


class GroupedRunner:
    """Compiled per-bucket programs + layout; .run() yields one finalized
    aggregation batch per lifespan.  Built once per plan compile and
    reused across re-executions (jitted programs are instance state)."""

    def __init__(self, compiler, chain, layout, anchor, dep_names,
                 key_names, specs, agg_exprs_fn, G, expands, shared_aux,
                 per_bucket_builds, key_dtypes, key_dicts, probe_table):
        self.compiler = compiler
        self.chain = chain
        self.layout = layout
        self.anchor = anchor
        self.dep_names = dep_names
        self.key_names = key_names
        self.specs = specs
        self.agg_exprs_fn = agg_exprs_fn
        self.G = G
        self.expands = expands
        self.shared_aux = shared_aux          # None entries = per-bucket
        self.per_bucket_builds = per_bucket_builds
        self.key_dtypes = key_dtypes
        self.key_dicts = key_dicts
        self.probe_table = probe_table
        self.leaf_cap = chain.leaf_cap(expands)
        # parameter fingerprint the shared aux / bucket-0 probe / fanout
        # reservations were built under; the caller rebuilds the runner
        # when a parameterized BUILD subtree sees a different fingerprint
        self.params_fp = compiler.ctx.params_fingerprint
        self._sort_progs: Dict[int, callable] = {}
        # bucket-0 (aux, dup flags) built during eligibility; consumed by
        # the first run() so the build work is not repeated
        self._aux0 = None

    # -- per-bucket pieces -------------------------------------------------

    def _bucket_chunks(self, rows: Tuple[int, int]):
        p, end = rows
        out = []
        while p < end:
            n = min(self.leaf_cap, end - p)
            out.append((p, n))
            p += n
        return out

    def _bucket_aux(self, bucket):
        """aux tuple for this bucket: shared entries + freshly materialized
        bucketed build tables (restricted to the bucket's row range, via
        _materialize_bucket_build).  A build whose reserved fanout is 1
        becomes a direct-address table keyed off the bucket's key base;
        a fanout-k build becomes a hash-sorted table probed with the
        k-way expansion the shared program reserved at prep time."""
        from .fused import DirectTable, _direct_builder, _drop_null_keys, \
            _max_run
        aux = list(self.shared_aux)
        # per-build overflow flags (device bools): key duplicated in a
        # fanout-1 build, or multiplicity > k in a fanout-k build
        dups: List = []
        for (ai, jn, scan_node, btable, bkey, k) in self.per_bucket_builds:
            b = _materialize_bucket_build(self.compiler, jn, scan_node,
                                          btable, bucket.rows[btable])
            b = _drop_null_keys(b, (bkey,))
            if k == 1:
                col = b.columns[bkey]
                slots, dup = _direct_builder(self.G)(
                    col.values, b.mask, jnp.int64(bucket.key_lo))
                dups.append(dup)
                aux[ai] = DirectTable(slots, jnp.int64(bucket.key_lo),
                                      dict(b.columns))
            else:
                from .pipeline import _jits
                tbl = _jits()[1](b, (bkey,))
                dups.append(_max_run(tbl) > k)
                aux[ai] = tbl
        return tuple(aux), dups

    def _get_sort_prog(self, S: int):
        prog = self._sort_progs.get(S)
        if prog is None:
            chain, expands, leaf_cap = self.chain, self.expands, self.leaf_cap
            key_names, specs = self.key_names, self.specs
            agg_exprs = self.agg_exprs_fn

            @jax.jit
            def prog(pos_arr, cnt_arr, aux):
                def step(pc):
                    b = chain.make(pc[0], pc[1], aux, expands, leaf_cap)
                    cols = {k: b.columns[k] for k in key_names}
                    for out, col in agg_exprs(b).items():
                        if col is not None:
                            cols["$in_" + out] = col
                    return Batch(cols, b.mask)
                stacked = jax.lax.map(step, (pos_arr, cnt_arr))
                flat = jax.tree_util.tree_map(
                    lambda a: a.reshape((-1,) + a.shape[2:]), stacked)
                inputs = {s.output: flat.columns.get("$in_" + s.output)
                          for s in specs}
                return ops.sort_group_aggregate(
                    Batch({k: flat.columns[k] for k in key_names},
                          flat.mask), key_names, inputs, specs, {})
            self._sort_progs[S] = prog
        return prog

    # -- driver ------------------------------------------------------------

    @staticmethod
    def _check_dups(dup_flags) -> None:
        if dup_flags and any(bool(d) for d in jax.device_get(dup_flags)):  # lint: allow-host-sync
            # a bucketed build's key multiplicity exceeds what the shared
            # program reserved for this bucket (duplicates against a
            # direct table, or a run longer than the fanout-k expansion):
            # the probe would keep an arbitrary subset of matches, and
            # earlier lifespans already streamed downstream, so the only
            # correct move is to fail loudly (the single-lifespan path
            # handles any fanout via replicated builds)
            raise NotImplementedError(
                "grouped execution: bucketed build key multiplicity "
                "exceeds the reserved fanout within a lifespan")

    def _stage_bucket(self, bi: int, aux0):
        """Host-stage one bucket: split arithmetic, build materialization
        (device dispatches + small sync), chunk arrays.  Returns the
        ready-to-dispatch entry, or None for an empty bucket."""
        bucket = self.layout[bi]
        chunks = self._bucket_chunks(bucket.rows[self.probe_table])
        if not chunks:
            return None
        if bi == 0 and aux0 is not None:
            aux, dups = aux0
        else:
            aux, dups = self._bucket_aux(bucket)
        if self.chain.has_params:
            # shared aux carries the params vector bound when the runner
            # was built — swap in this execution's (traced arg: no retrace)
            aux = tuple(aux)[:-1] + (self.compiler.ctx.params,)
        pos_arr = jnp.asarray([c[0] for c in chunks], dtype=jnp.int64)
        cnt_arr = jnp.asarray([c[1] for c in chunks], dtype=jnp.int64)
        return len(chunks), pos_arr, cnt_arr, aux, dups

    def run(self):
        """Pipelined lifespan loop: keep up to grouped_prefetch_depth
        buckets STAGED (builds materialized, chunk arrays device-put)
        beyond the one being consumed, so bucket k+1's host reads and
        host->HBM transfers overlap bucket k's device compute — JAX async
        dispatch executes device programs in dispatch order, so staging
        ahead keeps the device queue full while downstream drains bucket
        k.  Depth 0 reproduces the strictly serial pre-pipelining loop.

        With lifespan sharding (TaskContext.grouped_shard = (i, n)) this
        task runs only buckets i, i+n, ... — the scheduler hands every
        task full splits and disjoint bucket subsets.

        RuntimeStats (when the runner wired a sink into the context):
        groupedBucketGenWallNanos  — host wall staging each bucket
        groupedBucketComputeWallNanos — wall from dispatching a bucket's
        program until downstream finished consuming it
        groupedRunWallNanos — whole loop; overlap shows as run wall <
        gen.sum + compute.sum."""
        import time
        from collections import deque
        ctx = self.compiler.ctx
        depth = max(0, getattr(ctx.config, "grouped_prefetch_depth", 1))
        stats = getattr(ctx, "runtime_stats", None)
        aux0 = self._aux0
        self._aux0 = None           # one-shot: don't pin HBM across runs
        indices = range(len(self.layout))
        shard = getattr(ctx, "grouped_shard", None)
        if shard is not None:
            indices = range(shard[0], len(self.layout), shard[1])
        t_run = time.perf_counter_ns()  # lint: allow-wall-clock
        it = iter(indices)
        staged = deque()
        exhausted = False
        while True:
            while not exhausted and len(staged) <= depth:
                bi = next(it, None)
                if bi is None:
                    exhausted = True
                    break
                t0 = time.perf_counter_ns()  # lint: allow-wall-clock
                ent = self._stage_bucket(bi, aux0)
                if stats is not None:
                    stats.add("groupedBucketGenWallNanos",
                              time.perf_counter_ns() - t0)  # lint: allow-wall-clock
                if ent is not None:
                    staged.append(ent)
            if not staged:
                break
            S, pos_arr, cnt_arr, aux, dups = staged.popleft()
            self._check_dups(dups)
            # per-bucket SORT aggregation: measured fastest on chip for
            # the SF100 shapes (argsort+segment scans beat both the
            # scatter table, ~100ms per scattered million rows, and a
            # streaming pre-grouped formulation whose extra segment
            # gathers outweighed the argsort it avoided)
            t0 = time.perf_counter_ns()  # lint: allow-wall-clock
            yield self._get_sort_prog(S)(pos_arr, cnt_arr, aux)
            if stats is not None:
                stats.add("groupedBucketComputeWallNanos",
                          time.perf_counter_ns() - t0)  # lint: allow-wall-clock
        if stats is not None:
            stats.add("groupedRunWallNanos",
                      time.perf_counter_ns() - t_run)  # lint: allow-wall-clock


def make_grouped_runner(compiler, node, chain, key_names, specs,
                        agg_exprs_fn, basic_specs, has_exprs2,
                        cfg) -> Optional[GroupedRunner]:
    """Eligibility + one-time prep.  Returns a GroupedRunner, or None to
    keep the single-lifespan path.  Called once per plan compile; cached
    by the aggregation compiler."""
    pool = compiler.ctx.memory
    if pool.budget is not None or has_exprs2 or not key_names:
        return None
    if not basic_specs:
        return None
    # parameterized chains are fine here: probe-side params ride the last
    # aux slot and _stage_bucket swaps in each execution's vector, and
    # bucketed builds re-materialize per run with the current params.
    # Shared builds / fanout reservations ARE frozen at build time, so
    # the caller rebuilds the runner when chain.build_params and the
    # fingerprint moved (see the gen() guard in pipeline.py).
    # PARTIAL is safe: each bucket's exact aggregate is a valid partial
    # state for the decomposable basic aggs, and the FINAL stage merges
    # per-bucket rows the same way it merges per-task rows
    if getattr(node, "step", P.SINGLE) not in (P.SINGLE, P.PARTIAL):
        return None
    K_conf = cfg.grouped_lifespans
    if K_conf == 1:
        return None
    meta = chain.scan_meta
    table, cid, sf = meta.get("table"), meta.get("cid"), meta.get("sf")
    if table is None:
        return None
    bcol = catalog.bucket_column(table, cid)
    if bcol is None:
        return None
    if not _full_coverage(meta["splits"], table, sf, cid):
        return None

    # lineage: which live column names carry the scan's bucket column
    colmap = meta.get("colmap", {})
    carriers = {n for n, c in colmap.items() if c == bcol}
    if not carriers:
        return None
    bucketed_joins: Dict[int, tuple] = {}
    for si, step in enumerate(chain.steps):
        kind = step[0]
        if kind == "project":
            carriers = {v.name for v, e in step[1]
                        if isinstance(e, VariableReferenceExpression)
                        and e.name in carriers}
        elif kind == "rename":
            carriers = {o for o, i in step[1] if i in carriers}
        elif kind == "join":
            jn = step[1]
            hit = None
            for left, right in jn.criteria:
                if left.name not in carriers:
                    continue
                res = _resolve_to_scan(jn.right, right.name)
                if res is None:
                    continue
                scan_node, col2 = res
                t2 = scan_node.table.table_name
                c2 = scan_node.table.connector_id
                if c2 == cid and catalog.bucket_column(t2, c2) == col2:
                    hit = (jn, scan_node, t2, right.name)
                    break
            if hit is not None:
                bucketed_joins[si] = hit
                if jn.join_type == P.INNER:
                    # the matched build key equals the probe key
                    carriers |= {r.name for l, r in jn.criteria
                                 if l.name in carriers}
            # non-bucketed joins replicate their build: correct, just no
            # memory win
        if not carriers:
            return None
    anchor = next((k for k in key_names if k in carriers), None)
    if anchor is None:
        return None     # groups would straddle buckets

    layout1 = catalog.bucket_layout(sf, 1, cid)
    if not layout1:
        return None
    span_total = layout1[-1].key_hi - layout1[0].key_lo
    if K_conf >= 2:
        K = K_conf
    else:               # auto: engage only for huge keyspaces
        if span_total <= AUTO_SPAN_THRESHOLD:
            return None
        K = -(-span_total // TARGET_BUCKET_SPAN)
    layout = catalog.bucket_layout(sf, K, cid)
    if len(layout) <= 1 and K_conf < 2:
        return None
    max_span = max(b.key_hi - b.key_lo for b in layout)
    if max_span > ops.SPAN_AGG_MAX_GROUPS:
        return None
    G = 1 << (max_span - 1).bit_length()

    # shared (bucket-invariant) builds once; bucketed builds defer to the
    # per-bucket lifespan (FusedChain.prep owns the aux-slot layout).  A
    # bucketed build must materialize through the fused path — its chunk
    # layout re-derives from the per-bucket split override — so
    # non-fusible bucketed builds are replicated instead.
    #
    # Fanout probing: the shared program must reserve a STATIC expansion
    # factor per deferred join, so probe bucket 0's build now and size k
    # from its maximum key run (k==1 -> direct table; k>1 -> hash table
    # probed with k-way expansion, e.g. a self-join on the bucket key).
    # Later buckets exceeding k fail loudly at runtime (_check_dups).
    from .fused import MAX_EXPAND, _drop_null_keys, _max_run, \
        assemble_chain

    fanouts: Dict[int, int] = {}
    for si, (jn, scan_node, t2, bkey) in bucketed_joins.items():
        if assemble_chain(compiler, jn.right) is None:
            continue                    # not fusible: replicate instead
        try:
            b0 = _materialize_bucket_build(compiler, jn, scan_node, t2,
                                           layout[0].rows[t2])
        except NotImplementedError:
            continue
        b0 = _drop_null_keys(b0, (bkey,))
        from .pipeline import _jits
        kmax = int(jax.device_get(_max_run(_jits()[1](b0, (bkey,)))))  # lint: allow-host-sync
        if kmax > MAX_EXPAND:
            continue                    # too wide to reserve: replicate
        fanouts[si] = 1 if kmax <= 1 else 1 << (kmax - 1).bit_length()

    def _defer(si, jn):
        return fanouts.get(si, 0)

    try:
        prep_res = chain.prep(defer=_defer)
    except NotImplementedError:
        return None
    if prep_res is None:
        return None
    shared_aux, expands, deferred = prep_res
    shared_aux = list(shared_aux)
    per_bucket_builds = [
        (ai, jn, bucketed_joins[si][1], bucketed_joins[si][2],
         bucketed_joins[si][3], fanouts[si])
        for ai, si, jn in deferred]

    runner = GroupedRunner(compiler, chain, layout, anchor,
                           tuple(k for k in key_names if k != anchor),
                           key_names, specs, agg_exprs_fn, G, expands,
                           shared_aux, per_bucket_builds, {}, {}, table)

    # probe schema (dtypes/dicts of the grouping keys) from a shape-only
    # evaluation with bucket 0's aux; the materialized builds are kept on
    # the runner so the first run() does not repeat the device work
    try:
        aux0, dups0 = runner._bucket_aux(layout[0])
    except NotImplementedError:
        return None
    if dups0 and any(bool(d) for d in jax.device_get(dups0)):  # lint: allow-host-sync
        return None     # non-unique bucketed build key: single lifespan
    runner._aux0 = (aux0, dups0)
    try:
        probe = jax.eval_shape(
            lambda p, v: chain.make(p, v, aux0, expands, runner.leaf_cap),
            jnp.int64(0), jnp.int64(1))
    except NotImplementedError:
        return None
    key_dtypes, key_dicts = {}, {}
    for k in key_names:
        c = probe.columns.get(k)
        if c is None or c.lazy is not None:
            return None
        key_dtypes[k] = c.values.dtype
        if c.dictionary is not None:
            key_dicts[k] = c.dictionary
    if probe.columns[anchor].dictionary is not None:
        return None
    if probe.columns[anchor].nulls is not None:
        # nullable bucket key: a NULL anchor has no home bucket, so its
        # group would be duplicated across lifespans (catalog.py
        # bucket_column contract) — keep the single-lifespan path
        return None
    runner.key_dtypes = key_dtypes
    runner.key_dicts = key_dicts
    return runner


# wrappers a fragment plants above its aggregation that don't change
# whether the agg itself can run grouped
_PEELABLE = (P.ProjectNode, P.FilterNode, P.SortNode, P.TopNNode,
             P.LimitNode)

_SHARDABLE_AGGS = {"sum", "avg", "count", "count_star", "min", "max"}


def stage_shards_lifespans(root: P.PlanNode, cfg) -> bool:
    """Plan-time predicate for the scheduler: may the tasks of this
    SOURCE-distributed fragment be given FULL splits plus disjoint
    round-robin lifespan subsets (TaskContext.grouped_shard) instead of
    the usual split round-robin?

    Mirrors make_grouped_runner's STATIC eligibility conditions (the
    ones decidable without compiling): one bucketed scan, a grouped
    basic aggregation keyed on its bucket column, config gates, and the
    force/auto lifespan-count decision.  A misprediction is safe in
    both directions — if grouped execution then fails to engage at
    runtime, shard 0 runs the ordinary fallback over the full splits
    and the other shards contribute nothing (pipeline.py gen()); if it
    would have engaged but this predicate said no, tasks fall back to
    split subsets, which _full_coverage rejects, and each task runs the
    ordinary single-lifespan path over its subset."""
    from .lowering import canonical_name
    if not cfg.grouped_lifespan_sharding or not cfg.fuse_pipelines:
        return False
    if cfg.grouped_lifespans == 1 or cfg.memory_budget_bytes is not None \
            or cfg.memory_max_query_bytes is not None:
        return False
    node = root
    while isinstance(node, _PEELABLE):
        node = node.source
    if not isinstance(node, P.AggregationNode):
        return False
    if getattr(node, "step", P.SINGLE) not in (P.SINGLE, P.PARTIAL):
        return False
    if not node.grouping_keys:
        return False
    for agg in node.aggregations.values():
        if agg.distinct or agg.mask is not None:
            return False
        fname = canonical_name(agg.call.display_name)
        if fname == "count" and not agg.call.arguments:
            fname = "count_star"
        if fname not in _SHARDABLE_AGGS:
            return False
    # exactly one scan subtree: broadcast build sides arrive as
    # RemoteSources in a SOURCE fragment, so >1 scan means a co-located
    # join shape the runtime walker would have to re-verify per task
    scans = [n for n in P.walk_plan(node)
             if isinstance(n, P.TableScanNode)]
    if len(scans) != 1:
        return False
    scan = scans[0]
    table = scan.table.table_name
    cid = scan.table.connector_id
    bcol = catalog.bucket_column(table, cid)
    if bcol is None:
        return False
    if not any((_resolve_to_scan(node.source, k.name) or (None, None))
               == (scan, bcol) for k in node.grouping_keys):
        return False
    if cfg.grouped_lifespans >= 2:
        return True
    sf = dict(scan.table.extra).get("scaleFactor", 0.01)
    layout1 = catalog.bucket_layout(sf, 1, cid)
    if not layout1:
        return False
    return layout1[-1].key_hi - layout1[0].key_lo > AUTO_SPAN_THRESHOLD
