"""In-kernel join probe lowering for the Pallas fused scan kernel.

exec/fused.py already compiles probe-side FK->PK join chains into one
XLA program, but each probe still materializes gathered build columns
as a full chunk-width page between chain steps.  This module lowers the
two fanout-1 probe forms into the scan kernel BODY so
decode -> filter -> probe(-> probe...) -> compact -> agg runs in a
single PrefetchScalarGridSpec launch:

  * DirectTable (fused.probe_direct / ops.direct_lookup): dense integer
    PK; the probe is one int32 gather against the whole-block
    VMEM-resident slot array.
  * hash-sorted ops.BuildTable (fused.probe_unique): multi-column or
    sparse keys; searchsorted becomes the fixed-trip _bisect_left below
    (jnp.searchsorted does not lower inside Pallas TPU kernels; the
    loop is exact integer arithmetic, so it cannot drift from the XLA
    chain's side="left" search).

plan_join_layout inspects the chain's join/semi steps ONCE per launch
and flattens every build operand (slot/hash arrays, gathered build
columns, the semi null-key flag) into a positional array list; the scan
kernel passes them as whole-1D VMEM blocks and join_appliers rebuilds
per-step closures over the in-kernel refs.  Build operands therefore
live across the entire grid without ever being re-materialized as a
probe output page.

Gates (kernelDeclined reasons, scan_kernel.KERNEL_DECLINE_REASONS):
  JoinShape      fanout-k expansion joins (expands[ji] > 1), residual
                 ON filters, non-INNER/LEFT forms, deferred build
                 slots, and dictionary/lazy build columns (their
                 decode state lives outside the kernel)
  JoinBuildSize  flattened operand bytes over
                 KERNEL_JOIN_MAX_BUILD_BYTES, or the MemoryContext
                 reservation failed (kernels hold a live device
                 reference, so the bytes are charged NON-revocable:
                 arbitration may revoke others to admit them but can
                 never spill the build mid-launch)

Parity contract: the applier math is copied operation-for-operation
from ops.direct_lookup / fused.probe_unique / FusedChain._apply_join /
the semi branch of FusedChain.make, so hit masks, gathered values and
three-valued semi markers are bit-identical to the XLA chain.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax.numpy as jnp

from ...spi import plan as P
from .. import operators as ops
from ..batch import Batch, Column

# cap on the flattened build-operand bytes a single kernel launch may
# pin in VMEM next to the decoded block (dim tables for the Q3/Q18/Q95
# shapes are far below this; a fact-sized build declines and runs the
# XLA chain, which pages through HBM instead)
KERNEL_JOIN_MAX_BUILD_BYTES = 1 << 22


def _bisect_left(a, v):
    """searchsorted(a, v, side="left") as a fixed-trip vectorized
    binary search — the side="left" twin of scan_kernel._bisect_right,
    matching fused.probe_unique's jnp.searchsorted exactly."""
    size = a.shape[0]
    steps = max(1, int(math.ceil(math.log2(size + 1))) + 1)
    lo = jnp.zeros(v.shape, dtype=jnp.int64)
    hi = jnp.full(v.shape, size, dtype=jnp.int64)
    for _ in range(steps):
        cont = lo < hi
        mid = (lo + hi) // 2
        lt = a[jnp.clip(mid, 0, size - 1)] < v
        lo = jnp.where(cont & lt, mid + 1, lo)
        hi = jnp.where(cont & ~lt, mid, hi)
    return lo


class JoinStepPlan(NamedTuple):
    si: int                            # chain step index
    kind: str                          # "join" | "semi"
    table: str                         # "direct" | "unique"
    is_left: bool                      # LEFT join (null-extend misses)
    probe_keys: Tuple[str, ...]        # probe-side key column names
    out_name: str                      # semi marker output ("" for join)
    gcols: Tuple[Tuple[str, bool], ...]  # (build column, has_nulls)
    arr_count: int                     # flat operands this step consumes


class JoinPlan(NamedTuple):
    steps: Tuple[JoinStepPlan, ...]
    arrays: tuple                      # flat device operands, step order
    sig: tuple                         # hashable layout key (runner cache)
    nbytes: int                        # flattened operand bytes


def plan_join_layout(steps, aux, expands, declined, max_bytes=None):
    """Flatten the chain's join/semi build tables into a kernel operand
    layout.  `steps`/`aux`/`expands` use FusedChain.prep's layout
    (aux[0] = scan cache, aux[ji + 1] per join-ish step).  Returns a
    JoinPlan (empty when the chain has no join/semi steps) or None
    after metering one decline."""
    from ..fused import DirectTable, _join_build_cols
    jsteps = []
    arrays = []
    sig = []
    nbytes = 0
    ji = 0
    for si, step in enumerate(steps):
        kind = step[0]
        if kind not in ("join", "semi"):
            continue
        node = step[1]
        ent = aux[ji + 1]
        fanout = expands[ji]
        ji += 1
        if fanout != 1:
            # fanout-k expansion changes the chunk capacity mid-chain;
            # the kernel's fixed block geometry cannot follow it
            declined("JoinShape")
            return None
        is_left = False
        out_name = ""
        if kind == "semi":
            tbl, bhn = ent
            probe_keys = (node.source_join_variable.name,)
            gcols: Tuple[Tuple[str, bool], ...] = ()
            out_name = node.semi_join_output.name
        else:
            tbl = ent
            if node.filter is not None \
                    or node.join_type not in (P.INNER, P.LEFT):
                declined("JoinShape")
                return None
            is_left = node.join_type == P.LEFT
            probe_keys = tuple(l.name for l, _r in node.criteria)
            build_names = {v.name for v in node.right.output_variables}
            out_names = [v.name for v in node.outputs]
            gspec = []
            for n in _join_build_cols(node, out_names, build_names):
                c = tbl.columns[n]
                if c.dictionary is not None or c.lazy is not None:
                    declined("JoinShape")
                    return None
                gspec.append((n, c.nulls is not None))
            gcols = tuple(gspec)
        if isinstance(tbl, DirectTable):
            table_kind = "direct"
            step_arrays = [tbl.slots,
                           jnp.asarray(tbl.base, jnp.int64).reshape(1)]
        elif isinstance(tbl, ops.BuildTable):
            table_kind = "unique"
            step_arrays = [tbl.keyhash_sorted, tbl.perm]
        else:
            # deferred build slot (grouped-lifespan execution) or an
            # unknown table form
            declined("JoinShape")
            return None
        for n, has_nulls in gcols:
            c = tbl.columns[n]
            step_arrays.append(c.values)
            if has_nulls:
                step_arrays.append(c.nulls)
        if kind == "semi":
            step_arrays.append(jnp.asarray(bhn, bool).reshape(1))
        nbytes += sum(int(a.size) * a.dtype.itemsize for a in step_arrays)
        jsteps.append(JoinStepPlan(si, kind, table_kind, is_left,
                                   probe_keys, out_name, gcols,
                                   len(step_arrays)))
        sig.append((si, kind, table_kind, is_left, probe_keys, out_name,
                    gcols))
        arrays += step_arrays
    if jsteps and max_bytes is not None and nbytes > max_bytes:
        declined("JoinBuildSize")
        return None
    return JoinPlan(tuple(jsteps), tuple(arrays), tuple(sig), nbytes)


def reserve_build_operands(pool, nbytes: int) -> bool:
    """Charge the kernel's build operands to the owning operator's
    MemoryContext as NON-revocable (revocation-exempt) reserved bytes:
    the launched kernel holds a live device reference, so arbitration
    may revoke OTHER revocable holders to admit the reservation but
    must never spill the build itself mid-launch.  The caller frees the
    same byte count after the launch."""
    if pool is None or not nbytes:
        return True
    return pool.try_reserve(nbytes)


def _make_applier(sp: JoinStepPlan, arrs):
    """One chain-step replacement closure over the step's in-kernel
    operand arrays (scan_kernel.run_chain_steps `appliers`)."""
    if sp.table == "direct":
        slots, base = arrs[0], arrs[1]

        def probe(batch):
            # ops.direct_lookup over the VMEM-resident slot array
            col = batch.columns[sp.probe_keys[0]]
            v = col.values.astype(jnp.int64)
            size = slots.shape[0]
            k = v - base[0]
            inb = (k >= 0) & (k < size)
            slot = slots[jnp.clip(k, 0, size - 1).astype(jnp.int32)]
            hit = inb & (slot >= 0)
            if col.nulls is not None:
                hit = hit & ~col.nulls
            return hit, jnp.where(hit, slot, 0)
    else:
        khs, perm = arrs[0], arrs[1]

        def probe(batch):
            # fused.probe_unique with the fixed-trip bisect standing in
            # for jnp.searchsorted(side="left")
            cols = [batch.columns[k] for k in sp.probe_keys]
            kh = ops._orderable_hash(ops.hash_columns(cols))
            nb = perm.shape[0]
            lo = jnp.clip(_bisect_left(khs, kh).astype(jnp.int32),
                          0, nb - 1)
            hit = khs[lo] == kh
            for c in cols:
                if c.nulls is not None:
                    hit = hit & ~c.nulls
            return hit, jnp.where(hit, perm[lo], 0)

    if sp.kind == "semi":
        bhn = arrs[2]

        def semi_applier(batch):
            hit, _ = probe(batch)
            # three-valued marker: NULL probe key, or miss against a
            # build side that contained NULL (FusedChain.make semantics)
            nulls = ~hit & bhn[0]
            pn = batch.columns[sp.probe_keys[0]].nulls
            if pn is not None:
                nulls = nulls | pn
            return batch.with_columns({sp.out_name: Column(hit, nulls)})
        return semi_applier

    gathered = []
    i = 2
    for name, has_nulls in sp.gcols:
        gv = arrs[i]
        i += 1
        gn = None
        if has_nulls:
            gn = arrs[i]
            i += 1
        gathered.append((name, gv, gn))

    def join_applier(batch):
        hit, bidx = probe(batch)
        cols = dict(batch.columns)
        for name, gv, gn in gathered:
            vals = gv[bidx]
            nulls = gn[bidx] if gn is not None else None
            if sp.is_left:
                # null-extend build columns on misses; probe rows stay
                miss = ~hit
                nulls = (nulls | miss) if nulls is not None else miss
            cols[name] = Column(vals, nulls)
        if sp.is_left:
            return Batch(cols, batch.mask)
        return Batch(cols, batch.mask & hit)
    return join_applier


def join_appliers(plan: JoinPlan, arrs):
    """{step index: applier} closures over the flat in-kernel operand
    arrays (the kernel body reads each join ref whole and passes the
    list here, in plan.arrays order)."""
    appliers = {}
    off = 0
    for sp in plan.steps:
        appliers[sp.si] = _make_applier(sp, arrs[off:off + sp.arr_count])
        off += sp.arr_count
    return appliers
