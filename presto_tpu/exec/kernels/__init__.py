"""Hand-written Pallas TPU kernels for the scan hot path.

The fused compiler (exec/fused.py, exec/pipeline.py) stays the planner
and fallback; this package holds the kernels it can dispatch to when a
chain is eligible, selected by the `scan.kernel = xla | pallas | auto`
ExecutionConfig knob.  CPU runs execute the same kernels through Pallas
interpret mode (kernels/shim.py, the only sanctioned `interpret=True`
site) so tier-1 tests cover the kernel path.

kernels/join.py lowers the fused chain's probe-side joins into the scan
kernel body (build tables ride as whole-block operands); kernels/
window.py evaluates running window aggregates with the same pairing
prefix scan the compaction step uses.
"""
from .scan_kernel import (DMA_MODES, KERNEL_DECLINE_REASONS,
                          KERNEL_HASH_MAX_SLOTS, KERNEL_SPAN_MAX_GROUPS,
                          SUBTILE_ROWS, build_direct_runner,
                          try_direct_scan_kernel)
from .grouped import build_hash_runner, try_grouped_scan_kernel
from .join import (KERNEL_JOIN_MAX_BUILD_BYTES, plan_join_layout,
                   reserve_build_operands)
from .window import KERNEL_WINDOW_MAX_BYTES, try_window_kernel
from .shim import kernel_interpret

__all__ = [
    "DMA_MODES",
    "KERNEL_DECLINE_REASONS",
    "KERNEL_HASH_MAX_SLOTS",
    "KERNEL_JOIN_MAX_BUILD_BYTES",
    "KERNEL_SPAN_MAX_GROUPS",
    "KERNEL_WINDOW_MAX_BYTES",
    "SUBTILE_ROWS",
    "build_direct_runner",
    "build_hash_runner",
    "plan_join_layout",
    "reserve_build_operands",
    "try_direct_scan_kernel",
    "try_grouped_scan_kernel",
    "try_window_kernel",
    "kernel_interpret",
]
