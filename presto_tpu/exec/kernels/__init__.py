"""Hand-written Pallas TPU kernels for the scan hot path.

The fused compiler (exec/fused.py, exec/pipeline.py) stays the planner
and fallback; this package holds the kernels it can dispatch to when a
chain is eligible, selected by the `scan.kernel = xla | pallas | auto`
ExecutionConfig knob.  CPU runs execute the same kernels through Pallas
interpret mode (kernels/shim.py, the only sanctioned `interpret=True`
site) so tier-1 tests cover the kernel path.
"""
from .scan_kernel import (DMA_MODES, KERNEL_DECLINE_REASONS,
                          KERNEL_HASH_MAX_SLOTS, KERNEL_SPAN_MAX_GROUPS,
                          SUBTILE_ROWS, build_direct_runner,
                          try_direct_scan_kernel)
from .grouped import build_hash_runner, try_grouped_scan_kernel
from .shim import kernel_interpret

__all__ = [
    "DMA_MODES",
    "KERNEL_DECLINE_REASONS",
    "KERNEL_HASH_MAX_SLOTS",
    "KERNEL_SPAN_MAX_GROUPS",
    "SUBTILE_ROWS",
    "build_direct_runner",
    "build_hash_runner",
    "try_direct_scan_kernel",
    "try_grouped_scan_kernel",
    "kernel_interpret",
]
