"""Backend shim for Pallas kernels: the ONE place `interpret=True` may
appear.

Tier-1 tests run on CPU, where Mosaic cannot lower; Pallas interpret
mode executes the SAME kernel python (block specs, scalar prefetch,
grid accumulation) with jax-level semantics, so the tests exercise the
real kernel path bit-for-bit for integer outputs.  On TPU the kernel
compiles natively.  A stray `interpret=True` anywhere else would make a
TPU build silently run the interpreter at Python speed — analysis/lint
KERNEL001 forbids the literal outside this file.
"""
from __future__ import annotations

import jax


def kernel_interpret() -> bool:
    """True when Pallas must run in interpret mode (non-TPU backends)."""
    return jax.default_backend() != "tpu"


def pallas_call(kernel, **kwargs):
    """`pl.pallas_call` with the backend-appropriate execution mode."""
    from jax.experimental import pallas as pl
    if kernel_interpret():
        kwargs["interpret"] = True  # lint: allow-pallas-interpret
    return pl.pallas_call(kernel, **kwargs)
