"""Pallas fused scan kernel: decode -> filter -> prefix-sum compact ->
partial aggregation in one VMEM-resident grid pass.

The XLA fused chain (exec/fused.py) already collapses scan -> filter ->
project -> partial-agg into one program, but its aggregation update
reads the FULL chunk tile: a selective predicate (TPC-H Q6 keeps ~2% of
rows) still pays the G x cap one-hot grid over every padded row.  This
kernel is the hand-written hot path the ROADMAP's HBM-gap item calls
for:

  grid      one step per SURVIVING block-aligned chunk.  The kernel
            re-grids the scan's split ranges onto its OWN power-of-two
            block size (block_rows_for: the pow2 ceiling of the chain's
            chunk capacity — aggregation is order-insensitive, so any
            partition of the same row set is legal); each grid entry
            carries its block index plus a [lo, hi) live row range as
            scalar-prefetch operands, which also masks short/misaligned
            chunk tails (the launcher zero-pads encoded arrays up to the
            grid, so tail shape never declines the kernel).  Zone-map
            pruning runs over THIS grid, so pruned blocks never issue
            DMAs -- they are simply not in the grid.
  decode    ResidentColumn blocks stream out of HBM in ENCODED form.
            `dma = single` uses Pallas block specs (the implicit
            double-buffering Pallas applies across grid steps);
            `dma = double` stages the per-row slabs MANUALLY: block k+1's
            encoded slabs start their pltpu.make_async_copy into the
            alternate VMEM buffer while block k decodes/aggregates
            (_stage_slabs).  Dict gather / RLE binary search then runs
            in vector registers -- late materialization with the same
            semantics as ResidentColumn.slice_decode.
  filter    the chain's own predicate/project expressions, lowered by
            the SAME exec/lowering.Lowering the XLA chain uses -- the
            kernel cannot drift from the engine semantics.  Bound
            parameters (the serving tier parameterizes plan literals)
            ride as traced scalar inputs, so re-executions with
            different constants reuse the compiled kernel.
  compact   a work-efficient Blelloch exclusive prefix sum over the
            selection mask drives an in-VMEM scatter compaction (no XLA
            gather round-trip), after which the aggregation update only
            touches ceil(live/SUBTILE) subtiles instead of the full tile
  agg       operators.agg_direct_update (one-hot grid, G<=64) or
            operators.agg_span_update (packed scatter, grouped span
            mode -- kernels/grouped.py) over compacted subtiles; the
            packed int64/float64 accumulators live in the kernel's
            output block across grid steps and feed the operators
            finalize path unchanged.  Hashed grouped shapes build their
            own kernel in kernels/grouped.py from these helpers.

Device-side row counters (scan live rows + live rows after every chain
step) accumulate in an output block exactly like the XLA chain's
with_counts path, so EXPLAIN ANALYZE / QueryInfo operator stats stay
accurate on the kernel path.

Parity contract (tests/test_scan_kernel.py): integer accumulators
(sums over int64/decimal/date/bool, count, min, max) and the row
counters are BIT-FOR-BIT identical to the XLA chain -- integer adds
and min/max are associative, so compaction and re-gridding cannot
change them.  float64 sum/avg may differ in the last ulp (different
reduction tree pairings); TPC-H decimals are unscaled int64 on device,
so the Q1/Q6 money aggregates are exact.
"""
from __future__ import annotations

import math
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import operators as ops
from ..batch import Batch, Column
from . import shim

# Eligibility refusals, surfaced as kernelDeclined{reason} RuntimeStats
# counters (exec/pipeline.py _kernel_declined) -- the kernel twin of the
# fusionDeclined{...} family.  "Disabled", "AggFunctionShape" and
# "Backend"(auto) are recorded by the pipeline itself; the rest are
# produced here / in kernels/grouped.py.  ("ChunkAlignment" was held at 0
# for one release after tail padding landed and is now retired — the
# launcher pads/lane-masks every tail, so the decline cannot occur.)
KERNEL_DECLINE_REASONS = (
    "Disabled",              # scan.kernel = xla
    "AggFunctionShape",      # non-BASIC aggregate functions (moment/corr/
    #                          percentile/HLL state has no kernel stacks)
    "AggGroupCardinality",   # group count beyond the VMEM accumulator
    #                          gates (span > KERNEL_SPAN_MAX_GROUPS and
    #                          hash estimate/collision > KERNEL_HASH_MAX_SLOTS)
    "Backend",               # platform is neither tpu nor cpu-interpret
    "PlanShape",             # chain has uid steps (position-keyed unique
    #                          ids need the XLA chain's expansion layout)
    "ColumnsNotResident",    # a scanned column is not HBM-resident encoded
    "JoinShape",             # fanout-k expansion join, residual ON filter,
    #                          or a non-INNER/LEFT fused join form
    #                          (kernels/join.py plan_join_layout)
    "JoinBuildSize",         # build-table operand bytes over
    #                          KERNEL_JOIN_MAX_BUILD_BYTES, or the
    #                          MemoryContext reservation failed
    "WindowFunctionShape",   # window function / frame / float accumulation
    #                          outside the prefix-sum kernel's repertoire
    #                          (kernels/window.py)
    "WindowKeyShape",        # late-materialized (lazy) partition/order/arg
    #                          column: peer detection needs decoded values
    "WindowInputSize",       # padded sort run over KERNEL_WINDOW_MAX_BYTES
    #                          (whole input must sit in VMEM at once)
)

# compacted rows are aggregated in subtiles of this many rows: the
# G x SUBTILE one-hot grid stays small while a selective filter skips
# most subtiles entirely (n_sub = ceil(live/SUBTILE) loop trips)
SUBTILE_ROWS = 2048
# grouped modes scatter instead of building the one-hot grid, so their
# subtiles can be wider (fewer fori_loop trips over the probe rounds)
GROUPED_SUBTILE_ROWS = 8192

# VMEM accumulator gates for the grouped modes (kernels/grouped.py).
# span: G * ~(1 + n_specs * 2) int64/float64 rows must sit in VMEM next
# to the decoded block; 32K groups * ~10 accumulator rows * 8B = 2.5MB.
# hash: the open-addressing table carries keyhash/occupied/key values/
# per-spec accumulators per slot; 64K slots * ~15 arrays * 8B = 7.5MB.
# Both leave headroom under a 16MB VMEM core budget at 64K-row blocks;
# truly huge G declines with AggGroupCardinality and runs the XLA chain.
KERNEL_SPAN_MAX_GROUPS = 1 << 15
KERNEL_HASH_MAX_SLOTS = 1 << 16

# scan.kernel-dma knob values (ExecutionConfig.scan_kernel_dma)
DMA_MODES = ("single", "double")


class KernelMetrics:
    """Process-lifetime roll-up of the per-query kernel counters, so the
    telemetry scraper (telemetry/otlp.py scrape_metric_points) and
    /v1/metrics can export kernel engagement without a live query: every
    kernelDeclined{reason} tick and meter_kernel_run call lands here too."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self.declined: Dict[str, int] = {}
        self.scan_programs = 0
        self.window_programs = 0
        self.dma_staged_blocks = 0
        self.dma_prefetched_blocks = 0

    def record_declined(self, reason: str) -> None:
        with self._lock:
            self.declined[reason] = self.declined.get(reason, 0) + 1

    def record_run(self, n_staged_copies: int, n_prefetched: int) -> None:
        with self._lock:
            self.scan_programs += 1
            self.dma_staged_blocks += n_staged_copies
            self.dma_prefetched_blocks += n_prefetched

    def record_window_run(self) -> None:
        with self._lock:
            self.window_programs += 1

    def snapshot(self) -> dict:
        with self._lock:
            staged = self.dma_staged_blocks
            return {
                "declined": dict(self.declined),
                "scan_programs": self.scan_programs,
                "window_programs": self.window_programs,
                "dma_staged_blocks": staged,
                "dma_prefetched_blocks": self.dma_prefetched_blocks,
                "dma_overlap_fraction": (
                    self.dma_prefetched_blocks / staged if staged else 0.0),
            }


KERNEL_METRICS = KernelMetrics()


def _blelloch_exclusive(x):
    """Work-efficient (Blelloch) exclusive prefix sum of a power-of-two
    length vector, expressed with reshapes so both the up-sweep and the
    down-sweep are dense vector ops (no scatter): pairing adjacent
    elements halves the vector per level, then each level's prefix
    splits back into (left, left + pair_first)."""
    cur = x
    levels = []
    while cur.shape[0] > 1:
        levels.append(cur)
        pairs = cur.reshape(-1, 2)
        cur = pairs[:, 0] + pairs[:, 1]
    pref = jnp.zeros_like(cur)
    for lvl in reversed(levels):
        pairs = lvl.reshape(-1, 2)
        left = pref
        right = pref + pairs[:, 0]
        pref = jnp.stack([left, right], axis=1).reshape(-1)
    return pref


def _bisect_right(a, v):
    """searchsorted(a, v, side="right") as a fixed-trip vectorized
    binary search -- jnp.searchsorted does not lower inside Pallas TPU
    kernels, and the loop is exact integer arithmetic so interpret and
    compiled runs agree with the XLA chain's searchsorted decode."""
    size = a.shape[0]
    steps = max(1, int(math.ceil(math.log2(size + 1))) + 1)
    lo = jnp.zeros(v.shape, dtype=jnp.int64)
    hi = jnp.full(v.shape, size, dtype=jnp.int64)
    for _ in range(steps):
        cont = lo < hi
        mid = (lo + hi) // 2
        le = a[jnp.clip(mid, 0, size - 1)] <= v
        lo = jnp.where(cont & le, mid + 1, lo)
        hi = jnp.where(cont & ~le, mid, hi)
    return lo


class _Runner(NamedTuple):
    fn: Callable                 # jitted launcher
    init_i: object               # (Ni, G) int64 accumulator init rows
    init_f: object               # (max(Nf,1), G) float64 init rows
    int_names: Tuple[str, ...]   # acc_i row -> agg state key
    flt_names: Tuple[str, ...]   # acc_f row -> agg state key


def _chunk_block(i, bidx, lo, hi):
    return (bidx[i],)


def _whole_1d(i, bidx, lo, hi):
    return (0,)


def _whole_2d(i, bidx, lo, hi):
    return (0, 0)


def _merged_ranges(splits) -> List[Tuple[int, int]]:
    """The scan's owned row ranges, sorted and coalesced."""
    out: List[List[int]] = []
    for s, e in sorted((int(sp.start), int(sp.end)) for sp in splits):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _block_pruned(zone_maps, pushdown, params, pos: int,
                  count: int) -> bool:
    """storage/pushdown.prune_chunks' conservative unsatisfiability
    test for ONE aligned block (the kernel grid differs from the
    chain's split-relative chunk grid, so pruning re-runs here; the
    chain already metered ITS grid in chunks_for)."""
    from ...storage.pushdown import (entry_unsatisfiable,
                                     resolve_entry_value)
    for e in pushdown:
        zm = zone_maps.get(e["column"])
        if zm is None:
            continue
        value = resolve_entry_value(e["value"], params)
        if value is None:
            continue
        bounds = zm.chunk_bounds(pos, count)
        if bounds is None:
            continue
        if entry_unsatisfiable(e["op"], value, *bounds):
            return True
    return False


def block_rows_for(cap: int) -> int:
    """The kernel's block size for a chain with chunk capacity `cap`:
    the power-of-two ceiling.  The Blelloch scan pairs elements level by
    level, so tiles must be pow2; re-gridding is legal because
    aggregation is order-insensitive, and rows between a split end and
    the block end are lane-masked via the [lo, hi) scalar-prefetch range
    (the launcher zero-pads encoded arrays to the grid, so a short last
    chunk never declines the kernel)."""
    return 1 << max(0, int(cap - 1).bit_length())


def aligned_grid(meta: dict, block_rows: int,
                 params) -> List[Tuple[int, int, int]]:
    """(block index, lo, hi) grid entries tiling the scan's split
    ranges with block_rows-aligned blocks; [lo, hi) is the
    block-relative live row range.  A block straddling two disjoint
    owned ranges yields two entries (grid steps accumulate, so
    revisiting a block is sound).  Zone-map-pruned entries are dropped
    HERE -- they never reach the grid, so their HBM blocks are never
    DMA'd."""
    zone_maps = meta.get("zone_maps") or {}
    pushdown = meta.get("pushdown") or []
    entries: List[Tuple[int, int, int]] = []
    for s, e in _merged_ranges(meta["splits"]):
        for b in range(s // block_rows, (e - 1) // block_rows + 1):
            lo = max(s, b * block_rows) - b * block_rows
            hi = min(e, (b + 1) * block_rows) - b * block_rows
            if zone_maps and pushdown and _block_pruned(
                    zone_maps, pushdown, params,
                    b * block_rows + lo, hi - lo):
                continue
            entries.append((b, lo, hi))
    return entries


# ---------------------------------------------------------------------------
# shared kernel-body helpers (direct + grouped runners)
# ---------------------------------------------------------------------------

def staged_indices(names, kinds) -> Tuple[int, ...]:
    """Flat input indices of the PER-ROW encoded arrays (plain data,
    dict codes) -- the arrays whose blocks stream per grid step and are
    therefore candidates for manual double-buffered DMA staging.  Whole
    arrays (dict values, RLE runs) are VMEM-resident block specs in
    both modes."""
    idx, r = [], 0
    for name in names:
        kind = kinds[name]
        if kind == "plain":
            idx.append(r)
            r += 1
        elif kind == "dict":
            idx.append(r)
            r += 2
        else:                                        # rle: whole arrays
            r += 2
    return tuple(idx)


def _stage_slabs(col_refs, staged, scratch, sem, bidx_ref, block_rows):
    """Manual double-buffered DMA staging of the current grid block's
    per-row slabs: start block k+1's HBM->VMEM copies into the alternate
    buffer BEFORE waiting on block k's own, so the next block's copy
    overlaps this block's decode/aggregate compute (the pallas guide's
    double-buffering pattern, driven by the scalar-prefetch block index
    array).  Returns {flat input index: slab} for the current step."""
    i = pl.program_id(0)
    n = pl.num_programs(0)

    def copy(slot, step, j):
        ref = col_refs[staged[j]]
        return pltpu.make_async_copy(
            ref.at[pl.ds(bidx_ref[step] * block_rows, block_rows)],
            scratch[j].at[slot], sem.at[slot, j])

    @pl.when(i == 0)
    def _warm_up():
        for j in range(len(staged)):
            copy(0, 0, j).start()

    @pl.when(i + 1 < n)
    def _prefetch_next():
        for j in range(len(staged)):
            copy((i + 1) % 2, i + 1, j).start()

    slot = i % 2
    slabs = {}
    for j in range(len(staged)):
        copy(slot, i, j).wait()
        slabs[staged[j]] = scratch[j][slot]
    return slabs


def decode_columns(names, kinds, dicts, col_refs, slabs, pos, idx0,
                   live) -> Dict[str, Column]:
    """ResidentColumn.slice_decode semantics over the block's VMEM
    slabs: plain read, dict gather, RLE binary search, then the scan's
    dead-row zeroing.  `slabs` overrides col_refs for manually staged
    per-row arrays (dma = double); empty in single mode."""
    def read(r):
        return slabs[r] if r in slabs else col_refs[r][...]

    cols: Dict[str, Column] = {}
    r = 0
    for name in names:
        kind = kinds[name]
        if kind == "plain":
            v = read(r)
            r += 1
        elif kind == "dict":
            codes = read(r)
            values = col_refs[r + 1][...]
            r += 2
            v = values[codes.astype(jnp.int32)]
        else:                                    # rle
            run_values = col_refs[r][...]
            run_starts = col_refs[r + 1][...]
            r += 2
            ri = _bisect_right(run_starts, pos + idx0) - 1
            ri = jnp.clip(ri, 0, run_values.shape[0] - 1)
            v = run_values[ri]
        v = jnp.where(live, v, jnp.zeros((), v.dtype))
        cols[name] = Column(v, None, dicts.get(name))
    return cols


def run_chain_steps(batch: Batch, live, steps, lowering, params_k,
                    n_params, appliers=None):
    """The chain's own filter/project/rename steps, lowered by the
    engine's Lowering (shared with the XLA chain), with the same
    per-step live-row counters chain.make(with_counts=True) emits.
    The bound-parameter vector rides along for step expressions exactly
    as in FusedChain.make's _pb (aggregation input expressions see a
    param-less batch on both paths).  `appliers` maps a step index to an
    in-kernel replacement closure (the join/semi probe appliers from
    kernels/join.py, which read the VMEM-resident build operands
    directly) -- every other step kind still lowers here."""
    def _pb(b):
        return b.with_params(params_k) if n_params else b

    counts = [jnp.sum(live)]
    for si, step in enumerate(steps):
        kind = step[0]
        if appliers is not None and si in appliers:
            batch = appliers[si](batch)
        elif kind == "filter":
            batch = ops.apply_filter(
                batch, lowering.eval(step[1], _pb(batch)))
        elif kind == "project":
            pb = _pb(batch)
            batch = Batch({v2.name: lowering.eval(e, pb)
                           for v2, e in step[1]}, batch.mask)
        else:                                    # rename
            batch = Batch({o: batch.columns[src]
                           for o, src in step[1]}, batch.mask)
        counts.append(jnp.sum(batch.mask))
    return batch, counts


def compact_columns(mask, cap, named):
    """Prefix-sum compaction: exclusive Blelloch scan of the mask gives
    each live row its packed slot; dead rows scatter to index cap and
    drop.  `named` is a list of (key, 1-D array) pairs; returns (live
    total, {key: compacted array}).  Downstream aggregation then loops
    over live subtiles only."""
    pref = _blelloch_exclusive(mask.astype(jnp.int32))
    total = pref[cap - 1] + mask[cap - 1].astype(jnp.int32)
    dest = jnp.where(mask, pref, cap)
    out = {k: jnp.zeros(cap, dtype=a.dtype).at[dest].set(a, mode="drop")
           for k, a in named}
    return total, out


def agg_compaction_entries(specs, agg_cols):
    """(key, array) compaction entries for the aggregate input columns
    ("v:" values / "n:" nulls per spec output; count_star has none)."""
    named = []
    for spec in specs:
        col = agg_cols.get(spec.output)
        if col is None:                          # count_star
            continue
        named.append(("v:" + spec.output, col.values))
        if col.nulls is not None:
            named.append(("n:" + spec.output, col.nulls))
    return named


def subtile_agg_inputs(compacted, specs, off, ts):
    """Slice one subtile's aggregate inputs out of the compacted
    columns (dynamic_slice keeps the loop body shape-static)."""
    sa: Dict[str, Optional[Column]] = {}
    for spec in specs:
        cv = compacted.get("v:" + spec.output)
        if cv is None:
            sa[spec.output] = None
            continue
        sv = jax.lax.dynamic_slice(cv, (off,), (ts,))
        cn = compacted.get("n:" + spec.output)
        sn = (jax.lax.dynamic_slice(cn, (off,), (ts,))
              if cn is not None else None)
        sa[spec.output] = Column(sv, sn)
    return sa


def encoded_in_specs(names, kinds, flat, block_rows, staged):
    """BlockSpecs for the flat encoded-array inputs, in staged_indices
    order.  Per-row arrays stream per grid block (single mode) or sit in
    ANY memory space awaiting the kernel's manual DMA (double mode);
    whole arrays are always whole VMEM blocks."""
    row_spec = (pl.BlockSpec(memory_space=pltpu.ANY) if staged
                else pl.BlockSpec((block_rows,), _chunk_block))
    in_specs: List = []
    r = 0
    for name in names:
        kind = kinds[name]
        if kind == "plain":
            in_specs.append(row_spec)
            r += 1
        elif kind == "dict":
            in_specs += [row_spec,
                         pl.BlockSpec(flat[r + 1].shape, _whole_1d)]
            r += 2
        else:                                    # rle
            in_specs += [pl.BlockSpec(flat[r].shape, _whole_1d),
                         pl.BlockSpec(flat[r + 1].shape, _whole_1d)]
            r += 2
    return in_specs


def dma_scratch_shapes(staged, flat, block_rows):
    """Double-buffer VMEM scratch (2 slots per staged array) plus one
    (2, n_staged) DMA semaphore array for _stage_slabs."""
    shapes = [pltpu.VMEM((2, block_rows), flat[r].dtype) for r in staged]
    shapes.append(pltpu.SemaphoreType.DMA((2, len(staged))))
    return shapes


def chain_eligible(chain, aux, declined, allow_joins: bool = False):
    """Gates shared by every kernel mode: backend, chain step shapes,
    HBM residency.  Returns (cached, colmap) or None after metering one
    decline.  `allow_joins` admits join/semi probe steps (the caller
    must then lower them via kernels/join.py plan_join_layout, which
    applies its own Join* gates); uid steps always decline -- their
    position-keyed ids need the XLA chain's expansion layout."""
    allowed = (("filter", "project", "rename", "join", "semi")
               if allow_joins else ("filter", "project", "rename"))
    if jax.default_backend() not in ("cpu", "tpu"):
        declined("Backend")
        return None
    if any(s[0] not in allowed for s in chain.steps):
        declined("PlanShape")
        return None
    cached = aux[0] or {}
    colmap = chain.scan_meta.get("colmap") or {}
    if not colmap or any(colmap[n] not in cached for n in colmap):
        declined("ColumnsNotResident")
        return None
    return cached, colmap


def gather_encoded_arrays(cached, colmap, names, need, cache):
    """The flat encoded-array inputs in staged_indices order, with
    per-row arrays zero-padded up to `need` rows (the grid's last block
    end) when the store's build-time capacity falls short -- padded
    lanes are dead by the [lo, hi) mask, so a short tail never declines.
    Pads are cached per (column, need) and invalidated when the store
    regenerates the underlying array (LRU eviction)."""
    flat: List = []
    for name in names:
        rc = cached[colmap[name]]
        arrs = tuple(rc.arrays)
        if rc.kind in ("plain", "dict") and arrs[0].shape[0] < need:
            ck = ("kernel_pad", colmap[name], need)
            hit = cache.get(ck)
            if hit is None or hit[0] is not arrs[0]:
                hit = (arrs[0],
                       jnp.pad(arrs[0], (0, need - arrs[0].shape[0])))
                cache[ck] = hit
            arrs = (hit[1],) + arrs[1:]
        flat += list(arrs)
    return tuple(flat)


def meter_kernel_run(runtime_stats, n_blocks, n_staged, dma) -> None:
    """One kernelScanPrograms tick per launched kernel; in double-DMA
    mode also the structural overlap fraction: every staged slab copy
    after the first block's was issued while the PREVIOUS block
    computed, so prefetched/staged = (n_blocks-1)/n_blocks of the DMA
    traffic overlapped compute.  (A wall-clock overlap measure needs the
    real-TPU re-run the ROADMAP tracks; the structural fraction is
    deterministic, so tests and dashboards can pin it.)"""
    staged_copies = prefetched = 0
    if dma == "double" and n_staged and n_blocks:
        staged_copies = n_blocks * n_staged
        prefetched = (n_blocks - 1) * n_staged
    KERNEL_METRICS.record_run(staged_copies, prefetched)
    if runtime_stats is None:
        return
    runtime_stats.add("kernelScanPrograms", 1)
    if staged_copies:
        runtime_stats.add("kernelDmaStagedBlocks", staged_copies)
        runtime_stats.add("kernelDmaPrefetchedBlocks", prefetched)
        runtime_stats.add("kernelDmaOverlapFraction",
                          prefetched / staged_copies)


# ---------------------------------------------------------------------------
# direct / span runner (stacked int64+float64 accumulator outputs)
# ---------------------------------------------------------------------------

def build_direct_runner(chain, kinds: Dict[str, str], n_params: int, *,
                        specs, key_names, strides, G, agg_exprs,
                        lowering, dma: str = "single",
                        update_fn=None, subtile: int = None,
                        join_plan=None) -> _Runner:
    """Compile the chain's static shape (column encodings, steps, agg
    specs) into a jitted Pallas launcher.  `kinds` maps each scan
    output name to its ResidentColumn encoding; `n_params` is the
    length of the chain's bound-parameter vector.  The launcher
    re-traces when the surviving-grid length changes (param pruning);
    everything else is baked in, mirroring the fused_cache programs of
    the XLA path.

    agg_span_init IS agg_direct_init (same state template and dtype
    split), so the SAME stacked-accumulator kernel serves both the
    direct mode (update_fn = ops.agg_direct_update, one-hot grid,
    G<=64) and the grouped span mode (update_fn = ops.agg_span_update,
    packed scatter, G up to KERNEL_SPAN_MAX_GROUPS).

    `join_plan` (kernels/join.py JoinPlan) lowers the chain's fanout-1
    join/semi probe steps into the kernel body: its flat build operands
    ride as whole-1D VMEM inputs between the encoded columns and the
    bound parameters, and run_chain_steps swaps the matching steps for
    the plan's probe appliers."""
    from .join import join_appliers
    update_fn = update_fn or ops.agg_direct_update
    ts_rows = subtile or SUBTILE_ROWS
    n_join = len(join_plan.arrays) if join_plan is not None else 0
    meta = chain.scan_meta
    br = block_rows_for(chain.leaf_cap(()))
    steps = chain.steps
    n_steps = len(steps)
    dicts = meta["dicts"]
    colmap = meta["colmap"]
    names = tuple(colmap)
    staged = staged_indices(names, kinds) if dma == "double" else ()
    n_staged = len(staged)

    template = ops.agg_direct_init(G, specs)
    int_names = tuple(k for k, v in template.items()
                      if v.dtype == jnp.int64)
    flt_names = tuple(k for k, v in template.items()
                      if v.dtype == jnp.float64)
    assert len(int_names) + len(flt_names) == len(template)
    n_i = len(int_names)
    n_f = len(flt_names)
    init_i = jnp.stack([template[k] for k in int_names])
    init_f = (jnp.stack([template[k] for k in flt_names]) if n_f
              else jnp.zeros((1, G), dtype=jnp.float64))

    def kernel(bidx_ref, lo_ref, hi_ref, *refs):
        if n_staged:
            scratch = refs[-(n_staged + 1):-1]
            sem = refs[-1]
            refs = refs[:-(n_staged + 1)]
        col_refs = refs[:len(refs) - 5 - n_params - n_join]
        join_refs = refs[len(col_refs):len(col_refs) + n_join]
        param_refs = refs[len(col_refs) + n_join:
                          len(col_refs) + n_join + n_params]
        init_i_ref, init_f_ref = refs[-5:-3]
        acc_i_ref, acc_f_ref, counts_ref = refs[-3:]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init_outputs():
            acc_i_ref[...] = init_i_ref[...]
            acc_f_ref[...] = init_f_ref[...]
            counts_ref[...] = jnp.zeros((1, 1 + n_steps), dtype=jnp.int64)

        slabs = (_stage_slabs(col_refs, staged, scratch, sem, bidx_ref,
                              br) if n_staged else {})
        pos = bidx_ref[i].astype(jnp.int64) * br
        idx0 = jnp.arange(br, dtype=jnp.int64)
        live = (idx0 >= lo_ref[i].astype(jnp.int64)) \
            & (idx0 < hi_ref[i].astype(jnp.int64))

        cols = decode_columns(names, kinds, dicts, col_refs, slabs,
                              pos, idx0, live)
        params_k = tuple(p[...][0] for p in param_refs)
        appliers = (join_appliers(join_plan,
                                  [r[...] for r in join_refs])
                    if n_join else None)
        batch, counts = run_chain_steps(Batch(cols, live), live, steps,
                                        lowering, params_k, n_params,
                                        appliers)

        codes = None
        for k, stride in zip(key_names, strides):
            c = batch.columns[k].values.astype(jnp.int64)
            codes = c * stride if codes is None else codes + c * stride
        if codes is None:
            codes = jnp.zeros(br, dtype=jnp.int64)
        agg_cols = agg_exprs(batch)
        total, compacted = compact_columns(
            batch.mask, br,
            [("codes", codes)] + agg_compaction_entries(specs, agg_cols))

        ts = min(br, ts_rows)
        n_sub = (total + ts - 1) // ts
        acc_i = acc_i_ref[...]
        acc_f = acc_f_ref[...]
        state = {k: acc_i[j] for j, k in enumerate(int_names)}
        state.update({k: acc_f[j] for j, k in enumerate(flt_names)})
        sub_idx = jnp.arange(ts, dtype=jnp.int32)

        def sub(j, st):
            off = j * ts
            m = (off + sub_idx) < total
            sc = jax.lax.dynamic_slice(compacted["codes"], (off,), (ts,))
            sa = subtile_agg_inputs(compacted, specs, off, ts)
            return update_fn(st, Batch({}, m), sc, sa, specs, G)
        state = jax.lax.fori_loop(0, n_sub, sub, state)
        acc_i_ref[...] = jnp.stack([state[k] for k in int_names])
        if n_f:
            acc_f_ref[...] = jnp.stack([state[k] for k in flt_names])
        counts_ref[...] = counts_ref[...] + jnp.stack(counts).astype(
            jnp.int64)[None, :]

    @jax.jit
    def run(bidx, lo, hi, arrays, jarrays, params, init_i_arg,
            init_f_arg):
        flat = list(arrays)
        in_specs = encoded_in_specs(names, kinds, flat, br, staged)
        for a in jarrays:
            flat.append(a)
            in_specs.append(pl.BlockSpec(a.shape, _whole_1d))
        for p in params:
            flat.append(jnp.asarray(p).reshape(1))
            in_specs.append(pl.BlockSpec((1,), _whole_1d))
        flat += [init_i_arg, init_f_arg]
        in_specs += [pl.BlockSpec(init_i_arg.shape, _whole_2d),
                     pl.BlockSpec(init_f_arg.shape, _whole_2d)]
        out_shape = [
            jax.ShapeDtypeStruct((n_i, G), jnp.int64),
            jax.ShapeDtypeStruct((max(n_f, 1), G), jnp.float64),
            jax.ShapeDtypeStruct((1, 1 + n_steps), jnp.int64),
        ]
        out_specs = [
            pl.BlockSpec((n_i, G), _whole_2d),
            pl.BlockSpec((max(n_f, 1), G), _whole_2d),
            pl.BlockSpec((1, 1 + n_steps), _whole_2d),
        ]
        scratch_shapes = (dma_scratch_shapes(staged, flat, br)
                          if n_staged else [])
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bidx.shape[0],),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=tuple(scratch_shapes),
        )
        return shim.pallas_call(kernel, grid_spec=grid_spec,
                                out_shape=out_shape)(bidx, lo, hi, *flat)

    return _Runner(run, init_i, init_f, int_names, flt_names)


def try_direct_scan_kernel(chain, aux, *, specs, key_names, strides, G,
                           agg_exprs, lowering, cache, declined,
                           runtime_stats=None, dma: str = "single",
                           expands=(), pool=None):
    """Run the fused scan chain through the Pallas kernel when eligible.

    Returns (agg_direct state dict, int64[1 + n_steps] row counters,
    grid length) on success -- the caller feeds them to
    agg_direct_finalize and the operator-stats spine exactly like the
    XLA direct path -- or None after recording one
    kernelDeclined{reason} counter.

    Chains with fanout-1 join/semi steps lower their probes in-kernel
    (kernels/join.py); `expands` is prep()'s per-join fanout tuple and
    `pool` the owning operator's MemoryContext, charged the build
    operand bytes non-revocably for the launch's duration."""
    from .join import (KERNEL_JOIN_MAX_BUILD_BYTES, plan_join_layout,
                       reserve_build_operands)
    elig = chain_eligible(chain, aux, declined, allow_joins=True)
    if elig is None:
        return None
    cached, colmap = elig
    jplan = plan_join_layout(chain.steps, aux, expands, declined,
                             max_bytes=KERNEL_JOIN_MAX_BUILD_BYTES)
    if jplan is None:
        return None
    br = block_rows_for(chain.leaf_cap(()))
    params_fp = chain.compiler.ctx.params_fingerprint
    grid = aligned_grid(chain.scan_meta, br, params_fp)
    if not grid:
        # everything pruned: the XLA chain keeps one chunk for its
        # compiled fori_loop, but the kernel can simply return its init
        # state (the residual filter would kill every row anyway)
        template = ops.agg_direct_init(G, specs)
        return (template,
                jnp.zeros(1 + len(chain.steps), dtype=jnp.int64), 0)
    names = tuple(colmap)
    max_block = max(b for b, _lo, _hi in grid)
    flat_arrays = gather_encoded_arrays(cached, colmap, names,
                                        (max_block + 1) * br, cache)

    params = tuple(aux[-1]) if chain.has_params else ()
    key = ("pallas_direct", G, strides, len(params), dma, jplan.sig)
    runner = cache.get(key)
    if runner is None:
        kinds = {name: cached[colmap[name]].kind for name in colmap}
        runner = build_direct_runner(
            chain, kinds, len(params), specs=specs, key_names=key_names,
            strides=strides, G=G, agg_exprs=agg_exprs, lowering=lowering,
            dma=dma, join_plan=jplan if jplan.steps else None)
        cache[key] = runner
    if not reserve_build_operands(pool, jplan.nbytes):
        declined("JoinBuildSize")
        return None
    bidx = jnp.asarray([b for b, _lo, _hi in grid], dtype=jnp.int32)
    lo = jnp.asarray([lo_ for _b, lo_, _hi in grid], dtype=jnp.int32)
    hi = jnp.asarray([hi_ for _b, _lo, hi_ in grid], dtype=jnp.int32)
    try:
        acc_i, acc_f, kcounts = runner.fn(bidx, lo, hi, flat_arrays,
                                          jplan.arrays, params,
                                          runner.init_i, runner.init_f)
    finally:
        if pool is not None and jplan.nbytes:
            pool.free(jplan.nbytes)
    state = {k: acc_i[j] for j, k in enumerate(runner.int_names)}
    state.update({k: acc_f[j] for j, k in enumerate(runner.flt_names)})
    kinds = {name: cached[colmap[name]].kind for name in colmap}
    meter_kernel_run(runtime_stats, len(grid),
                     len(staged_indices(names, kinds)), dma)
    return state, kcounts[0], len(grid)
