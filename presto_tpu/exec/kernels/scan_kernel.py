"""Pallas fused scan kernel: decode -> filter -> prefix-sum compact ->
partial aggregation in one VMEM-resident grid pass.

The XLA fused chain (exec/fused.py) already collapses scan -> filter ->
project -> partial-agg into one program, but its aggregation update
reads the FULL chunk tile: a selective predicate (TPC-H Q6 keeps ~2% of
rows) still pays the G x cap one-hot grid over every padded row.  This
kernel is the hand-written hot path the ROADMAP's HBM-gap item calls
for:

  grid      one step per SURVIVING block-aligned chunk.  The kernel
            re-grids the scan's split ranges onto cap-aligned blocks
            (aggregation is order-insensitive, so any partition of the
            same row set is legal) because Pallas block specs index
            whole blocks; each grid entry carries its block index plus
            a [lo, hi) live row range as scalar-prefetch operands.
            Zone-map pruning runs over THIS grid, so pruned blocks
            never issue DMAs -- they are simply not in the grid.
  decode    ResidentColumn blocks stream out of HBM in ENCODED form via
            block specs (Pallas double-buffers the HBM->VMEM copies
            across grid steps); dict gather / RLE binary search runs in
            vector registers -- late materialization with the same
            semantics as ResidentColumn.slice_decode
  filter    the chain's own predicate/project expressions, lowered by
            the SAME exec/lowering.Lowering the XLA chain uses -- the
            kernel cannot drift from the engine semantics.  Bound
            parameters (the serving tier parameterizes plan literals)
            ride as traced scalar inputs, so re-executions with
            different constants reuse the compiled kernel.
  compact   a work-efficient Blelloch exclusive prefix sum over the
            selection mask drives an in-VMEM scatter compaction (no XLA
            gather round-trip), after which the aggregation update only
            touches ceil(live/SUBTILE) subtiles instead of the full tile
  agg       operators.agg_direct_update over compacted subtiles; the
            packed int64/float64 accumulators live in the kernel's
            output block across grid steps and feed
            operators.agg_direct_finalize unchanged

Device-side row counters (scan live rows + live rows after every chain
step) accumulate in an output block exactly like the XLA chain's
with_counts path, so EXPLAIN ANALYZE / QueryInfo operator stats stay
accurate on the kernel path.

Parity contract (tests/test_scan_kernel.py): integer accumulators
(sums over int64/decimal/date/bool, count, min, max) and the row
counters are BIT-FOR-BIT identical to the XLA chain -- integer adds
and min/max are associative, so compaction and re-gridding cannot
change them.  float64 sum/avg may differ in the last ulp (different
reduction tree pairings); TPC-H decimals are unscaled int64 on device,
so the Q1/Q6 money aggregates are exact.
"""
from __future__ import annotations

import math
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import operators as ops
from ..batch import Batch, Column
from . import shim

# Eligibility refusals, surfaced as kernelDeclined{reason} RuntimeStats
# counters (exec/pipeline.py _kernel_declined) -- the kernel twin of the
# fusionDeclined{...} family.  "Disabled" and "AggShape" are recorded by
# the pipeline itself (knob off / no direct-mode aggregation to fuse
# into); the rest are produced here.
KERNEL_DECLINE_REASONS = (
    "Disabled",            # scan.kernel = xla
    "AggShape",            # aggregation not direct-mode (G<=64) eligible
    "Backend",             # platform is neither tpu nor cpu-interpret
    "PlanShape",           # chain has join/semi/uid steps
    "ColumnsNotResident",  # a scanned column is not HBM-resident encoded
    "ChunkAlignment",      # encoded arrays cannot tile the block grid
)

# compacted rows are aggregated in subtiles of this many rows: the
# G x SUBTILE one-hot grid stays small while a selective filter skips
# most subtiles entirely (n_sub = ceil(live/SUBTILE) loop trips)
SUBTILE_ROWS = 2048


def _blelloch_exclusive(x):
    """Work-efficient (Blelloch) exclusive prefix sum of a power-of-two
    length vector, expressed with reshapes so both the up-sweep and the
    down-sweep are dense vector ops (no scatter): pairing adjacent
    elements halves the vector per level, then each level's prefix
    splits back into (left, left + pair_first)."""
    cur = x
    levels = []
    while cur.shape[0] > 1:
        levels.append(cur)
        pairs = cur.reshape(-1, 2)
        cur = pairs[:, 0] + pairs[:, 1]
    pref = jnp.zeros_like(cur)
    for lvl in reversed(levels):
        pairs = lvl.reshape(-1, 2)
        left = pref
        right = pref + pairs[:, 0]
        pref = jnp.stack([left, right], axis=1).reshape(-1)
    return pref


def _bisect_right(a, v):
    """searchsorted(a, v, side="right") as a fixed-trip vectorized
    binary search -- jnp.searchsorted does not lower inside Pallas TPU
    kernels, and the loop is exact integer arithmetic so interpret and
    compiled runs agree with the XLA chain's searchsorted decode."""
    size = a.shape[0]
    steps = max(1, int(math.ceil(math.log2(size + 1))) + 1)
    lo = jnp.zeros(v.shape, dtype=jnp.int64)
    hi = jnp.full(v.shape, size, dtype=jnp.int64)
    for _ in range(steps):
        cont = lo < hi
        mid = (lo + hi) // 2
        le = a[jnp.clip(mid, 0, size - 1)] <= v
        lo = jnp.where(cont & le, mid + 1, lo)
        hi = jnp.where(cont & ~le, mid, hi)
    return lo


class _Runner(NamedTuple):
    fn: Callable                 # jitted launcher
    init_i: object               # (Ni, G) int64 accumulator init rows
    init_f: object               # (max(Nf,1), G) float64 init rows
    int_names: Tuple[str, ...]   # acc_i row -> agg_direct state key
    flt_names: Tuple[str, ...]   # acc_f row -> agg_direct state key


def _chunk_block(i, bidx, lo, hi):
    return (bidx[i],)


def _whole_1d(i, bidx, lo, hi):
    return (0,)


def _whole_2d(i, bidx, lo, hi):
    return (0, 0)


def _merged_ranges(splits) -> List[Tuple[int, int]]:
    """The scan's owned row ranges, sorted and coalesced."""
    out: List[List[int]] = []
    for s, e in sorted((int(sp.start), int(sp.end)) for sp in splits):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _block_pruned(zone_maps, pushdown, params, pos: int,
                  count: int) -> bool:
    """storage/pushdown.prune_chunks' conservative unsatisfiability
    test for ONE aligned block (the kernel grid differs from the
    chain's split-relative chunk grid, so pruning re-runs here; the
    chain already metered ITS grid in chunks_for)."""
    from ...storage.pushdown import (entry_unsatisfiable,
                                     resolve_entry_value)
    for e in pushdown:
        zm = zone_maps.get(e["column"])
        if zm is None:
            continue
        value = resolve_entry_value(e["value"], params)
        if value is None:
            continue
        bounds = zm.chunk_bounds(pos, count)
        if bounds is None:
            continue
        if entry_unsatisfiable(e["op"], value, *bounds):
            return True
    return False


def aligned_grid(meta: dict, block_rows: int,
                 params) -> List[Tuple[int, int, int]]:
    """(block index, lo, hi) grid entries tiling the scan's split
    ranges with cap-aligned blocks; [lo, hi) is the block-relative live
    row range.  A block straddling two disjoint owned ranges yields two
    entries (grid steps accumulate, so revisiting a block is sound).
    Zone-map-pruned entries are dropped HERE -- they never reach the
    grid, so their HBM blocks are never DMA'd."""
    zone_maps = meta.get("zone_maps") or {}
    pushdown = meta.get("pushdown") or []
    entries: List[Tuple[int, int, int]] = []
    for s, e in _merged_ranges(meta["splits"]):
        for b in range(s // block_rows, (e - 1) // block_rows + 1):
            lo = max(s, b * block_rows) - b * block_rows
            hi = min(e, (b + 1) * block_rows) - b * block_rows
            if zone_maps and pushdown and _block_pruned(
                    zone_maps, pushdown, params,
                    b * block_rows + lo, hi - lo):
                continue
            entries.append((b, lo, hi))
    return entries


def build_direct_runner(chain, kinds: Dict[str, str], n_params: int, *,
                        specs, key_names, strides, G, agg_exprs,
                        lowering) -> _Runner:
    """Compile the chain's static shape (column encodings, steps, agg
    specs) into a jitted Pallas launcher.  `kinds` maps each scan
    output name to its ResidentColumn encoding; `n_params` is the
    length of the chain's bound-parameter vector.  The launcher
    re-traces when the surviving-grid length changes (param pruning);
    everything else is baked in, mirroring the fused_cache programs of
    the XLA path."""
    meta = chain.scan_meta
    cap = chain.leaf_cap(())
    steps = chain.steps
    n_steps = len(steps)
    dicts = meta["dicts"]
    colmap = meta["colmap"]
    names = tuple(colmap)

    template = ops.agg_direct_init(G, specs)
    int_names = tuple(k for k, v in template.items()
                      if v.dtype == jnp.int64)
    flt_names = tuple(k for k, v in template.items()
                      if v.dtype == jnp.float64)
    assert len(int_names) + len(flt_names) == len(template)
    n_i = len(int_names)
    n_f = len(flt_names)
    init_i = jnp.stack([template[k] for k in int_names])
    init_f = (jnp.stack([template[k] for k in flt_names]) if n_f
              else jnp.zeros((1, G), dtype=jnp.float64))

    def kernel(bidx_ref, lo_ref, hi_ref, *refs):
        col_refs = refs[:len(refs) - 5 - n_params]
        param_refs = refs[len(col_refs):len(col_refs) + n_params]
        init_i_ref, init_f_ref = refs[-5:-3]
        acc_i_ref, acc_f_ref, counts_ref = refs[-3:]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init_outputs():
            acc_i_ref[...] = init_i_ref[...]
            acc_f_ref[...] = init_f_ref[...]
            counts_ref[...] = jnp.zeros((1, 1 + n_steps), dtype=jnp.int64)

        pos = bidx_ref[i].astype(jnp.int64) * cap
        idx0 = jnp.arange(cap, dtype=jnp.int64)
        live = (idx0 >= lo_ref[i].astype(jnp.int64)) \
            & (idx0 < hi_ref[i].astype(jnp.int64))

        # -- late decode: ResidentColumn.slice_decode semantics over the
        # chunk's VMEM blocks, then the scan's dead-row zeroing
        cols: Dict[str, Column] = {}
        r = 0
        for name in names:
            kind = kinds[name]
            if kind == "plain":
                v = col_refs[r][...]
                r += 1
            elif kind == "dict":
                codes = col_refs[r][...]
                values = col_refs[r + 1][...]
                r += 2
                v = values[codes.astype(jnp.int32)]
            else:                                    # rle
                run_values = col_refs[r][...]
                run_starts = col_refs[r + 1][...]
                r += 2
                ri = _bisect_right(run_starts, pos + idx0) - 1
                ri = jnp.clip(ri, 0, run_values.shape[0] - 1)
                v = run_values[ri]
            v = jnp.where(live, v, jnp.zeros((), v.dtype))
            cols[name] = Column(v, None, dicts.get(name))
        batch = Batch(cols, live)

        # -- the chain's own filter/project/rename steps, lowered by the
        # engine's Lowering (shared with the XLA chain), with the same
        # per-step live-row counters chain.make(with_counts=True) emits.
        # The bound-parameter vector rides along for step expressions
        # exactly as in FusedChain.make's _pb (aggregation input
        # expressions see a param-less batch on both paths).
        params_k = tuple(p[...][0] for p in param_refs)

        def _pb(b):
            return b.with_params(params_k) if n_params else b
        counts = [jnp.sum(live)]
        for step in steps:
            kind = step[0]
            if kind == "filter":
                batch = ops.apply_filter(
                    batch, lowering.eval(step[1], _pb(batch)))
            elif kind == "project":
                pb = _pb(batch)
                batch = Batch({v2.name: lowering.eval(e, pb)
                               for v2, e in step[1]}, batch.mask)
            else:                                    # rename
                batch = Batch({o: batch.columns[src]
                               for o, src in step[1]}, batch.mask)
            counts.append(jnp.sum(batch.mask))

        codes = None
        for k, stride in zip(key_names, strides):
            c = batch.columns[k].values.astype(jnp.int64)
            codes = c * stride if codes is None else codes + c * stride
        if codes is None:
            codes = jnp.zeros(cap, dtype=jnp.int64)
        agg_cols = agg_exprs(batch)
        mask = batch.mask

        # -- prefix-sum compaction: exclusive scan of the mask gives
        # each live row its packed slot; dead rows scatter to index cap
        # and drop.  Downstream aggregation then loops over live
        # subtiles only.
        pref = _blelloch_exclusive(mask.astype(jnp.int32))
        total = pref[cap - 1] + mask[cap - 1].astype(jnp.int32)
        dest = jnp.where(mask, pref, cap)
        ccodes = jnp.zeros(cap, dtype=jnp.int64).at[dest].set(
            codes, mode="drop")
        cvals: Dict[str, object] = {}
        cnulls: Dict[str, object] = {}
        for spec in specs:
            col = agg_cols.get(spec.output)
            if col is None:                          # count_star
                continue
            cvals[spec.output] = jnp.zeros(
                cap, dtype=col.values.dtype).at[dest].set(
                    col.values, mode="drop")
            if col.nulls is not None:
                cnulls[spec.output] = jnp.zeros(
                    cap, dtype=bool).at[dest].set(col.nulls, mode="drop")

        ts = min(cap, SUBTILE_ROWS)
        n_sub = (total + ts - 1) // ts
        acc_i = acc_i_ref[...]
        acc_f = acc_f_ref[...]
        state = {k: acc_i[j] for j, k in enumerate(int_names)}
        state.update({k: acc_f[j] for j, k in enumerate(flt_names)})
        sub_idx = jnp.arange(ts, dtype=jnp.int32)

        def sub(j, st):
            off = j * ts
            m = (off + sub_idx) < total
            sc = jax.lax.dynamic_slice(ccodes, (off,), (ts,))
            sa: Dict[str, Optional[Column]] = {}
            for spec in specs:
                cv = cvals.get(spec.output)
                if cv is None:
                    sa[spec.output] = None
                    continue
                sv = jax.lax.dynamic_slice(cv, (off,), (ts,))
                cn = cnulls.get(spec.output)
                sn = (jax.lax.dynamic_slice(cn, (off,), (ts,))
                      if cn is not None else None)
                sa[spec.output] = Column(sv, sn)
            return ops.agg_direct_update(st, Batch({}, m), sc, sa,
                                         specs, G)
        state = jax.lax.fori_loop(0, n_sub, sub, state)
        acc_i_ref[...] = jnp.stack([state[k] for k in int_names])
        if n_f:
            acc_f_ref[...] = jnp.stack([state[k] for k in flt_names])
        counts_ref[...] = counts_ref[...] + jnp.stack(counts).astype(
            jnp.int64)[None, :]

    @jax.jit
    def run(bidx, lo, hi, cached, params, init_i_arg, init_f_arg):
        flat: List = []
        in_specs: List = []
        for name in names:
            rc = cached[colmap[name]]
            if rc.kind == "plain":
                (data,) = rc.arrays
                flat.append(data)
                in_specs.append(pl.BlockSpec((cap,), _chunk_block))
            elif rc.kind == "dict":
                codes, values = rc.arrays
                flat += [codes, values]
                in_specs += [pl.BlockSpec((cap,), _chunk_block),
                             pl.BlockSpec(values.shape, _whole_1d)]
            else:                                    # rle
                run_values, run_starts = rc.arrays
                flat += [run_values, run_starts]
                in_specs += [pl.BlockSpec(run_values.shape, _whole_1d),
                             pl.BlockSpec(run_starts.shape, _whole_1d)]
        for p in params:
            flat.append(jnp.asarray(p).reshape(1))
            in_specs.append(pl.BlockSpec((1,), _whole_1d))
        flat += [init_i_arg, init_f_arg]
        in_specs += [pl.BlockSpec(init_i_arg.shape, _whole_2d),
                     pl.BlockSpec(init_f_arg.shape, _whole_2d)]
        out_shape = [
            jax.ShapeDtypeStruct((n_i, G), jnp.int64),
            jax.ShapeDtypeStruct((max(n_f, 1), G), jnp.float64),
            jax.ShapeDtypeStruct((1, 1 + n_steps), jnp.int64),
        ]
        out_specs = [
            pl.BlockSpec((n_i, G), _whole_2d),
            pl.BlockSpec((max(n_f, 1), G), _whole_2d),
            pl.BlockSpec((1, 1 + n_steps), _whole_2d),
        ]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bidx.shape[0],),
            in_specs=in_specs,
            out_specs=out_specs,
        )
        return shim.pallas_call(kernel, grid_spec=grid_spec,
                                out_shape=out_shape)(bidx, lo, hi, *flat)

    return _Runner(run, init_i, init_f, int_names, flt_names)


def try_direct_scan_kernel(chain, aux, *, specs, key_names, strides, G,
                           agg_exprs, lowering, cache, declined,
                           runtime_stats=None):
    """Run the fused scan chain through the Pallas kernel when eligible.

    Returns (agg_direct state dict, int64[1 + n_steps] row counters,
    grid length) on success -- the caller feeds them to
    agg_direct_finalize and the operator-stats spine exactly like the
    XLA direct path -- or None after recording one
    kernelDeclined{reason} counter."""
    if jax.default_backend() not in ("cpu", "tpu"):
        declined("Backend")
        return None
    if any(s[0] not in ("filter", "project", "rename")
           for s in chain.steps):
        declined("PlanShape")
        return None
    cap = chain.leaf_cap(())
    if cap & (cap - 1):
        # the Blelloch scan pairs elements level by level: power-of-two
        # tiles only
        declined("ChunkAlignment")
        return None
    cached = aux[0] or {}
    colmap = chain.scan_meta.get("colmap") or {}
    if not colmap or any(colmap[n] not in cached for n in colmap):
        declined("ColumnsNotResident")
        return None
    params_fp = chain.compiler.ctx.params_fingerprint
    grid = aligned_grid(chain.scan_meta, cap, params_fp)
    if not grid:
        # everything pruned: the XLA chain keeps one chunk for its
        # compiled fori_loop, but the kernel can simply return its init
        # state (the residual filter would kill every row anyway)
        template = ops.agg_direct_init(G, specs)
        return (template,
                jnp.zeros(1 + len(chain.steps), dtype=jnp.int64), 0)
    # per-row encoded arrays must tile cleanly under the block grid:
    # every grid block [b*cap, (b+1)*cap) must lie inside the padded
    # array (store.py pads by the BUILD-time capacity, which can differ
    # from this chain's chunk capacity)
    max_block = max(b for b, _lo, _hi in grid)
    for name in colmap:
        rc = cached[colmap[name]]
        if rc.kind in ("plain", "dict") \
                and rc.arrays[0].shape[0] < (max_block + 1) * cap:
            declined("ChunkAlignment")
            return None

    params = tuple(aux[-1]) if chain.has_params else ()
    key = ("pallas_direct", G, strides, len(params))
    runner = cache.get(key)
    if runner is None:
        kinds = {name: cached[colmap[name]].kind for name in colmap}
        runner = build_direct_runner(
            chain, kinds, len(params), specs=specs, key_names=key_names,
            strides=strides, G=G, agg_exprs=agg_exprs, lowering=lowering)
        cache[key] = runner
    bidx = jnp.asarray([b for b, _lo, _hi in grid], dtype=jnp.int32)
    lo = jnp.asarray([lo_ for _b, lo_, _hi in grid], dtype=jnp.int32)
    hi = jnp.asarray([hi_ for _b, _lo, hi_ in grid], dtype=jnp.int32)
    acc_i, acc_f, kcounts = runner.fn(bidx, lo, hi, cached, params,
                                      runner.init_i, runner.init_f)
    state = {k: acc_i[j] for j, k in enumerate(runner.int_names)}
    state.update({k: acc_f[j] for j, k in enumerate(runner.flt_names)})
    if runtime_stats is not None:
        runtime_stats.add("kernelScanPrograms", 1)
    return state, kcounts[0], len(grid)
