"""Prefix-sum window aggregation inside a Pallas kernel.

The XLA window path (operators.window_batch) computes every running
aggregate / ranking function with whole-array cumulative scans; each
scan materializes its intermediate in HBM.  This kernel keeps the
sorted run VMEM-resident and evaluates ALL window outputs that share
one (partition, order) spec in a single launch, using the same
work-efficient pairing scan the scan kernel's compaction uses
(generalized to max/min/add so segment starts, peer ends and running
sums are in-kernel scans):

  sort      stays OUTSIDE the kernel: ops.sort_indices is the single
            definition of order semantics (dictionary ranks, NULL
            sentinels, padding-last), shared with the XLA path so the
            two paths see the SAME permutation.
  segments  partition / peer boundaries from null-aware change flags
            over the sorted key columns (operators._row_change twin),
            plus the live->padding mask transition, exactly as in
            window_batch; segment starts/ends come from inclusive
            max/min scans over flagged indices.
  frames    the default frame (RANGE UNBOUNDED PRECEDING .. CURRENT
            ROW) = [segment start, peer-group end]; running
            SUM/COUNT/AVG read two points of an inclusive prefix sum.

Parity contract: the pairing scans are exact for the integer max/min/
add operators regardless of association, the frame-aggregate identity
cnt0[fe+1] - cnt0[fs] == incl[fe] - incl[fs] + contrib[fs] is exact
int64 arithmetic, and padding lanes (appended after the sorted dead
rows to reach the scan's power-of-two width) start their own segment
at the mask transition exactly like window_batch's padding rows -- so
live-row outputs are bit-identical to the XLA path and the numpy
oracle.  Float sum/avg would re-associate the reduction tree, so they
decline instead (WindowFunctionShape); TPC-H decimals are unscaled
int64 on device and stay exact, including _decimal_avg rounding.

Gates (kernelDeclined reasons, scan_kernel.KERNEL_DECLINE_REASONS):
  WindowFunctionShape  function outside {row_number, rank, dense_rank,
                       count, count_star, sum, avg}, an explicit
                       frame, constant extras, or float accumulation
  WindowKeyShape       a late-materialized (lazy) partition/order/arg
                       column -- peer detection must not reorder the
                       row-id indirection
  WindowInputSize      padded operand bytes over
                       KERNEL_WINDOW_MAX_BYTES (the whole sorted run
                       must sit in VMEM at once)
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .. import operators as ops
from ..batch import Batch, Column
from . import shim
from .scan_kernel import KERNEL_METRICS

# the whole sorted run (mask + key/arg columns + per-spec outputs) is
# VMEM-resident for the launch; bigger inputs decline and run the XLA
# scans, which stream through HBM
KERNEL_WINDOW_MAX_BYTES = 1 << 23

_SUPPORTED = ("row_number", "rank", "dense_rank", "count", "count_star",
              "sum", "avg")

# compiled launchers keyed by the static shape (spec tuple, key layout,
# padded width) -- the window twin of the scan kernel's runner cache
_RUNNER_CACHE: Dict[tuple, object] = {}


def _exclusive_scan(x, op, ident):
    """scan_kernel._blelloch_exclusive generalized to any associative
    `op` with identity `ident` (max/min/add over a power-of-two
    vector).  Integer ops are exact under any pairing, so the result
    matches lax.cummax/cummin/jnp.cumsum bit-for-bit."""
    cur = x
    levels = []
    while cur.shape[0] > 1:
        levels.append(cur)
        pairs = cur.reshape(-1, 2)
        cur = op(pairs[:, 0], pairs[:, 1])
    pref = jnp.full_like(cur, ident)
    for lvl in reversed(levels):
        pairs = lvl.reshape(-1, 2)
        left = pref
        right = op(pref, pairs[:, 0])
        pref = jnp.stack([left, right], axis=1).reshape(-1)
    return pref


def _inclusive_scan(x, op, ident):
    return op(_exclusive_scan(x, op, ident), x)


def _change(v, nulls):
    """operators._row_change over raw (values, nulls) arrays: [i] = row
    i differs from row i-1, null-aware (two NULLs equal, NaN equals
    NaN -- grouping semantics)."""
    a, b = v[1:], v[:-1]
    if jnp.issubdtype(v.dtype, jnp.floating):
        eq = (a == b) | (jnp.isnan(a) & jnp.isnan(b))
    else:
        eq = a == b
    if nulls is not None:
        na, nb = nulls[1:], nulls[:-1]
        eq = jnp.where(na | nb, na & nb, eq)
    return jnp.concatenate([jnp.ones(1, dtype=bool), ~eq])


def _build_runner(partition_names, orderings, specs, layout, N):
    """Jitted whole-array Pallas launch for one static window shape.
    `layout` lists the kernel's column operands as (name, has_nulls) in
    input order; every operand is a padded (N,) array."""
    n_specs = len(specs)

    def kernel(*refs):
        mask = refs[0][...]
        arrays = {}
        r = 1
        for name, has_nulls in layout:
            v = refs[r][...]
            r += 1
            nl = None
            if has_nulls:
                nl = refs[r][...]
                r += 1
            arrays[name] = (v, nl)
        out_val_refs = refs[r:r + n_specs]
        out_null_refs = refs[r + n_specs:]

        idx = jnp.arange(N, dtype=jnp.int64)
        # the valid->padding transition starts a segment so padding
        # never joins (or extends the frame of) the last real partition
        part_start = (idx == 0) | jnp.concatenate(
            [jnp.zeros(1, dtype=bool), mask[1:] != mask[:-1]])
        for p in partition_names:
            part_start = part_start | _change(*arrays[p])
        peer_start = part_start
        for o, _ in orderings:
            peer_start = peer_start | _change(*arrays[o])

        seg_start = _inclusive_scan(jnp.where(part_start, idx, 0),
                                    jnp.maximum, 0)
        peer_start_idx = _inclusive_scan(jnp.where(peer_start, idx, 0),
                                         jnp.maximum, 0)
        at_or_after = jnp.flip(_inclusive_scan(
            jnp.flip(jnp.where(peer_start, idx, N)), jnp.minimum, N))
        peer_end = jnp.concatenate(
            [at_or_after[1:], jnp.full(1, N, dtype=jnp.int64)]) - 1

        # default frame: RANGE UNBOUNDED PRECEDING .. CURRENT ROW
        fs, fe = seg_start, peer_end
        empty = fe < fs
        fs_c = jnp.clip(fs, 0, N - 1)
        fe_c = jnp.clip(fe, 0, N - 1)

        for j, spec in enumerate(specs):
            nulls = None
            if spec.name == "row_number":
                vals = idx - seg_start + 1
            elif spec.name == "rank":
                vals = peer_start_idx - seg_start + 1
            elif spec.name == "dense_rank":
                cp = _inclusive_scan(peer_start.astype(jnp.int64),
                                     jnp.add, 0)
                vals = cp - cp[seg_start] + 1
            else:
                if spec.name == "count_star":
                    contrib = mask
                    x = contrib.astype(jnp.int64)
                else:
                    x, xn = arrays[spec.arg]
                    contrib = mask if xn is None else (mask & ~xn)
                # cnt0[fe+1] - cnt0[fs] over the concat([0], cumsum)
                # prefix == incl[fe] - incl[fs] + contrib[fs]: exact
                # int64, no length-(N+1) array in VMEM
                ci = contrib.astype(jnp.int64)
                cnt_incl = _inclusive_scan(ci, jnp.add, 0)
                frame_cnt = jnp.where(
                    empty, 0,
                    cnt_incl[fe_c] - cnt_incl[fs_c] + ci[fs_c])
                if spec.name in ("count", "count_star"):
                    vals = frame_cnt
                else:                            # sum / avg (integer)
                    xv = jnp.where(contrib, x, 0).astype(jnp.int64)
                    sum_incl = _inclusive_scan(xv, jnp.add, 0)
                    frame_sum = jnp.where(
                        empty, 0,
                        sum_incl[fe_c] - sum_incl[fs_c] + xv[fs_c])
                    isempty = frame_cnt == 0
                    if spec.name == "sum":
                        vals = frame_sum
                    else:
                        vals = ops._decimal_avg(frame_sum, frame_cnt,
                                                isempty)
                    nulls = isempty
            out_val_refs[j][...] = vals.astype(jnp.int64)
            out_null_refs[j][...] = (nulls if nulls is not None
                                     else jnp.zeros(N, dtype=bool))

    out_shape = ([jax.ShapeDtypeStruct((N,), jnp.int64)
                  for _ in range(n_specs)]
                 + [jax.ShapeDtypeStruct((N,), bool)
                    for _ in range(n_specs)])

    @jax.jit
    def launch(flat):
        return shim.pallas_call(kernel, out_shape=out_shape)(*flat)

    return launch


def try_window_kernel(batch: Batch, partition_names, orderings, specs, *,
                      declined, runtime_stats=None):
    """Evaluate a WindowNode's shared-spec functions through the Pallas
    prefix-scan kernel when eligible.  Returns the output Batch (sorted
    row order, same contract as ops.window_batch) or None after
    metering one kernelDeclined{reason} -- the XLA path takes over."""
    for spec in specs:
        if (spec.name not in _SUPPORTED or spec.frame is not None
                or spec.extra):
            declined("WindowFunctionShape")
            return None
        if spec.name in ("sum", "avg") and spec.is_float:
            # float cumsum re-associates the reduction tree; declining
            # preserves the bit-identity contract
            declined("WindowFunctionShape")
            return None
    if jax.default_backend() not in ("cpu", "tpu"):
        declined("Backend")
        return None
    needed = []
    for nm in (tuple(partition_names) + tuple(o for o, _ in orderings)
               + tuple(s.arg for s in specs if s.arg)):
        if nm not in needed:
            needed.append(nm)
    for nm in needed:
        if batch.columns[nm].lazy is not None:
            declined("WindowKeyShape")
            return None

    n = batch.capacity
    N = 1 << max(0, int(n - 1).bit_length())
    layout = []
    nbytes = N                                    # mask
    for nm in needed:
        c = batch.columns[nm]
        has_nulls = c.nulls is not None
        layout.append((nm, has_nulls))
        nbytes += N * (c.values.dtype.itemsize + (1 if has_nulls else 0))
    nbytes += N * 9 * max(1, len(specs))          # int64+bool outputs
    if nbytes > KERNEL_WINDOW_MAX_BYTES:
        declined("WindowInputSize")
        return None

    # the sort and gather are shared with the XLA path: one definition
    # of order semantics, one permutation
    sort_keys = [(p, "ASC_NULLS_FIRST") for p in partition_names] \
        + list(orderings)
    perm = ops.sort_indices(batch, sort_keys)
    cols = {nm: c.gather(perm) for nm, c in batch.columns.items()}
    mask = batch.mask[perm]

    pad = N - n

    def p1(a):
        return jnp.pad(a, (0, pad)) if pad else a

    flat = [p1(mask)]
    for nm, has_nulls in layout:
        c = cols[nm]
        flat.append(p1(c.values))
        if has_nulls:
            flat.append(p1(c.nulls))

    key = (tuple(partition_names), tuple(orderings), tuple(specs),
           tuple((nm, str(cols[nm].values.dtype), hn)
                 for nm, hn in layout), N)
    runner = _RUNNER_CACHE.get(key)
    if runner is None:
        runner = _build_runner(tuple(partition_names), tuple(orderings),
                               tuple(specs), tuple(layout), N)
        _RUNNER_CACHE[key] = runner
    outs = runner(tuple(flat))

    n_specs = len(specs)
    out = dict(cols)
    for j, spec in enumerate(specs):
        vals = outs[j][:n]
        if spec.name in ("sum", "avg"):
            out[spec.output] = Column(vals, outs[n_specs + j][:n])
        else:
            out[spec.output] = Column(vals, None)
    KERNEL_METRICS.record_window_run()
    if runtime_stats is not None:
        runtime_stats.add("kernelWindowPrograms", 1)
    return Batch(out, mask)
