"""Grouped aggregation inside the Pallas scan kernel.

PR 10's kernel only ran direct-mode shapes (G<=64 one-hot accumulator
grids); every grouped-by-key plan fell back to the XLA chain.  This
module keeps the whole decode -> predicate -> Blelloch-compact pipeline
of scan_kernel.py and swaps the aggregation tail for one of two
slot-addressing modes, mirroring the XLA chain's own span/hash split:

  span   closed dictionary/bool key domains whose stride product fits
         the VMEM accumulator gate (KERNEL_SPAN_MAX_GROUPS): the
         combined stride code IS the slot index, and because
         operators.agg_span_init is agg_direct_init (same state
         template and int64/float64 dtype split), the direct runner's
         stacked-accumulator kernel is reused verbatim with
         ops.agg_span_update as the subtile update -- a packed scatter
         instead of the G x rows one-hot grid.  Finalize reconstructs
         the key values from the slot index exactly like the XLA
         static-span path, so results stay bit-identical (integers) /
         last-ulp (float sums).

  hash   everything else (open integer domains, multi-key mixes, lazy
         row-id keys): operators.agg_update's open-addressing scatter
         table runs IN-KERNEL over compacted subtiles with salt 0.  The
         per-slot state (keyhash / occupied / key values / accumulator
         columns) lives across grid steps in the kernel's output
         blocks, initialized from the agg_init template on step 0, and
         feeds ops.agg_finalize unchanged.  The table is sized from the
         optimizer's group estimate (the pipeline's initial_slots) and
         capped at KERNEL_HASH_MAX_SLOTS; an estimate over the cap, a
         failed memory reservation, or a runtime probe overflow
         (__collision) declines with AggGroupCardinality and the XLA
         chain -- with its doubling collision retry -- takes over.

Both modes share the direct kernel's grid construction (zone-map-pruned
pow2 blocks, padded tails) and DMA staging knob (`scan.kernel-dma`),
and emit the same device-side per-step row counters.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import operators as ops
from ..batch import Batch, Column
from . import shim
from .scan_kernel import (GROUPED_SUBTILE_ROWS, KERNEL_HASH_MAX_SLOTS,
                          _chunk_block, _whole_1d, _whole_2d,
                          agg_compaction_entries, aligned_grid,
                          block_rows_for, build_direct_runner,
                          chain_eligible, compact_columns,
                          decode_columns, dma_scratch_shapes,
                          encoded_in_specs, gather_encoded_arrays,
                          meter_kernel_run, run_chain_steps,
                          staged_indices, subtile_agg_inputs,
                          _stage_slabs)


def build_hash_runner(chain, kinds: Dict[str, str], n_params: int, *,
                      specs, key_names, key_dtypes, num_slots, salt=0,
                      agg_exprs, lowering, dma: str = "single",
                      join_plan=None):
    """Jitted Pallas launcher for the hashed grouped mode: the
    open-addressing accumulator table of ops.agg_init/agg_update lives
    in the kernel's per-entry output blocks (grid steps accumulate into
    block 0), updated subtile-by-subtile over the compacted rows with
    the SAME probe/scatter code the XLA chain runs -- the kernel cannot
    drift from the engine's slot semantics.  Returns (launcher,
    entry_names).  `join_plan` lowers fanout-1 join/semi probe steps
    in-kernel exactly as in build_direct_runner (kernels/join.py)."""
    from .join import join_appliers
    n_join = len(join_plan.arrays) if join_plan is not None else 0
    meta = chain.scan_meta
    br = block_rows_for(chain.leaf_cap(()))
    steps = chain.steps
    n_steps = len(steps)
    dicts = meta["dicts"]
    colmap = meta["colmap"]
    names = tuple(colmap)
    staged = staged_indices(names, kinds) if dma == "double" else ()
    n_staged = len(staged)

    template = ops.agg_init(num_slots, specs, key_names, key_dtypes)
    entry_names = tuple(template)
    n_entries = len(entry_names)
    # every agg_init entry is a UNIFORM fill (zeros / EMPTY_SLOT /
    # +-int64 extrema), so the kernel recreates the template in its
    # step-0 output init from host scalar fills -- pallas_call rejects
    # device arrays captured as tracing constants
    t_host = jax.device_get(template)  # lint: allow-host-sync
    fills = {name: np.asarray(v).flat[0] for name, v in t_host.items()}
    entry_dtypes = {name: np.asarray(v).dtype for name, v in t_host.items()}

    def kernel(bidx_ref, lo_ref, hi_ref, *refs):
        if n_staged:
            scratch = refs[-(n_staged + 1):-1]
            sem = refs[-1]
            refs = refs[:-(n_staged + 1)]
        col_refs = refs[:len(refs) - n_entries - 1 - n_params - n_join]
        join_refs = refs[len(col_refs):len(col_refs) + n_join]
        param_refs = refs[len(col_refs) + n_join:
                          len(col_refs) + n_join + n_params]
        state_refs = refs[-(n_entries + 1):-1]
        counts_ref = refs[-1]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init_outputs():
            for name, ref in zip(entry_names, state_refs):
                ref[...] = jnp.full(ref.shape, fills[name],
                                    dtype=entry_dtypes[name])
            counts_ref[...] = jnp.zeros((1, 1 + n_steps), dtype=jnp.int64)

        slabs = (_stage_slabs(col_refs, staged, scratch, sem, bidx_ref,
                              br) if n_staged else {})
        pos = bidx_ref[i].astype(jnp.int64) * br
        idx0 = jnp.arange(br, dtype=jnp.int64)
        live = (idx0 >= lo_ref[i].astype(jnp.int64)) \
            & (idx0 < hi_ref[i].astype(jnp.int64))

        cols = decode_columns(names, kinds, dicts, col_refs, slabs,
                              pos, idx0, live)
        params_k = tuple(p[...][0] for p in param_refs)
        appliers = (join_appliers(join_plan,
                                  [r[...] for r in join_refs])
                    if n_join else None)
        batch, counts = run_chain_steps(Batch(cols, live), live, steps,
                                        lowering, params_k, n_params,
                                        appliers)

        # compact the group-key columns alongside the aggregate inputs:
        # the hash update probes on VALUES, so the keys ride the same
        # prefix-sum scatter
        named = agg_compaction_entries(specs, agg_exprs(batch))
        key_has_nulls = {}
        for k in key_names:
            col = batch.columns[k]
            named.append(("kv:" + k, col.values))
            key_has_nulls[k] = col.nulls is not None
            if col.nulls is not None:
                named.append(("kn:" + k, col.nulls))
        total, compacted = compact_columns(batch.mask, br, named)

        state = {}
        for name, ref in zip(entry_names, state_refs):
            v = ref[...]
            state[name] = v[0] if name == "__collision" else v

        ts = min(br, GROUPED_SUBTILE_ROWS)
        n_sub = (total + ts - 1) // ts
        sub_idx = jnp.arange(ts, dtype=jnp.int32)

        def sub(j, st):
            off = j * ts
            m = (off + sub_idx) < total
            key_cols: List[Column] = []
            for k in key_names:
                sv = jax.lax.dynamic_slice(
                    compacted["kv:" + k], (off,), (ts,))
                sn = (jax.lax.dynamic_slice(
                    compacted["kn:" + k], (off,), (ts,))
                    if key_has_nulls[k] else None)
                key_cols.append(Column(sv, sn))
            sa = subtile_agg_inputs(compacted, specs, off, ts)
            return ops.agg_update(st, Batch({}, m), key_cols, sa, specs,
                                  num_slots, salt, key_names, None)
        state = jax.lax.fori_loop(0, n_sub, sub, state)
        for name, ref in zip(entry_names, state_refs):
            v = state[name]
            ref[...] = v.reshape(1) if name == "__collision" else v
        counts_ref[...] = counts_ref[...] + jnp.stack(counts).astype(
            jnp.int64)[None, :]

    @jax.jit
    def run(bidx, lo, hi, arrays, jarrays, params):
        flat = list(arrays)
        in_specs = encoded_in_specs(names, kinds, flat, br, staged)
        for a in jarrays:
            flat.append(a)
            in_specs.append(pl.BlockSpec(a.shape, _whole_1d))
        for p in params:
            flat.append(jnp.asarray(p).reshape(1))
            in_specs.append(pl.BlockSpec((1,), _whole_1d))
        out_shape = []
        out_specs = []
        for name in entry_names:
            shape = (1,) if name == "__collision" else (num_slots,)
            out_shape.append(
                jax.ShapeDtypeStruct(shape, template[name].dtype))
            out_specs.append(pl.BlockSpec(shape, _whole_1d))
        out_shape.append(
            jax.ShapeDtypeStruct((1, 1 + n_steps), jnp.int64))
        out_specs.append(pl.BlockSpec((1, 1 + n_steps), _whole_2d))
        scratch_shapes = (dma_scratch_shapes(staged, flat, br)
                          if n_staged else [])
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bidx.shape[0],),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=tuple(scratch_shapes),
        )
        return shim.pallas_call(kernel, grid_spec=grid_spec,
                                out_shape=out_shape)(bidx, lo, hi, *flat)

    return run, entry_names


def try_grouped_scan_kernel(chain, aux, *, specs, key_names, key_dtypes,
                            key_dicts, key_lazy, span_info, est_slots,
                            agg_exprs, lowering, cache, declined, pool,
                            state_bytes, runtime_stats=None,
                            dma: str = "single", expands=()):
    """Run a grouped (G > 64) aggregation chain through the Pallas
    kernel when eligible: span mode when `span_info` (the caller's
    _direct_mode_info at gmax=KERNEL_SPAN_MAX_GROUPS) is set, hashed
    open addressing otherwise.  Returns (finalized Batch,
    int64[1 + n_steps] row counters, grid length), or None after
    metering one kernelDeclined{reason} -- the XLA span/sort/hash paths
    take over.  The AggGroupCardinality capacity gate covers: a group
    estimate over KERNEL_HASH_MAX_SLOTS, a failed accumulator memory
    reservation, and a runtime probe overflow (each of which means the
    group population is too large for a VMEM-resident table).

    Chains with fanout-1 join/semi steps (Q3/Q18 shapes) lower their
    probes in-kernel; `expands` is prep()'s per-join fanout tuple, and
    the build operand bytes are charged to `pool` non-revocably for
    each launch (kernels/join.py)."""
    from .join import (KERNEL_JOIN_MAX_BUILD_BYTES, plan_join_layout,
                       reserve_build_operands)
    elig = chain_eligible(chain, aux, declined, allow_joins=True)
    if elig is None:
        return None
    cached, colmap = elig
    jplan = plan_join_layout(chain.steps, aux, expands, declined,
                             max_bytes=KERNEL_JOIN_MAX_BUILD_BYTES)
    if jplan is None:
        return None
    names = tuple(colmap)
    br = block_rows_for(chain.leaf_cap(()))
    n_steps = len(chain.steps)
    params_fp = chain.compiler.ctx.params_fingerprint
    grid = aligned_grid(chain.scan_meta, br, params_fp)
    params = tuple(aux[-1]) if chain.has_params else ()
    kinds = {name: cached[colmap[name]].kind for name in colmap}
    n_staged = (len(staged_indices(names, kinds))
                if dma == "double" else 0)

    if span_info is not None:
        doms, G, strides, kdts, kdicts = span_info
        reserve = G * 24 * max(1, len(specs))
        if not pool.try_reserve(reserve):
            declined("AggGroupCardinality")
            return None
        try:
            if not grid:
                state = ops.agg_span_init(G, specs)
                kcounts = jnp.zeros(1 + n_steps, dtype=jnp.int64)
                n_blocks = 0
            else:
                max_block = max(b for b, _lo, _hi in grid)
                flat_arrays = gather_encoded_arrays(
                    cached, colmap, names, (max_block + 1) * br, cache)
                key = ("pallas_span", G, strides, len(params), dma,
                       jplan.sig)
                runner = cache.get(key)
                if runner is None:
                    runner = build_direct_runner(
                        chain, kinds, len(params), specs=specs,
                        key_names=key_names, strides=strides, G=G,
                        agg_exprs=agg_exprs, lowering=lowering, dma=dma,
                        update_fn=ops.agg_span_update,
                        subtile=GROUPED_SUBTILE_ROWS,
                        join_plan=jplan if jplan.steps else None)
                    cache[key] = runner
                bidx = jnp.asarray([b for b, _, _ in grid],
                                   dtype=jnp.int32)
                lo = jnp.asarray([l for _, l, _ in grid],
                                 dtype=jnp.int32)
                hi = jnp.asarray([h for _, _, h in grid],
                                 dtype=jnp.int32)
                if not reserve_build_operands(pool, jplan.nbytes):
                    declined("JoinBuildSize")
                    return None
                try:
                    acc_i, acc_f, kc = runner.fn(
                        bidx, lo, hi, flat_arrays, jplan.arrays, params,
                        runner.init_i, runner.init_f)
                finally:
                    if jplan.nbytes:
                        pool.free(jplan.nbytes)
                state = {k: acc_i[j]
                         for j, k in enumerate(runner.int_names)}
                state.update({k: acc_f[j]
                              for j, k in enumerate(runner.flt_names)})
                kcounts = kc[0]
                n_blocks = len(grid)
            slot = jnp.arange(G, dtype=jnp.int64)
            key_arrays = {}
            stride = G
            for k, dom, dt in zip(key_names, doms, kdts):
                stride //= dom
                key_arrays[k] = ((slot // stride) % dom).astype(dt)
            out = ops.agg_span_finalize(state, specs, key_names,
                                        key_arrays, kdicts, key_lazy)
        finally:
            pool.free(reserve)
        meter_kernel_run(runtime_stats, n_blocks, n_staged, dma)
        return out, kcounts, n_blocks

    # ---- hashed open-addressing mode ----
    # the caller's est_slots carries ~2x probing headroom over the
    # optimizer's group estimate, so only an estimate beyond 2x the cap
    # means the group population itself cannot fit the VMEM table; a
    # merely pessimistic estimate is clamped and the runtime __collision
    # probe below stays the ground truth
    if est_slots > 2 * KERNEL_HASH_MAX_SLOTS:
        declined("AggGroupCardinality")
        return None
    if not grid:
        state = ops.agg_init(num_slots := min(max(int(est_slots), 1024),
                                              KERNEL_HASH_MAX_SLOTS),
                             specs, key_names, key_dtypes)
        out = ops.agg_finalize(state, specs, key_names, key_dicts,
                               key_lazy)
        meter_kernel_run(runtime_stats, 0, n_staged, dma)
        return out, jnp.zeros(1 + n_steps, dtype=jnp.int64), 0
    max_block = max(b for b, _lo, _hi in grid)
    flat_arrays = gather_encoded_arrays(
        cached, colmap, names, (max_block + 1) * br, cache)
    bidx = jnp.asarray([b for b, _, _ in grid], dtype=jnp.int32)
    lo = jnp.asarray([l for _, l, _ in grid], dtype=jnp.int32)
    hi = jnp.asarray([h for _, _, h in grid], dtype=jnp.int32)
    # mirror the XLA hash path's collision discipline (doubling + fresh
    # salt per attempt), bounded by the VMEM slot cap instead of the
    # retry budget: past the cap the shape genuinely doesn't fit and the
    # XLA chain — which can keep doubling in HBM — takes over
    num_slots = min(max(int(est_slots), 1024), KERNEL_HASH_MAX_SLOTS)
    salt = 0
    while True:
        reserve = state_bytes(num_slots, key_names, specs)
        if not pool.try_reserve(reserve):
            declined("AggGroupCardinality")
            return None
        try:
            key = ("pallas_hash", num_slots, salt, tuple(key_names),
                   tuple(str(d) for d in key_dtypes), len(params), dma,
                   jplan.sig)
            hit = cache.get(key)
            if hit is None:
                hit = build_hash_runner(
                    chain, kinds, len(params), specs=specs,
                    key_names=key_names, key_dtypes=key_dtypes,
                    num_slots=num_slots, salt=salt, agg_exprs=agg_exprs,
                    lowering=lowering, dma=dma,
                    join_plan=jplan if jplan.steps else None)
                cache[key] = hit
            run, entry_names = hit
            if not reserve_build_operands(pool, jplan.nbytes):
                declined("JoinBuildSize")
                return None
            try:
                outs = run(bidx, lo, hi, flat_arrays, jplan.arrays,
                           params)
            finally:
                if jplan.nbytes:
                    pool.free(jplan.nbytes)
            state = {}
            for name, v in zip(entry_names, outs[:-1]):
                state[name] = v[0] if name == "__collision" else v
            if not bool(jax.device_get(state["__collision"])):  # lint: allow-host-sync
                out = ops.agg_finalize(state, specs, key_names,
                                       key_dicts, key_lazy)
                meter_kernel_run(runtime_stats, len(grid), n_staged, dma)
                return out, outs[-1][0], len(grid)
        finally:
            pool.free(reserve)
        if num_slots >= KERNEL_HASH_MAX_SLOTS:
            # probe overflow at the cap: the real group population
            # outgrew the VMEM-resident table
            declined("AggGroupCardinality")
            return None
        num_slots = min(2 * num_slots, KERNEL_HASH_MAX_SLOTS)
        salt += 1
