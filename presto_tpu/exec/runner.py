"""LocalQueryRunner: SQL string -> results, single process.

The analog of the reference LocalQueryRunner
(presto-main-base/.../testing/LocalQueryRunner.java:304): full
parse -> plan -> execute in one process with no HTTP, used for engine and
planner correctness tests and as the execution core the worker shell drives.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..common.block import block_to_values
from ..common.page import Page
from ..sql.planner import Planner
from .pipeline import (ExecutionConfig, PlanCompiler, TaskContext,
                       tuned_config)


@dataclass
class QueryResult:
    column_names: List[str]
    column_types: List
    rows: List[List]
    # per-query RuntimeStats map (§5.1; populated by the runners)
    runtime_stats: dict = None
    # statement-protocol side channel: PREPARE sets (name, text) so the
    # server can answer with X-Presto-Added-Prepare; DEALLOCATE the name
    added_prepare: tuple = None
    deallocated_prepare: str = None
    # device-profiler capture directory when the `profile` session
    # property was set (telemetry/profiler.py); None when not captured
    profile_trace_dir: Optional[str] = None

    def sorted_rows(self):
        return sorted(self.rows, key=lambda r: tuple(
            (v is None, str(type(v)), v) for v in r))


def plan_template_digest(template_sk) -> str:
    """Stable short digest of a parameterized plan's structural key — the
    join key between a query-history record ("planTemplate") and a later
    run of the same canonical plan (adaptive.history-sizing)."""
    import hashlib
    return hashlib.sha256(repr(template_sk).encode()).hexdigest()[:16]


_history_qid = itertools.count()


def pages_to_result(pages, names, types) -> "QueryResult":
    """Decode host pages into a QueryResult row list."""
    rows: List[List] = []
    for page in pages:
        cols = [block_to_values(t, b) for t, b in zip(types, page.blocks)]
        for i in range(page.position_count):
            rows.append([c[i] for c in cols])
    return QueryResult(names, types, rows)


@dataclass
class _Execution:
    """One checked-out canonical-cache execution: the optimized template,
    an exclusively-owned compiler, and how to give both back (insert on
    miss, checkin on hit) after a SUCCESSFUL run — a failed run may leave
    the compiler's memory pool / partial state poisoned, so nothing is
    returned to the cache."""
    output: object                      # optimized OutputNode template
    compiler: PlanCompiler
    key: str
    fresh: bool                         # miss: insert; hit: checkin
    slot_types: list


class LocalQueryRunner:
    def __init__(self, schema: str = "sf0.01",
                 config: Optional[ExecutionConfig] = None,
                 catalog: str = "tpch", tracer_provider=None,
                 plan_cache=None, history=None):
        from ..serving import GLOBAL_PLAN_CACHE
        self.schema = schema
        self.catalog = catalog
        self.tracer_provider = tracer_provider   # utils.runtime_stats
        self.config = config or tuned_config()
        # optional telemetry.history.QueryHistoryStore: successful runs
        # record template-keyed observations, and — when the
        # adaptive.history-sizing knob is on — a repeat of the same plan
        # template seeds its aggregation-table size from the record
        self.history = history
        self._last_template_digest: Optional[str] = None
        # canonical plan/executable cache (presto_tpu/serving): keyed by
        # catalog + schema + config fingerprint + the structural key of
        # the PARAMETERIZED pre-optimizer plan, so re-executions with
        # different literal constants reuse the optimized template and
        # the compiled pipeline (jitted steps stay warm).  Process-global
        # by default; tests pass their own PlanCache for isolation.
        self.plan_cache = plan_cache if plan_cache is not None \
            else GLOBAL_PLAN_CACHE
        # session-scoped prepared statements (name -> SQL text); the HTTP
        # path passes its header map per call instead
        self._prepared: Dict[str, str] = {}
        # EXPLAIN ANALYZE side channel: node id -> operator stats from the
        # most recent analyzed execution (bench / tooling read this)
        self.last_operator_stats: Optional[dict] = None

    def _validation(self):
        """Scope plan validation (presto_tpu/analysis) to this runner's
        configured mode for the duration of a planning call."""
        from ..analysis import use_validation_mode
        return use_validation_mode(self.config.plan_validation)

    def plan(self, sql: str):
        with self._validation():
            return Planner(default_schema=self.schema,
                           default_catalog=self.catalog).plan(sql)

    # -- canonical plan cache ---------------------------------------------

    def _checkout(self, ast, stats, bound_params=None,
                  record_fast=None) -> _Execution:
        """Plan `ast` to the parameterized template, then check the
        canonical cache: a hit skips optimize (and, when a pooled compiler
        is available, every compiled XLA step); a miss optimizes and
        builds a compiler.  Either way the returned compiler's context
        carries the execution's bound-parameter vector."""
        from ..serving import SERVING_METRICS
        from ..sql.canonical import cache_key_from_parts, parameterize
        from ..spi import plan as P
        with stats.record_wall("queryPlan"), self._validation():
            planner = Planner(default_schema=self.schema,
                              default_catalog=self.catalog,
                              bound_params=bound_params)
            unopt = planner.plan_query_unoptimized(ast)
        pp = parameterize(unopt)
        # structural key taken BEFORE optimization (the optimizer mutates
        # the template in place) — it must match what the prepared fast
        # path re-derives from its recorded template_key
        template_sk = P.structural_key(pp.template)
        self._last_template_digest = plan_template_digest(template_sk)
        # adaptive.history-sizing: the effective config may carry the
        # prior run's observed group count — a fingerprinted field, so
        # the cache key below re-keys on a changed hint
        cfg = self._history_sized_config()
        key = cache_key_from_parts(template_sk, cfg, self.catalog,
                                   self.schema)
        hit = self.plan_cache.checkout(key)
        if hit is not None:
            output, slot_types, compiler = hit
            if compiler is None:
                # pooled compilers all checked out by concurrent
                # executions: rebuild one from the cached template —
                # parse/plan/optimize were still skipped
                compiler = PlanCompiler(TaskContext(config=cfg))
                SERVING_METRICS.incr("executable_builds")
            exe = _Execution(output, compiler, key, False,
                             list(slot_types))
        else:
            with stats.record_wall("queryOptimize"), self._validation():
                output = Planner.optimize_output(pp.template)
            compiler = PlanCompiler(TaskContext(config=cfg))
            SERVING_METRICS.incr("executable_builds")
            exe = _Execution(output, compiler, key, True,
                             [s.type for s in pp.slots])
        if record_fast is not None and pp.origins_complete:
            from ..serving.prepared import FastPath
            record_fast(FastPath(
                template_sk,
                [(s.origin, s.type,
                  None if s.origin is not None else s.value)
                 for s in pp.slots]))
        self._bind(exe, [s.value for s in pp.slots])
        return exe

    def _bind(self, exe: _Execution, values) -> None:
        from ..sql.canonical import device_params
        if exe.slot_types:
            dev, host = device_params(values, exe.slot_types)
            exe.compiler.ctx.params = dev
            exe.compiler.ctx.params_fingerprint = host
        else:
            exe.compiler.ctx.params = None
            exe.compiler.ctx.params_fingerprint = None

    def _release(self, exe: _Execution) -> None:
        """Return the compiler to the cache after a successful run."""
        if exe.fresh:
            self.plan_cache.insert(exe.key, exe.output, exe.slot_types,
                                   exe.compiler)
        else:
            self.plan_cache.checkin(exe.key, exe.compiler)

    # -- history-based sizing (adaptive.history-sizing) -------------------

    def _history_record(self) -> Optional[dict]:
        if (self.history is None or self._last_template_digest is None
                or not self.config.adaptive_history_sizing):
            return None
        return self.history.find_by_template(self._last_template_digest)

    def _history_sized_config(self) -> ExecutionConfig:
        """A prior FINISHED run of the same plan template seeds the
        aggregation table size: the observed group count replaces the
        optimizer's estimate (exec/pipeline.py initial_slots)."""
        rec = self._history_record()
        groups = (rec or {}).get("aggGroups")
        if not groups:
            return self.config
        import dataclasses

        from .adaptive import ADAPTIVE_METRICS
        ADAPTIVE_METRICS.incr("history_sized_queries")
        return dataclasses.replace(self.config,
                                   history_agg_groups=int(groups))

    def _record_history(self, result: QueryResult, root,
                        subplan=None) -> None:
        """Record one template-keyed observation after a successful run.
        aggGroups is recorded only when the output chain is
        Output -> (Project|Sort)* -> grouped Aggregation, where the
        result row count IS the observed group count."""
        if self.history is None or self._last_template_digest is None:
            return
        from ..spi import plan as P
        node = getattr(root, "source", None)
        while isinstance(node, (P.ProjectNode, P.SortNode,
                                P.RemoteSourceNode)):
            if isinstance(node, P.RemoteSourceNode):
                # distributed: the chain continues in the (sole) child
                # fragment feeding this gather edge
                if subplan is None or len(node.source_fragment_ids) != 1:
                    break
                by_id = {c.fragment.fragment_id: c
                         for c in subplan.children}
                child = by_id.get(node.source_fragment_ids[0])
                if child is None:
                    break
                subplan, node = child, child.fragment.root
            else:
                node = node.source
        rec = {"queryId": f"run-{next(_history_qid)}",
               "state": "FINISHED",
               "planTemplate": self._last_template_digest,
               "rows": len(result.rows),
               "peakMemoryBytes": getattr(result, "peak_memory_bytes",
                                          0) or 0}
        if isinstance(node, P.AggregationNode) and node.grouping_keys:
            rec["aggGroups"] = len(result.rows)
        try:
            self.history.record(rec)
        except Exception:   # noqa: BLE001 — history is advisory
            pass

    # -- prepared statements ----------------------------------------------

    def _prepared_text(self, name: str, prepared) -> str:
        text = (prepared or {}).get(name) or self._prepared.get(name)
        if text is None:
            raise KeyError(f"prepared statement {name!r} does not exist")
        return text

    def _execute_prepared(self, ast, stats, prepared) -> _Execution:
        """EXECUTE name USING v1, ... -> a ready _Execution.  The fast
        path (statement seen before, all origins extracted) rebuilds the
        cache key from recorded slots and skips parse+plan entirely; any
        mismatch — unbindable value, NULL, cold cache — replans with the
        USING values bound into the planner."""
        from ..serving import PREPARED_REGISTRY, SERVING_METRICS
        from ..sql.canonical import (BindError, cache_key_from_parts,
                                     literal_value)
        text = self._prepared_text(ast.name, prepared)
        ps = PREPARED_REGISTRY.get_or_parse(text)
        if len(ast.values) != ps.param_count:
            raise ValueError(
                f"prepared statement {ast.name!r} expects "
                f"{ps.param_count} parameters, got {len(ast.values)}")
        fast = ps.fast
        if fast is not None:
            try:
                raw = [literal_value(v) for v in ast.values]
                values = fast.bind(raw)
            except BindError:
                values = None
            if values is not None:
                self._last_template_digest = \
                    plan_template_digest(fast.template_key)
                key = cache_key_from_parts(fast.template_key, self.config,
                                           self.catalog, self.schema)
                hit = self.plan_cache.checkout(key)
                if hit is not None:
                    output, slot_types, compiler = hit
                    if compiler is None:
                        compiler = PlanCompiler(
                            TaskContext(config=self.config))
                        SERVING_METRICS.incr("executable_builds")
                    exe = _Execution(output, compiler, key, False,
                                     list(slot_types))
                    self._bind(exe, values)
                    SERVING_METRICS.incr("prepared_fast_path")
                    return exe
        # full pipeline with the USING values bound into the planner;
        # record the fast path for the NEXT execution of this statement
        SERVING_METRICS.incr("prepared_replans")
        return self._checkout(ps.statement, stats,
                              bound_params=list(ast.values),
                              record_fast=ps.record_fast_path)

    # -- micro-batched execution ------------------------------------------

    def execute_prepared_batch(self, sqls: List[str], prepared=None
                               ) -> Optional[List[Optional[QueryResult]]]:
        """Execute N concurrent EXECUTE..USING statements that share one
        prepared template as ONE device launch (serving/batched.py).

        `prepared` is a name->text map, or a list of such maps aligned
        with `sqls` (the HTTP path carries per-request header maps).
        Returns a list aligned with `sqls` — QueryResult for every lane
        served by the batched drain, None for lanes the caller must run
        sequentially (bind errors, arity mismatches: their solo run
        raises the right per-query error) — or None when no batch was
        possible at all (cold template, ineligible plan shape, cache
        miss).  Every returned lane's rows are bit-identical to a solo
        run: the vmapped program replays the sequential fused path's
        exact update sequence per lane."""
        from ..serving import PREPARED_REGISTRY, SERVING_METRICS
        from ..serving.batched import batched_runner_for, disable_for
        from ..sql import parser as A
        from ..sql.canonical import (BindError, cache_key_from_parts,
                                     device_params, literal_value)
        if len(sqls) < 2:
            return None
        pmaps = (list(prepared) if isinstance(prepared, (list, tuple))
                 else [prepared] * len(sqls))
        text = None
        asts = []
        try:
            for s, pm in zip(sqls, pmaps):
                ast = A.parse_sql(s)
                if not isinstance(ast, A.ExecuteStmt):
                    return None
                t = self._prepared_text(ast.name, pm)
                if text is None:
                    text = t
                elif t != text:
                    return None     # mixed templates: not one batch
                asts.append(ast)
        except Exception:   # noqa: BLE001 — unknown name etc: sequential
            return None
        ps = PREPARED_REGISTRY.get_or_parse(text)
        fast = ps.fast
        if fast is None:
            return None             # cold: a solo run records the path
        values_by_lane: List[Optional[list]] = [None] * len(sqls)
        for i, ast in enumerate(asts):
            if len(ast.values) != ps.param_count:
                continue            # isolated arity error -> solo run
            try:
                raw = [literal_value(v) for v in ast.values]
                values_by_lane[i] = fast.bind(raw)
            except BindError:
                continue            # isolated bind error -> solo run
        lanes = [i for i, v in enumerate(values_by_lane) if v is not None]
        if len(lanes) < 2:
            return None
        key = cache_key_from_parts(fast.template_key, self.config,
                                   self.catalog, self.schema)
        hit = self.plan_cache.checkout(key)
        if hit is None:
            return None
        output, slot_types, compiler = hit
        if compiler is None:
            compiler = PlanCompiler(TaskContext(config=self.config))
            SERVING_METRICS.incr("executable_builds")
        exe = _Execution(output, compiler, key, False, list(slot_types))
        if not exe.slot_types:
            self.plan_cache.checkin(key, compiler)
            return None
        self._bind(exe, values_by_lane[lanes[0]])
        runner = batched_runner_for(compiler, output)
        if runner is None:
            self.plan_cache.checkin(key, compiler)
            return None
        dev_list = [device_params(values_by_lane[i], exe.slot_types)[0]
                    for i in lanes]
        try:
            pages, launch_ns, demux_ns = runner.run(dev_list)
        except Exception:   # noqa: BLE001 — whole drain failed: the
            # compiler may be poisoned (not returned to the pool) and the
            # template is pinned sequential; every lane re-runs solo
            disable_for(compiler)
            return None
        self._last_template_digest = plan_template_digest(
            fast.template_key)
        names = output.column_names
        types = [v.type for v in output.outputs]
        results: List[Optional[QueryResult]] = [None] * len(sqls)
        width = 1 << max(0, len(lanes) - 1).bit_length()
        for j, i in enumerate(lanes):
            res = pages_to_result([pages[j]], names, types)
            res.peak_memory_bytes = (compiler.ctx.memory.peak
                                     if compiler.ctx.memory is not None
                                     else 0)
            res.runtime_stats = {
                "servingBatchOccupancy": {"sum": len(lanes), "unit": "NONE"},
                "servingBatchLaunchNanos": {"sum": launch_ns,
                                            "unit": "NANO"},
            }
            results[i] = res
            SERVING_METRICS.incr("prepared_fast_path")
            self._record_history(res, output)
        self._release(exe)
        SERVING_METRICS.record_batch(len(lanes), demux_ns,
                                     padded_lanes=width - len(lanes))
        return results

    # -- execution --------------------------------------------------------

    def execute(self, sql: str, prepared: Optional[Dict[str, str]] = None
                ) -> QueryResult:
        from ..common.types import BOOLEAN
        from ..serving import PREPARED_REGISTRY
        from ..sql import parser as A
        from ..utils.runtime_stats import RuntimeStats
        stats = RuntimeStats()
        tracer = self.tracer_provider.new_tracer(sql) \
            if self.tracer_provider else None
        with stats.record_wall("queryParse"):
            ast = A.parse_sql(sql)
        if tracer:
            tracer.add_point("query parsed")
        if isinstance(ast, A.Explain):
            return self._explain(ast)
        if isinstance(ast, (A.CreateTableAs, A.InsertInto, A.DropTable)):
            return self._execute_ddl(ast)
        if isinstance(ast, A.Prepare):
            self._prepared[ast.name] = ast.text
            PREPARED_REGISTRY.get_or_parse(ast.text)   # warm the memo
            res = QueryResult(["result"], [BOOLEAN], [[True]])
            res.added_prepare = (ast.name, ast.text)
            return res
        if isinstance(ast, A.Deallocate):
            self._prepared.pop(ast.name, None)
            res = QueryResult(["result"], [BOOLEAN], [[True]])
            res.deallocated_prepare = ast.name
            return res
        if isinstance(ast, A.ExecuteStmt):
            exe = self._execute_prepared(ast, stats, prepared)
        else:
            exe = self._checkout(ast, stats)
        if tracer:
            tracer.add_point("query planned")
        output, compiler = exe.output, exe.compiler
        names = output.column_names
        types = [v.type for v in output.outputs]
        # operators add fine-grained counters (grouped bucket walls, ...)
        compiler.ctx.runtime_stats = stats
        from contextlib import nullcontext

        from ..telemetry import profile_capture
        with (tracer.span("query", sql=sql) if tracer else nullcontext()):
            with profile_capture(self.config.profile_dir, "query",
                                 enabled=self.config.profile) as trace_dir:
                with stats.record_wall("queryExecute"):
                    result = pages_to_result(
                        compiler.run_to_pages(output), names, types)
        result.profile_trace_dir = trace_dir
        result.runtime_stats = stats.to_dict()
        # peak MemoryPool reservation, for QueryCompletedEvent enrichment
        result.peak_memory_bytes = (compiler.ctx.memory.peak
                                    if compiler.ctx.memory is not None
                                    else 0)
        if tracer:
            tracer.end_trace("query finished")
        self._release(exe)
        self._record_history(result, output)
        return result

    def execute_streaming(self, sql: str,
                          prepared: Optional[Dict[str, str]] = None):
        """(columns-meta, row iterator) for a plain SELECT — pages are
        decoded and yielded as they are produced, so callers (the
        statement protocol) never hold the full result set (reference
        Query.java:116 streams from the root-stage ExchangeClient).
        Returns None for statements that need materialized execution
        (DDL / EXPLAIN / PREPARE / DEALLOCATE)."""
        from ..sql import parser as A
        from ..utils.runtime_stats import RuntimeStats
        stats = RuntimeStats()
        with stats.record_wall("queryParse"):
            ast = A.parse_sql(sql)
        if isinstance(ast, (A.Explain, A.CreateTableAs, A.InsertInto,
                            A.DropTable, A.Prepare, A.Deallocate)):
            return None
        if isinstance(ast, A.ExecuteStmt):
            exe = self._execute_prepared(ast, stats, prepared)
        else:
            exe = self._checkout(ast, stats)
        output, compiler = exe.output, exe.compiler
        names = output.column_names
        types = [v.type for v in output.outputs]
        compiler.ctx.runtime_stats = stats
        columns = [{"name": n, "type": str(t)}
                   for n, t in zip(names, types)]

        def rows():
            from ..common.block import block_to_values
            with stats.record_wall("queryExecute"):
                for page in compiler.run_to_pages(output):
                    cols = [block_to_values(t, b)
                            for t, b in zip(types, page.blocks)]
                    for i in range(page.position_count):
                        yield [c[i] for c in cols]
            # release only after a fully successful drain (mirrors execute)
            self._release(exe)
        return columns, rows(), stats

    def _execute_ddl(self, ast) -> QueryResult:
        """CREATE TABLE AS / INSERT INTO / DROP TABLE (reference
        DataDefinitionExecution + TableWriter/TableFinish plans; writes run
        through the normal pipeline compiler)."""
        from ..common.types import BIGINT
        from ..connectors import catalog as cat
        from ..sql import parser as A
        writable = [cid for cid in cat._CONNECTORS
                    if hasattr(cat.module(cid), "begin_write")]
        if isinstance(ast, A.DropTable):
            # droppable catalogs win the name lookup: a generated tpch
            # table of the same name must not shadow the stored one
            cid = next((c for c in writable
                        if ast.table in cat.module(c).SCHEMAS), None)
            if cid is None or not hasattr(cat.module(cid), "drop_table"):
                if ast.if_exists:
                    return QueryResult(["rows"], [BIGINT], [[0]])
                raise KeyError(f"unknown or non-droppable table "
                               f"{ast.table!r}")
            # cached plans may reference the dropped table
            self._invalidate_plans()
            cat.module(cid).drop_table(ast.table)
            return QueryResult(["rows"], [BIGINT], [[0]])
        if isinstance(ast, A.CreateTableAs) and ast.if_not_exists:
            # IF NOT EXISTS consults only writable catalogs: a read-only
            # generated table of the same name does not shadow the target
            if any(ast.table in cat.module(cid).SCHEMAS for cid in writable):
                return QueryResult(["rows"], [BIGINT], [[0]])
        with self._validation():
            output = Planner(default_schema=self.schema,
                             default_catalog=self.catalog).plan_write(ast)
        compiler = PlanCompiler(TaskContext(config=self.config))
        names = output.column_names
        types = [v.type for v in output.outputs]
        # writes invalidate any cached plans that scanned the target table
        self._invalidate_plans()
        return pages_to_result(compiler.run_to_pages(output), names, types)

    def _invalidate_plans(self) -> None:
        """DDL changed table contents: every cached plan/executable (and
        every recorded prepared fast path, whose template keys assume the
        old tables) may be stale."""
        from ..serving import FRAGMENT_JIT_CACHE, PREPARED_REGISTRY
        self.plan_cache.invalidate_all()
        PREPARED_REGISTRY.invalidate_fast_paths()
        FRAGMENT_JIT_CACHE.invalidate_all()

    def _explain(self, ast) -> QueryResult:
        """EXPLAIN: plan text.  EXPLAIN ANALYZE: execute with per-node
        instrumentation and annotate the plan (reference PlanPrinter /
        ExplainAnalyzeOperator).  EXPLAIN (TYPE VALIDATE): run the plan
        checker at every stage and print the diagnostic list."""
        from ..common.types import VarcharType
        from ..sql.explain import format_analyze_footer, format_plan
        from ..utils.runtime_stats import RuntimeStats
        if ast.explain_type == "VALIDATE":
            return self._explain_validate(ast)
        with self._validation():
            output = Planner(default_schema=self.schema,
                             default_catalog=self.catalog) \
                .plan_query_to_output(ast.query)
        stats = rstats = None
        trace_dir = None
        if ast.analyze:
            # fusion stays ENABLED: the fused chain emits device-side row
            # counters as extra jit outputs, so this profiles the real
            # execution path.  analyze_unfused retains the old per-node
            # interpreted profiling.
            from ..telemetry import profile_capture
            stats = {}
            rstats = RuntimeStats()
            ctx = TaskContext(config=self.config, stats=stats,
                              runtime_stats=rstats)
            compiler = PlanCompiler(ctx)
            # local EXPLAIN ANALYZE runs single-driver on this thread:
            # sample thread CPU at the same driver boundary the
            # scheduler/worker paths use so the footer's CPU-vs-wall
            # line is populated here too
            import time as _t
            t0 = _t.perf_counter()  # lint: allow-wall-clock
            c0 = _t.thread_time()
            with profile_capture(self.config.profile_dir, "analyze",
                                 enabled=self.config.profile) as trace_dir:
                with rstats.record_wall("queryExecute"):
                    for _page in compiler.run_to_pages(output):
                        pass
            rstats.add("driverCpuNanos",
                       (_t.thread_time() - c0) * 1e9, "NANO")
            rstats.add("driverWallNanos",
                       (_t.perf_counter() - t0) * 1e9, "NANO")  # lint: allow-wall-clock
            self.last_operator_stats = stats
        text = format_plan(output, stats)
        if rstats is not None:
            footer = format_analyze_footer(rstats, profile_dir=trace_dir)
            if footer:
                text += "\n\n" + footer
        return QueryResult(["Query Plan"], [VarcharType(max(1, len(text)))],
                           [[text]])

    def _fragmenter_config(self):
        from ..sql.fragmenter import FragmenterConfig
        return FragmenterConfig()

    def _explain_validate(self, ast) -> QueryResult:
        """EXPLAIN (TYPE VALIDATE): run every checker stage (post-plan,
        post-optimize, post-fragment) with fail-fast raising DISABLED so
        the full diagnostic list is reported instead of the first error —
        the debugging surface for a plan the validator rejects."""
        from ..analysis import (VALIDATION_OFF, check_plan, check_subplan,
                                use_validation_mode)
        from ..common.types import VarcharType
        from ..sql.explain import format_validation
        from ..sql.fragmenter import plan_distributed
        from ..sql.optimizer import optimize
        from ..spi import plan as P
        planner = Planner(default_schema=self.schema,
                          default_catalog=self.catalog)
        with use_validation_mode(VALIDATION_OFF):
            node, names, out_vars = planner.plan_query_any(ast.query)
            out = P.OutputNode(planner.new_id("output"), node, names,
                               out_vars)
            sections = [("post-plan", check_plan(out, "post-plan"))]
            out = optimize(out)
            sections.append(("post-optimize",
                             check_plan(out, "post-optimize")))
            # scan-pushdown decisions: collected BEFORE fragmentation
            # (plan_distributed moves the scans into fragment subplans,
            # mutating this tree); appended OUTSIDE format_validation so
            # informational entries don't count as diagnostics
            seen, decisions = set(), []
            for n in P.walk_plan(out):
                if id(n) in seen or not isinstance(n, P.TableScanNode):
                    continue
                seen.add(id(n))
                tname = f"{n.table.connector_id}.{n.table.table_name}"
                if getattr(n, "pushdown", None):
                    for e in n.pushdown:
                        decisions.append(
                            f"  {tname} [{n.id}]: "
                            f"{e['column']} {e['op']} {e['value']}")
                else:
                    decisions.append(f"  {tname} [{n.id}]: (no pushdown)")
            subplan = plan_distributed(out, self._fragmenter_config())
            from ..parallel.mesh import mesh_size
            from ..sql.fragmenter import annotate_exchange_fabrics
            annotate_exchange_fabrics(
                subplan, exec_config=self.config,
                mesh_size=mesh_size(getattr(self, "mesh", None)),
                batch_mode=getattr(self, "_batch_mode", False))
            sections.append(("post-fragment",
                             check_subplan(subplan, "post-fragment",
                                           exec_config=self.config)))
        text = format_validation(sections)
        text += "\n\n== scan-pushdown ==\n" + "\n".join(
            decisions if decisions else ["  (no table scans)"])
        return QueryResult(["Query Plan"], [VarcharType(max(1, len(text)))],
                           [[text]])

    def execute_reference(self, sql: str) -> QueryResult:
        """Same query through the numpy reference interpreter (the oracle).

        Per-node {rows, wall_s, batches} land in
        `last_reference_operator_stats` keyed by plan-node id, so
        differential tests can diff the stats surface against the
        engine's EXPLAIN ANALYZE / QueryInfo counters too."""
        from .reference import execute_reference
        output = self.plan(sql)
        stats: dict = {}
        rows = execute_reference(output, stats=stats)
        self.last_reference_operator_stats = stats
        types = [v.type for v in output.outputs]
        return QueryResult(output.column_names, types, rows)

    def assert_same_as_reference(self, sql: str, ordered: bool = False):
        got = self.execute(sql)
        exp = self.execute_reference(sql)
        _assert_rows_equal(got, exp, ordered)
        return got


class DistributedQueryRunner(LocalQueryRunner):
    """Plans with exchange insertion + fragmentation and executes the fragment
    DAG as multi-task stages through the in-process scheduler — the analog of
    the reference DistributedQueryRunner (presto-tests/.../DistributedQueryRunner.java:108)
    with in-process "workers"."""

    def __init__(self, schema: str = "sf0.01",
                 config: Optional[ExecutionConfig] = None,
                 n_tasks: int = 2, broadcast_threshold: int = 600_000,
                 catalog: str = "tpch", mesh=None, tracer_provider=None,
                 history=None):
        super().__init__(schema, config, catalog,
                         tracer_provider=tracer_provider, history=history)
        self.n_tasks = n_tasks
        self.broadcast_threshold = broadcast_threshold
        # jax.sharding.Mesh: hashed exchanges between stages whose task
        # count equals the mesh size run as ICI all_to_all collectives
        self.mesh = mesh
        # history-seeded hash-stage task count for the CURRENT query
        # (adaptive.history-sizing); None means use n_tasks
        self._history_tasks: Optional[int] = None

    # materialized exchanges can't stay device-resident; overridden by
    # BatchQueryRunner so fabric resolution demotes its edges to http
    _batch_mode = False

    def _annotate_fabrics(self, subplan):
        """Resolve and stamp each remote-exchange edge's fabric on the
        fragment output schemes (sql/fragmenter.annotate_exchange_fabrics)
        so EXPLAIN / EXPLAIN (TYPE VALIDATE) show the same choice the
        scheduler will make at runtime."""
        from ..parallel.mesh import mesh_size
        from ..sql.fragmenter import annotate_exchange_fabrics
        return annotate_exchange_fabrics(
            subplan, exec_config=self.config,
            mesh_size=mesh_size(self.mesh),
            batch_mode=self._batch_mode)

    def plan_subplan(self, sql: str, ast=None):
        from ..sql.fragmenter import plan_distributed
        with self._validation():
            if ast is not None:
                output = Planner(default_schema=self.schema,
                                 default_catalog=self.catalog) \
                    .plan_query_to_output(ast)
            else:
                output = self.plan(sql)
            names = output.column_names
            types = [v.type for v in output.outputs]
            subplan = plan_distributed(output, self._fragmenter_config(),
                                       exec_config=self.config)
            self._annotate_fabrics(subplan)
        return subplan, names, types

    def _fragmenter_config(self):
        from ..sql.fragmenter import FragmenterConfig
        return FragmenterConfig(
            broadcast_threshold=self.broadcast_threshold)

    def _explain_distributed(self, ast, sql: str = "") -> QueryResult:
        """EXPLAIN over the fragmented (distributed) plan — the analog of
        the reference's EXPLAIN (TYPE DISTRIBUTED).  ANALYZE executes the
        fragment DAG through the in-process scheduler with per-task
        operator stats enabled and annotates every fragment from the
        merged (task-rolled-up) map."""
        from ..common.types import VarcharType
        from ..sql.explain import format_analyze_footer, format_subplan
        from ..sql.fragmenter import plan_distributed
        if ast.explain_type == "VALIDATE":
            return self._explain_validate(ast)
        with self._validation():
            output = Planner(default_schema=self.schema,
                             default_catalog=self.catalog) \
                .plan_query_to_output(ast.query)
            subplan = plan_distributed(output, self._fragmenter_config(),
                                       exec_config=self.config)
            self._annotate_fabrics(subplan)
        stats = None
        footer = ""
        if ast.analyze:
            from contextlib import nullcontext

            from ..telemetry import profile_capture
            from .scheduler import InProcessScheduler
            sched = InProcessScheduler(self._scheduler_config())
            sched.node_stats = stats = {}
            # ANALYZE collects per-node stats, so the scheduler can also
            # emit the full query->fragment->task->operator span hierarchy
            tracer = self.tracer_provider.new_tracer(sql) \
                if (self.tracer_provider and sql) else None
            if tracer is not None:
                sched.tracer = tracer
            with (tracer.span("query", sql=sql) if tracer
                  else nullcontext()):
                with profile_capture(self.config.profile_dir, "analyze",
                                     enabled=self.config.profile) \
                        as trace_dir:
                    for _page in sched.execute(subplan):
                        pass
            if tracer:
                tracer.end_trace("query finished")
            self.last_operator_stats = stats
            footer = format_analyze_footer(sched.stats,
                                           profile_dir=trace_dir)
        text = format_subplan(subplan, stats)
        if footer:
            text += "\n\n" + footer
        return QueryResult(["Query Plan"], [VarcharType(max(1, len(text)))],
                           [[text]])

    def execute(self, sql: str) -> QueryResult:
        from ..sql import parser as A
        ast = A.parse_sql(sql)
        if isinstance(ast, A.Explain):
            return self._explain_distributed(ast, sql=sql)
        if isinstance(ast, (A.CreateTableAs, A.InsertInto, A.DropTable)):
            # writes run single-task through the local pipeline (the
            # reference's scaled-writer distribution is future work)
            return self._execute_ddl(ast)
        from contextlib import nullcontext

        from ..telemetry import profile_capture
        from .scheduler import InProcessScheduler
        restore = self._apply_history_sizing(ast)
        try:
            subplan, names, types = self.plan_subplan(sql, ast=ast)
            sched = InProcessScheduler(self._scheduler_config())
            tracer = self.tracer_provider.new_tracer(sql) \
                if self.tracer_provider else None
            if tracer is not None:
                sched.tracer = tracer
            with (tracer.span("query", sql=sql) if tracer
                  else nullcontext()):
                with profile_capture(self.config.profile_dir, "query",
                                     enabled=self.config.profile) \
                        as trace_dir:
                    result = pages_to_result(sched.execute(subplan),
                                             names, types)
        finally:
            restore()
        result.profile_trace_dir = trace_dir
        # fabric-tagged exchange stats (bytes / walls per fabric) collected
        # while the result drained
        result.runtime_stats = sched.stats.to_dict()
        # query-level context peak (all tasks' reservations bubbled up)
        result.peak_memory_bytes = (sched.memory.peak
                                    if sched.memory is not None else 0)
        if tracer:
            tracer.end_trace("query finished")
        self._record_history(result, subplan.fragment.root, subplan=subplan)
        return result

    def _apply_history_sizing(self, ast):
        """adaptive.history-sizing (distributed): parameterize the plan
        to its template digest; when a prior FINISHED run matches, seed
        the aggregation-table hint (config, consumed by every task's
        compiler) and the hash-stage task count from what that run
        observed.  Returns a restore callback for the per-query state."""
        self._last_template_digest = None
        if self.history is None:
            return lambda: None
        from ..spi import plan as P
        from ..sql.canonical import parameterize
        try:
            with self._validation():
                unopt = Planner(default_schema=self.schema,
                                default_catalog=self.catalog) \
                    .plan_query_unoptimized(ast)
            self._last_template_digest = plan_template_digest(
                P.structural_key(parameterize(unopt).template))
        except Exception:   # noqa: BLE001 — sizing is advisory
            return lambda: None
        rec = self._history_record()
        if rec is None:
            return lambda: None
        import dataclasses

        from .adaptive import ADAPTIVE_METRICS
        saved_cfg, saved_tasks = self.config, self._history_tasks
        changed = False
        groups = rec.get("aggGroups")
        if groups:
            self.config = dataclasses.replace(
                self.config, history_agg_groups=int(groups))
            changed = True
        rows = rec.get("rows")
        if rows is not None:
            # one hash task per ~500k observed output rows: a repeat of
            # a small query skips the fan-out cost the planned
            # parallelism assumed (never raised above n_tasks)
            seeded = max(1, min(self.n_tasks, -(-int(rows) // 500_000)))
            if seeded != self.n_tasks:
                self._history_tasks = seeded
                changed = True
        if changed:
            ADAPTIVE_METRICS.incr("history_sized_queries")

        def restore():
            self.config, self._history_tasks = saved_cfg, saved_tasks
        return restore

    def _scheduler_config(self):
        from .scheduler import SchedulerConfig
        return SchedulerConfig(
            exec_config=self.config, source_tasks=self.n_tasks,
            hash_tasks=self._history_tasks or self.n_tasks,
            mesh=self.mesh,
            broadcast_threshold=self.broadcast_threshold)


class BatchQueryRunner(DistributedQueryRunner):
    """Batch-mode execution — the Presto-on-Spark analog (SURVEY.md §2.7:
    PrestoSparkRunner.java:55 / PrestoSparkQueryExecutionFactory.java:164).
    The same fragment DAG runs stage-by-stage with every inter-stage
    exchange MATERIALIZED to local shuffle files (the Spark-shuffle /
    presto_cpp ShuffleWrite analog) and per-task retry from those durable
    inputs — batch fault tolerance instead of fail-fast MPP."""

    _batch_mode = True

    def __init__(self, schema: str = "sf0.01", config=None,
                 n_tasks: int = 2, catalog: str = "tpch",
                 task_retries: int = 2, temp_dir=None,
                 fault_injector=None):
        super().__init__(schema, config, n_tasks=n_tasks, catalog=catalog)
        self.task_retries = task_retries
        self.temp_dir = temp_dir
        self.fault_injector = fault_injector

    def _scheduler_config(self):
        cfg = super()._scheduler_config()
        cfg.batch_mode = True
        cfg.task_retries = self.task_retries
        cfg.temp_dir = self.temp_dir
        cfg.fault_injector = self.fault_injector
        return cfg


def _assert_rows_equal(got: QueryResult, exp: QueryResult, ordered: bool):
    g = got.rows if ordered else got.sorted_rows()
    e = exp.rows if ordered else exp.sorted_rows()
    if len(g) != len(e):
        raise AssertionError(
            f"row count mismatch: engine {len(g)} vs reference {len(e)}\n"
            f"engine head: {g[:5]}\nreference head: {e[:5]}")
    for i, (rg, re_) in enumerate(zip(g, e)):
        if len(rg) != len(re_):
            raise AssertionError(f"column count mismatch at row {i}")
        for j, (a, b) in enumerate(zip(rg, re_)):
            if not _value_eq(a, b):
                raise AssertionError(
                    f"value mismatch at row {i} col {j} "
                    f"({got.column_names[j]}): engine {a!r} vs reference {b!r}\n"
                    f"engine row: {rg}\nreference row: {re_}")


def _value_eq(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if fa == fb:
            return True
        denom = max(abs(fa), abs(fb), 1e-30)
        return abs(fa - fb) / denom < 1e-9
    return a == b
