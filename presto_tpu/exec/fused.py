"""Whole-pipeline fusion of scan -> filter/project -> join chain -> (agg).

Round-1 fused only scan->filter/project->direct-agg (TPC-H Q1/Q6 shape);
join-heavy queries streamed probe batches with a host sync per batch, which
dominated wall-clock (per-sync cost ~0.1-1s on a remote device).  This module
generalizes fusion to probe-side JOIN CHAINS so an entire pipeline compiles
into ONE XLA program with a fori_loop over scan chunks — the TPU analog of the
reference Driver streaming pages through an operator chain with zero host
round-trips (presto-main-base/.../operator/Driver.java:421-451).

The enabling observation: TPC-H/DS probe joins are FK->PK.  When the build
side's keys are UNIQUE (checked once on the host after the build side is
materialized), a probe is fanout<=1: the join never expands rows, so the
chunk capacity is preserved through the whole chain, no overflow machinery is
needed in-loop, and a join step reduces to "lookup + gather build columns +
mask update".  Two lookup structures:

  * DirectTable — dense integer PK (orderkey/custkey/partkey/...): a direct-
    address array keyed by (key - base).  Probe is ONE int32 gather — no
    hashing, no searchsorted.  The TPU-native analog of the reference's
    LookupJoinOperator fast path for integer keys.
  * the hash-sorted ops.BuildTable — multi-column or sparse keys; probe is
    one searchsorted (fanout-1 variant of ops.probe_join).

Build sides are materialized BEFORE the loop compiles (they are plan
subtrees, usually small dims); rows with NULL keys are excluded from the
build and NULL probe keys never match, per SQL equi-join semantics (the
numpy oracle exec/reference.py:438-449 is the fixture for this).

Semi joins (IN/EXISTS markers) fuse the same way; duplicate build keys are
harmless there (the marker is existence), so semi steps never force a
fallback.

Under scan.kernel = pallas (or auto on TPU), eligible fanout-1
INNER/LEFT and semi probe steps lower further: kernels/join.py rebuilds
the probe math inside the Pallas scan kernel body, with the DirectTable
/ BuildTable operands resident across the launch, so the chain runs
decode -> filter -> probe -> compact -> agg without the XLA chain's
per-step probe pages.  This module stays the planner, the build-side
materializer, and the fallback executor for everything the kernel
declines.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..spi import plan as P
from .batch import Batch, Column
from . import operators as ops

# absolute cap on a direct-address table (entries), and the max ratio of
# key span to build rows before falling back to the hash table
DIRECT_TABLE_MAX = 1 << 26
DIRECT_TABLE_SPAN_RATIO = 8
# largest per-join fanout the in-loop expansion handles, and the largest
# combined expansion across a chain (chunk capacity is divided by it)
MAX_EXPAND = 64
MAX_EXPAND_PRODUCT = 256


@dataclass
class DirectTable:
    """Direct-address build table for a dense integer key."""
    slots: jnp.ndarray                # int32 build-row index, -1 = absent
    base: jnp.ndarray                 # scalar int64: smallest key
    columns: Dict[str, Column]        # original build columns

    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        return ((self.slots, self.base,
                 tuple(self.columns[n] for n in names)), names)

    @classmethod
    def tree_unflatten(cls, names, children):
        slots, base, cols = children
        return cls(slots, base, dict(zip(names, cols)))


jax.tree_util.register_pytree_node_class(DirectTable)


@lru_cache(maxsize=None)
def _direct_builder(size: int):
    @jax.jit
    def build(values, mask, base):
        k = jnp.where(mask, values.astype(jnp.int64) - base, size)
        k = jnp.clip(k, 0, size).astype(jnp.int32)   # size = drop slot
        rows = jnp.arange(values.shape[0], dtype=jnp.int32)
        slots = jnp.full(size, -1, jnp.int32).at[k].set(
            rows, mode="drop")
        counts = jnp.zeros(size, jnp.int32).at[k].add(
            mask.astype(jnp.int32), mode="drop")
        return slots, jnp.any(counts > 1)
    return build


@jax.jit
def _key_stats(values, mask):
    """(min, max, live count) of a key column over live rows."""
    v = values.astype(jnp.int64)
    vmin = jnp.min(jnp.where(mask, v, jnp.iinfo(jnp.int64).max))
    vmax = jnp.max(jnp.where(mask, v, jnp.iinfo(jnp.int64).min))
    return vmin, vmax, jnp.sum(mask)


@jax.jit
def _max_run(table: ops.BuildTable):
    """Largest live-key duplicate run (the join's max fanout; padding runs
    excluded)."""
    n = table.run_len.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    return jnp.max(jnp.where(pos < table.valid_count, table.run_len, 0))


def _build_has_null_key(batch: Batch, key_names: Tuple[str, ...]) -> bool:
    """Whether any live build row has a NULL key — needed for the semi-join
    marker's three-valued output (x IN (...NULL...) is UNKNOWN on a miss)."""
    m = jnp.zeros((), dtype=bool)
    for k in key_names:
        c = batch.columns[k]
        if c.nulls is not None:
            m = m | jnp.any(batch.mask & c.nulls)
    return bool(jax.device_get(m))  # lint: allow-host-sync


def _drop_null_keys(batch: Batch, key_names: Tuple[str, ...]) -> Batch:
    """Exclude build rows with NULL keys (SQL equi-join: NULL never
    matches).  Runs eagerly — a handful of elementwise ops, once per build."""
    m = batch.mask
    for k in key_names:
        c = batch.columns[k]
        if c.nulls is not None:
            m = m & ~c.nulls
    return batch.with_mask(m)


def probe_direct(batch: Batch, dt: DirectTable, key_name: str):
    """(hit, build_row_index) for a direct-address lookup (shared slot
    math: ops.direct_lookup)."""
    return ops.direct_lookup(batch, dt, key_name)


def probe_unique(batch: Batch, table: ops.BuildTable,
                 key_names: Tuple[str, ...]):
    """(hit, build_row_index) against a hash-sorted unique-key build."""
    cols = [batch.columns[k] for k in key_names]
    kh = ops._orderable_hash(ops.hash_columns(cols))
    nb = table.perm.shape[0]
    lo = jnp.clip(jnp.searchsorted(table.keyhash_sorted, kh, side="left",
                                   method="scan_unrolled")
                  .astype(jnp.int32), 0, nb - 1)
    hit = table.keyhash_sorted[lo] == kh
    for c in cols:
        if c.nulls is not None:
            hit = hit & ~c.nulls
    return hit, jnp.where(hit, table.perm[lo], 0)


class FusedChain:
    """A compile-time description of a fusible probe pipeline.

    steps (leaf->root order):
      ("filter", predicate)
      ("project", [(variable, expr), ...])
      ("rename", [(out_name, in_name), ...])
      ("join", JoinNode)         aux entry: DirectTable | BuildTable
      ("semi", SemiJoinNode)     aux entry: DirectTable | BuildTable

    prep() (runtime) returns (aux, expands): per-join lookup tables plus
    static per-join fanout factors.  A join whose build keys repeat up to
    k times expands each probe row into k candidate slots IN-LOOP; the
    chunk capacity is divided by the product of factors so the in-flight
    batch footprint stays at the configured batch size.
    """

    def __init__(self, compiler, steps: List[tuple], scan_meta: dict,
                 step_ids: Optional[List[str]] = None,
                 scan_id: Optional[str] = None):
        self.compiler = compiler
        self.steps = steps
        self.scan_meta = scan_meta
        # plan-node ids for EXPLAIN ANALYZE row counters: node_ids[0] is
        # the scan, node_ids[i+1] the node step i came from (None when the
        # chain was assembled without id tracking, e.g. in older tests)
        self.step_ids = step_ids or [None] * len(steps)
        self.scan_id = scan_id
        self.node_ids = [scan_id] + list(self.step_ids)
        self.cap = scan_meta["cap"]
        # parameterized probe expressions ride the traced aux pytree (last
        # element) so re-executions with different bound constants reuse
        # the compiled program; parameterized BUILD subtrees and pushdown
        # markers instead force per-execution refresh of cached prep/chunk
        # state (see fused_stream / run_fused)
        from .lowering import expr_has_params
        self.has_params = any(
            (s[0] == "filter" and expr_has_params(s[1]))
            or (s[0] == "project"
                and any(expr_has_params(e) for _v, e in s[1]))
            for s in steps)
        self.build_params = any(
            '"@type": "parameter"' in P.structural_key(
                s[1].right if s[0] == "join" else s[1].filtering_source)
            for s in steps if s[0] in ("join", "semi"))
        self.params_pushdown = any(
            isinstance(e.get("value"), (list, tuple))
            for e in scan_meta.get("pushdown") or ())
        self.chunks = self.chunks_for((1,) * sum(
            1 for s in steps if s[0] in ("join", "semi")))
        self.total_rows = sum(n for _, n in self.chunks)
        self._leaf_make: Dict[int, Callable] = {}

    def chunks_for(self, expands: Tuple[int, ...],
                   meter: bool = False) -> List[Tuple[int, int]]:
        kprod = 1
        for k in expands:
            kprod *= k
        cap = max(1 << 12, self.cap // kprod)
        chunks = []
        for split in self.scan_meta["splits"]:
            p = split.start
            while p < split.end:
                chunks.append((p, min(cap, split.end - p)))
                p += cap
        zm = self.scan_meta.get("zone_maps")
        pd = self.scan_meta.get("pushdown")
        if zm and pd:
            # zone-map chunk skipping: host numpy over build-time stats.
            # For plan constants the pruned list is DETERMINISTIC per
            # compiled plan; ["param", i] marker entries resolve against
            # the CURRENT execution's parameter fingerprint, so consumers
            # that bake chunk counts into cached programs must recompute
            # this list per execution when self.params_pushdown is set
            from ..storage import prune_chunks
            dyn = self.scan_meta.get("dyn_summaries")
            detail: dict = {}
            chunks, _skipped = prune_chunks(
                chunks, zm, pd, self.compiler.ctx.params_fingerprint,
                dyn() if dyn is not None else None, detail=detail)
            if meter and detail.get("dyn_engaged"):
                # fused chains never reach the streaming scan's row-level
                # runtime filter, so chunk pruning IS the application
                # here — meter it once per execution (callers pass
                # meter=True only on their final pre-drain recompute)
                from .adaptive import ADAPTIVE_METRICS
                ADAPTIVE_METRICS.incr("filters_applied")
                ADAPTIVE_METRICS.incr("filter_rows_in", detail["rows_in"])
                ADAPTIVE_METRICS.incr("filter_rows_pruned",
                                      detail["dyn_rows_pruned"])
                ADAPTIVE_METRICS.incr("filter_chunks_skipped",
                                      detail["dyn_chunks_pruned"])
                rs = self.compiler.ctx.runtime_stats
                if rs is not None:
                    rs.add("dynamicFilterRowsIn", detail["rows_in"])
                    rs.add("dynamicFilterRowsPruned",
                           detail["dyn_rows_pruned"])
        return chunks

    def leaf_cap(self, expands: Tuple[int, ...]) -> int:
        kprod = 1
        for k in expands:
            kprod *= k
        return max(1 << 12, self.cap // kprod)

    # -- runtime: materialize build sides ---------------------------------
    def prep(self, defer: Optional[Callable] = None
             ) -> Optional[Tuple[tuple, Tuple[int, ...], List[tuple]]]:
        """Materialize every build side and construct lookup tables.
        Returns (aux, expands, deferred), or None when a join's fanout
        exceeds the expansion limits (caller falls back to the streaming
        executor).  defer(step_index, JoinNode) -> k (falsy = build here)
        reserves the join's aux slot instead of building it, with static
        fanout k baked into the shared program (grouped execution fills
        those slots per bucket lifespan: k == 1 means a unique-key direct
        table, k > 1 a hash-sorted table probed with k-way expansion);
        deferred lists (aux_index, step_index, JoinNode)."""
        # aux[0] carries the scan's HBM-cached whole-table columns as a
        # traced argument pytree (closure constants of this size would be
        # inlined as XLA literals); join/semi lookup tables follow
        aux: List = [self.scan_meta.get("cached_cols", {})]
        expands: List[int] = []
        deferred: List[tuple] = []
        for si, step in enumerate(self.steps):
            kind = step[0]
            if kind == "join":
                node = step[1]
                k_defer = defer(si, node) if defer is not None else 0
                if k_defer:
                    aux.append(None)
                    deferred.append((len(aux) - 1, si, node))
                    expands.append(int(k_defer))
                    continue
                res = self._build_for(
                    node.right, tuple(r.name for _l, r in node.criteria),
                    for_join=True)
                if res is None:
                    return None
                tbl, k, _ = res
                aux.append(tbl)
                expands.append(k)
            elif kind == "semi":
                node = step[1]
                fkey = node.filtering_source_join_variable.name
                tbl, _k, had_null = self._build_for(
                    node.filtering_source, (fkey,), for_join=False)
                # (table, build-had-null-key) — the flag rides the traced
                # aux pytree so the marker can go three-valued without a
                # retrace per data change
                aux.append((tbl, jnp.asarray(had_null)))
                expands.append(1)
        kprod = 1
        for k in expands:
            kprod *= k
        if kprod > MAX_EXPAND_PRODUCT:
            return None
        if self.has_params:
            # LAST so join/semi aux indexing (aux[ji + 1]) is unaffected;
            # traced, so a different parameter vector re-runs the same
            # compiled program instead of retracing
            aux.append(self.compiler.ctx.params)
        return tuple(aux), tuple(expands), deferred

    def _build_for(self, build_node: P.PlanNode, keys: Tuple[str, ...],
                   for_join: bool):
        return build_lookup(self.compiler, build_node, keys, for_join)

    def make(self, pos, valid, aux, expands: Tuple[int, ...],
             leaf_cap: int, with_counts: bool = False):
        """Apply the chain to one scan chunk.  With with_counts=True the
        return value is (Batch, int64[1+len(steps)]) where counts[0] is
        the scan's live rows and counts[i+1] the live rows after step i —
        the device-side OperatorStats row counters EXPLAIN ANALYZE reads
        (they ride the jitted program's outputs; no host syncs in-loop)."""
        meta = self.scan_meta
        mk = self._leaf_make.get(leaf_cap)
        if mk is None:
            mk = meta["make"] if leaf_cap == self.cap \
                else meta["make_factory"](leaf_cap)
            self._leaf_make[leaf_cap] = mk
        outs, live = mk(pos, valid, aux[0])
        dicts = meta["dicts"]
        batch = Batch({n: Column(v, None, dicts.get(n))
                       for n, v in outs.items()}, live)
        counts = [jnp.sum(live)] if with_counts else None
        low = self.compiler.lowering
        params = aux[-1] if self.has_params else None

        def _pb(b):
            # bound-parameter vector rides along for expression lowering
            # (Batch.params is not a pytree child, so every derived Batch
            # above dropped it)
            return b.with_params(params) if self.has_params else b
        ji = 0                      # join/semi ordinal; aux[0] = scan cache
        for step in self.steps:
            kind = step[0]
            if kind == "filter":
                batch = ops.apply_filter(batch,
                                         low.eval(step[1], _pb(batch)))
            elif kind == "project":
                pb = _pb(batch)
                batch = Batch({v.name: low.eval(e, pb)
                               for v, e in step[1]}, batch.mask)
            elif kind == "rename":
                batch = Batch({o: batch.columns[i] for o, i in step[1]},
                              batch.mask)
            elif kind == "join":
                if expands[ji] == 1:
                    batch = self._apply_join(batch, step[1], aux[ji + 1],
                                             low)
                else:
                    batch = self._apply_join_expand(
                        batch, step[1], aux[ji + 1], expands[ji], low)
                ji += 1
            elif kind == "uid":
                # position-keyed unique ids: chunk [pos, pos+leaf_cap)
                # owns id range [pos*K, (pos+leaf_cap)*K) where K is the
                # join expansion applied so far — disjoint across chunks
                # and splits, deterministic per (chain, splits), so a
                # deep-copied decorrelated subtree replays identical ids
                # (same contract as the streaming operator,
                # _compile_AssignUniqueIdNode)
                node = step[1]
                kprod = 1
                for j in range(ji):
                    kprod *= expands[j]
                cap_here = batch.mask.shape[0]
                leaf_c = cap_here // kprod
                base = self.compiler.ctx.task_index << 40
                # id keyed by (global leaf row, expansion branch): the
                # join-expand layout is slot = j*C + i, so slot s maps to
                # leaf row s % leaf_c and branch s // leaf_c — unique even
                # when a truncated chunk's live rows land in high branches
                s = jnp.arange(cap_here, dtype=jnp.int64)
                ids = (base
                       + (jnp.asarray(pos, dtype=jnp.int64) + s % leaf_c)
                       * kprod + s // leaf_c)
                batch = batch.with_columns(
                    {node.id_variable.name: Column(ids)})
            elif kind == "semi":
                node = step[1]
                key = node.source_join_variable.name
                tbl, bhn = aux[ji + 1]
                hit, _ = (probe_direct(batch, tbl, key)
                          if isinstance(tbl, DirectTable)
                          else probe_unique(batch, tbl, (key,)))
                # three-valued marker: NULL probe key, or miss against a
                # build side that contained NULL (reference
                # HashSemiJoinOperator semantics)
                nulls = ~hit & bhn
                pn = batch.columns[key].nulls
                if pn is not None:
                    nulls = nulls | pn
                batch = batch.with_columns(
                    {node.semi_join_output.name: Column(hit, nulls)})
                ji += 1
            if with_counts:
                counts.append(jnp.sum(batch.mask))
        if with_counts:
            return batch, jnp.stack(counts).astype(jnp.int64)
        return batch

    def _apply_join(self, batch: Batch, node: P.JoinNode, tbl, low) -> Batch:
        probe_keys = tuple(l.name for l, _r in node.criteria)
        if isinstance(tbl, DirectTable):
            hit, bidx = probe_direct(batch, tbl, probe_keys[0])
        else:
            hit, bidx = probe_unique(batch, tbl, probe_keys)
        build_names = {v.name for v in node.right.output_variables}
        out_names = [v.name for v in node.outputs]
        cols = dict(batch.columns)
        gcols = _join_build_cols(node, out_names, build_names)
        gathered = ops._packed_gather([tbl.columns[n] for n in gcols],
                                      bidx)
        for n in gcols:
            cols[n] = gathered[id(tbl.columns[n])]
        pairs = Batch(cols, batch.mask)
        matched = hit
        if node.filter is not None:
            pred = low.eval(node.filter, pairs)
            keep = pred.values.astype(bool)
            if pred.nulls is not None:
                keep = keep & ~pred.nulls
            matched = matched & keep
        if node.join_type == P.INNER:
            return Batch(cols, batch.mask & matched)
        # LEFT: keep every probe row; null-extend build columns on misses
        miss = ~matched
        for n in _join_build_cols(node, out_names, build_names):
            c = cols[n]
            cols[n] = Column(c.values, c.null_mask() | miss,
                             c.dictionary, c.lazy)
        return Batch(cols, batch.mask)

    def _apply_join_expand(self, batch: Batch, node: P.JoinNode,
                           tbl: ops.BuildTable, k: int, low) -> Batch:
        """Fanout-k join: each probe row expands into k candidate build
        slots (k = pow2-rounded max key run in the build).  Output capacity
        = k * input capacity; flat index j*C + i is (probe row i, match j)."""
        C = batch.capacity
        probe_keys = tuple(l.name for l, _r in node.criteria)
        pcols = [batch.columns[kk] for kk in probe_keys]
        kh = ops._orderable_hash(ops.hash_columns(pcols))
        nb = tbl.perm.shape[0]
        lo = jnp.clip(jnp.searchsorted(tbl.keyhash_sorted, kh, side="left",
                                       method="scan_unrolled")
                      .astype(jnp.int32), 0, nb - 1)
        hit = tbl.keyhash_sorted[lo] == kh
        for c in pcols:
            if c.nulls is not None:
                hit = hit & ~c.nulls
        cnt = jnp.where(hit & batch.mask, tbl.run_len[lo], 0)      # (C,)
        j = jnp.arange(k, dtype=jnp.int32)[:, None]                # (k,1)
        sub = j < cnt[None, :]                                     # (k,C)
        bpos = jnp.clip(lo[None, :] + j, 0, nb - 1)
        bidx = jnp.where(sub, tbl.perm[bpos], 0).reshape(k * C)

        build_names = {v.name for v in node.right.output_variables}
        out_names = [v.name for v in node.outputs]
        cols: Dict[str, Column] = {}
        for n, c in batch.columns.items():
            cols[n] = Column(jnp.tile(c.values, k),
                             None if c.nulls is None
                             else jnp.tile(c.nulls, k),
                             c.dictionary, c.lazy)
        gcols = _join_build_cols(node, out_names, build_names)
        gathered = ops._packed_gather([tbl.columns[n] for n in gcols],
                                      bidx)
        for n in gcols:
            cols[n] = gathered[id(tbl.columns[n])]
        pair_mask = (batch.mask[None, :] & sub).reshape(k * C)
        matched = pair_mask
        if node.filter is not None:
            pred = low.eval(node.filter, Batch(cols, pair_mask))
            keep = pred.values.astype(bool)
            if pred.nulls is not None:
                keep = keep & ~pred.nulls
            matched = matched & keep
        if node.join_type == P.INNER:
            return Batch(cols, matched)
        # LEFT: a probe row none of whose candidates survived emits one
        # null-extended row in its j==0 slot
        any_match = jnp.any(matched.reshape(k, C), axis=0)         # (C,)
        fill = jnp.where(jnp.arange(k, dtype=jnp.int32)[:, None] == 0,
                         (batch.mask & ~any_match)[None, :],
                         False).reshape(k * C)
        for n in _join_build_cols(node, out_names, build_names):
            c = cols[n]
            cols[n] = Column(c.values, c.null_mask() | fill,
                             c.dictionary, c.lazy)
        return Batch(cols, matched | fill)


def try_direct_table(batch: Batch, key: str,
                     allow_dup: bool) -> Optional[DirectTable]:
    """Direct-address table for a dense single integer key, or None when
    the key is non-integer / sparse / (for joins) duplicated.  Costs two
    small host fetches, once per build."""
    col = batch.columns[key]
    if col.values.dtype not in (jnp.int64, jnp.int32, jnp.int16):
        return None
    vmin, vmax, live = jax.device_get(_key_stats(col.values, batch.mask))  # lint: allow-host-sync
    span = int(vmax) - int(vmin) + 1
    if not (int(live) > 0 and span <= DIRECT_TABLE_MAX
            and span <= max(1024, DIRECT_TABLE_SPAN_RATIO * int(live))):
        return None
    size = 1 << (span - 1).bit_length()
    slots, dup = _direct_builder(size)(col.values, batch.mask,
                                       jnp.int64(int(vmin)))
    if not allow_dup and bool(jax.device_get(dup)):  # lint: allow-host-sync
        return None
    return DirectTable(slots, jnp.int64(int(vmin)), dict(batch.columns))


def build_lookup(compiler, build_node: P.PlanNode, keys: Tuple[str, ...],
                 for_join: bool):
    """Returns (table, fanout, build_had_null_key) — fanout is the
    pow2-rounded max key multiplicity (1 = unique keys) — or None when
    fanout > MAX_EXPAND.  The null flag is computed only for semi builds
    (for_join=False); join builds report False unconditionally (they drop
    NULL keys either way)."""
    batch = compiler._materialize_node(build_node, cache=True)
    if batch is None:
        batch = _empty_build_batch(build_node)
    # only semi-join markers need the null-key flag (three-valued
    # output); join builds skip the device round-trip it costs
    had_null = False if for_join else _build_has_null_key(batch, keys)
    batch = _drop_null_keys(batch, keys)
    if len(keys) == 1:
        dt = try_direct_table(batch, keys[0], allow_dup=not for_join)
        if dt is not None:
            return dt, 1, had_null
    from .pipeline import _jits
    table = _jits()[1](batch, keys)
    if not for_join:
        return table, 1, had_null
    kmax = int(jax.device_get(_max_run(table)))  # lint: allow-host-sync
    if kmax <= 1:
        return table, 1, False
    if kmax > MAX_EXPAND:
        return None
    return table, 1 << (kmax - 1).bit_length(), False


def assemble_chain(compiler, node: P.PlanNode) -> Optional[FusedChain]:
    """Walk a Filter/Project/Join/SemiJoin chain down to a device-generated
    TableScan.  Returns None when the plan shape is not fusible (the caller
    keeps the streaming path)."""
    steps: List[tuple] = []
    step_ids: List[str] = []
    nd = node
    while True:
        if isinstance(nd, P.FilterNode):
            steps.append(("filter", nd.predicate))
            step_ids.append(nd.id)
            nd = nd.source
        elif isinstance(nd, P.ProjectNode):
            steps.append(("project", list(nd.assignments.items())))
            step_ids.append(nd.id)
            nd = nd.source
        elif isinstance(nd, P.ExchangeNode) and not nd.inputs \
                and len(nd.exchange_sources) == 1:
            src = nd.exchange_sources[0]
            outer = [v.name for v in nd.partitioning_scheme.output_layout]
            inner = [v.name for v in src.output_variables]
            if outer != inner:
                steps.append(("rename", list(zip(outer, inner))))
                step_ids.append(nd.id)
            nd = src
        elif isinstance(nd, P.JoinNode) \
                and nd.join_type in (P.INNER, P.LEFT) and nd.criteria:
            steps.append(("join", nd))
            step_ids.append(nd.id)
            nd = nd.left
        elif isinstance(nd, P.SemiJoinNode):
            steps.append(("semi", nd))
            step_ids.append(nd.id)
            nd = nd.source
        elif isinstance(nd, P.AssignUniqueIdNode):
            # unique ids derive from the scan position (see make), so the
            # decorrelated EXISTS stacks (q21-class) stay in one program
            steps.append(("uid", nd))
            step_ids.append(nd.id)
            nd = nd.source
        elif isinstance(nd, P.TableScanNode):
            meta = getattr(compiler._compile(nd), "fused_scan", None)
            if meta is None:
                return None
            steps.reverse()
            step_ids.reverse()
            return FusedChain(compiler, steps, meta, step_ids, nd.id)
        else:
            return None


# PROCESS-WIDE cap on device-resident cached build materializations
# (the runner's plan cache can hold ~64 live compilers; a per-compiler
# budget would multiply); a compiler's contribution is returned to the
# pool when the compiler is garbage-collected (plan-cache eviction)
_FMAT_CACHE_BYTES = 1 << 31
_fmat_pool = {"bytes": 0}


def _fmat_reserve(compiler, nb: int) -> bool:
    import weakref
    if _fmat_pool["bytes"] + nb > _FMAT_CACHE_BYTES:
        return False
    _fmat_pool["bytes"] += nb

    def _release(n=nb):
        _fmat_pool["bytes"] -= n
    weakref.finalize(compiler, _release)
    return True


def fused_materialize(compiler, node: P.PlanNode,
                      cache: bool = False) -> Optional[Batch]:
    """Materialize a fusible chain's full output as ONE device batch via a
    single lax.map program over scan chunks — the zero-host-sync analog of
    draining a streaming subtree batch by batch.  Used for join build
    sides (cache=True: results stay HBM-resident across re-executions —
    generated connector data is immutable and writes clear the plan cache)
    and sort/window inputs.  Returns None when the subtree is not a
    fusible chain (caller streams instead)."""
    if compiler.ctx.memory.limited:
        return None     # budgeted/limited runs keep the accounted
        # streaming path (a bare query.max-memory ceiling still needs
        # the reservations that enforce it)
    # keyed STRUCTURALLY so replayed subtrees (scalar-subquery re-plans,
    # decorrelated copies — fresh node ids, same shape) share one
    # materialization; on a hit from a twin, columns rename positionally.
    # Parameterized subtrees append the execution's parameter fingerprint:
    # the cached batch is a function of the bound constants
    sk = P.structural_key(node)
    ckey = ("fmat_result", sk, compiler._splits_fingerprint(node))
    if '"@type": "parameter"' in sk:
        ckey += (compiler.ctx.params_fingerprint,)
    if cache and ckey in compiler._jit_cache:
        cached, names = compiler._jit_cache[ckey]
        return _renamed_batch(cached, names,
                              [v.name for v in node.output_variables])
    chain = assemble_chain(compiler, node)
    if chain is None or not chain.chunks:
        return None
    try:
        prep_res = chain.prep()
    except NotImplementedError:
        return None
    if prep_res is None:
        return None
    aux, expands, _deferred = prep_res
    leaf_cap = chain.leaf_cap(expands)
    chunks = chain.chunks_for(expands, meter=True)
    S = len(chunks)
    try:
        jax.eval_shape(lambda p, v: chain.make(p, v, aux, expands, leaf_cap),
                       jnp.int64(0), jnp.int64(1))
    except NotImplementedError:
        return None
    pos_arr = jnp.asarray([c[0] for c in chunks], dtype=jnp.int64)
    cnt_arr = jnp.asarray([c[1] for c in chunks], dtype=jnp.int64)
    key = ("fmat", node.id, expands)
    run_all = compiler._jit_cache.get(key)
    if run_all is None:
        @jax.jit
        def run_all(pos_arr, cnt_arr, aux):
            def step(pc):
                return chain.make(pc[0], pc[1], aux, expands, leaf_cap)
            stacked = jax.lax.map(step, (pos_arr, cnt_arr))
            return jax.tree_util.tree_map(
                lambda a: a.reshape((-1,) + a.shape[2:]), stacked)
        compiler._jit_cache[key] = run_all
    from .pipeline import _maybe_compact
    from .memory import batch_bytes
    out = _maybe_compact(run_all(pos_arr, cnt_arr, aux))
    if compiler.ctx.stats is not None:
        probe = chain_counts_fn(chain, expands, leaf_cap,
                                compiler._jit_cache,
                                ("fmat_counts", node.id, expands))
        record_chain_stats(compiler.ctx.stats, chain,
                           probe(pos_arr, cnt_arr, aux), S)
    if cache and _fmat_reserve(compiler, batch_bytes(out)):
        compiler._jit_cache[ckey] = \
            (out, [v.name for v in node.output_variables])
    return out


def _renamed_batch(batch: Batch, names: List[str],
                   new_names: List[str]) -> Batch:
    """Positionally rename a cached twin's columns to this subtree's
    output names (structural equality aligns the output order)."""
    if names == new_names:
        return batch
    cols = {new: batch.columns[old] for old, new in zip(names, new_names)}
    return Batch(cols, batch.mask)


def chain_counts_fn(chain: "FusedChain", expands: Tuple[int, ...],
                    leaf_cap: int, cache: dict, cache_key):
    """Cached jitted probe summing make()'s per-step row counters over
    every scan chunk — for executors whose main program cannot carry the
    counters in its loop state (sort-agg stacking, runtime-span)."""
    fn = cache.get(cache_key)
    if fn is None:
        @jax.jit
        def fn(pos_arr, cnt_arr, aux):
            def body(i, acc):
                _b, c = chain.make(pos_arr[i], cnt_arr[i], aux, expands,
                                   leaf_cap, with_counts=True)
                return acc + c
            return jax.lax.fori_loop(
                0, pos_arr.shape[0], body,
                jnp.zeros(1 + len(chain.steps), dtype=jnp.int64))
        cache[cache_key] = fn
    return fn


def record_chain_stats(stats, chain: "FusedChain", counts, n_chunks: int,
                       wall_s: float = 0.0, skip_root: bool = False) -> None:
    """Fold the device-side chain row counters into the EXPLAIN ANALYZE
    stats map: one entry per chain plan node, marked fused.  The wall is
    the WHOLE fused program's — operators compiled into one XLA program
    share a single dispatch, so per-operator wall does not decompose.
    skip_root leaves the chain root's rows/wall to the consumer's
    _instrument wrapper (fused_stream yields through it)."""
    if stats is None or counts is None:
        return
    vals = [int(v) for v in jax.device_get(counts)]  # lint: allow-host-sync
    root = chain.node_ids[-1] if chain.node_ids else None
    for nid, rows in zip(chain.node_ids, vals):
        if nid is None:
            continue
        ent = stats.setdefault(
            nid, {"rows": 0, "wall_s": 0.0, "batches": 0})
        ent["fused"] = True
        if skip_root and nid == root:
            continue        # the consumer's _instrument wrapper owns it
        ent["rows"] += rows
        ent["batches"] += n_chunks
        ent["wall_s"] += wall_s


def _join_build_cols(node: P.JoinNode, out_names, build_names):
    """Build columns a join step must gather: join outputs plus any
    build-side columns the ON filter reads (pruning may have dropped the
    latter from the output list)."""
    needed = [n for n in out_names if n in build_names]
    if node.filter is not None:
        from ..spi.expr import free_variables
        for v in free_variables(node.filter):
            if v.name in build_names and v.name not in needed:
                needed.append(v.name)
    return needed


def fused_stream(compiler, node: P.PlanNode):
    """Stream a fusible chain's output chunk by chunk as device Batches —
    one dispatch per chunk, ZERO host syncs (the fanout-bounded probes
    need no overflow checks).  Used by the streaming Join/SemiJoin
    compilers so chains consumed by non-aggregation operators (window,
    AssignUniqueId, ...) avoid the per-batch overflow-fetch pattern.
    Returns a Batch iterator or None (caller keeps the classic path)."""
    if compiler.ctx.memory.limited:
        return None
    analyzing = compiler.ctx.stats is not None
    cfg = compiler.ctx.config
    rs = getattr(compiler.ctx, "runtime_stats", None)
    if not cfg.fuse_pipelines:
        if rs is not None:
            rs.add("fusionDeclinedDisabled", 1)
        return None
    if analyzing and cfg.analyze_unfused:
        # the knob retains the old per-operator streaming profile for
        # join/semi-join chains too, not just the aggregation door
        if rs is not None:
            rs.add("fusionDeclinedAnalyzeUnfused", 1)
        return None
    key = ("fstream", node.id)
    ent = compiler._jit_cache.get(key, False)
    if ent is None:          # negative-cached
        return None
    if ent is False:
        chain = assemble_chain(compiler, node)
        if chain is None or not chain.chunks:
            compiler._jit_cache[key] = None
            return None
        try:
            prep_res = chain.prep()
        except NotImplementedError:
            prep_res = None
        if prep_res is None:
            compiler._jit_cache[key] = None
            return None
        aux, expands, _deferred = prep_res
        leaf_cap = chain.leaf_cap(expands)
        chunks = chain.chunks_for(expands)
        try:
            jax.eval_shape(
                lambda p, v: chain.make(p, v, aux, expands, leaf_cap),
                jnp.int64(0), jnp.int64(1))
        except NotImplementedError:
            compiler._jit_cache[key] = None
            return None

        @jax.jit
        def step(pos, valid, aux):
            # under EXPLAIN ANALYZE the per-step row counters ride the
            # same jitted program as extra outputs (zero host syncs)
            return chain.make(pos, valid, aux, expands, leaf_cap,
                              with_counts=analyzing)
        ent = (step, aux, chunks, chain, expands,
               compiler.ctx.params_fingerprint)
        compiler._jit_cache[key] = ent
    step, aux, chunks, chain, expands, ent_fp = ent

    # re-executions with different bound parameters: cached aux carries
    # the FIRST execution's parameter vector (and possibly stale build
    # tables / chunk lists) — refresh what depends on the params.  The
    # jitted step takes aux as a traced argument, so none of this retraces
    # unless a parameterized build's fanout changed.
    cur_fp = compiler.ctx.params_fingerprint
    if chain.build_params and cur_fp != ent_fp:
        try:
            prep_res = chain.prep()
        except NotImplementedError:
            prep_res = None
        if prep_res is None or prep_res[1] != expands:
            # build no longer fusible (or its fanout changed) under the
            # new constants: drop the entry and rebuild from scratch
            compiler._jit_cache.pop(key, None)
            return fused_stream(compiler, node)
        aux = prep_res[0]
        compiler._jit_cache[key] = (step, aux, chunks, chain, expands,
                                    cur_fp)
    if chain.has_params:
        aux = aux[:-1] + (compiler.ctx.params,)
    if chain.params_pushdown:
        chunks = chain.chunks_for(expands, meter=True)

    def gen():
        acc = None
        try:
            for pos, cnt in chunks:
                out = step(jnp.int64(pos), jnp.int64(cnt), aux)
                if analyzing:
                    out, c = out
                    acc = c if acc is None else acc + c
                yield out
        finally:
            if analyzing:
                record_chain_stats(compiler.ctx.stats, chain, acc,
                                   len(chunks), skip_root=True)
    return gen()


def _empty_build_batch(build_node: P.PlanNode) -> Batch:
    """8-row all-masked batch with the build schema (empty build side)."""
    from ..common.types import VarcharType, CharType
    from .lowering import _jnp_dtype
    cols = {}
    for v in build_node.output_variables:
        if isinstance(v.type, (VarcharType, CharType)):
            cols[v.name] = Column(jnp.zeros(8, dtype=jnp.int32), None, ("",))
        else:
            cols[v.name] = Column(jnp.zeros(8, dtype=_jnp_dtype(v.type)))
    return Batch(cols, jnp.zeros(8, dtype=bool))
