"""In-process distributed scheduler: runs a fragmented SubPlan as stages of
parallel tasks with partitioned / broadcast / gather exchanges between them.

The single-process analog of the reference's SqlQueryScheduler +
SqlStageExecution + exchange plumbing (SURVEY.md §2.4, §2.5): stages execute
bottom-up, each stage as N tasks; every task runs the fragment through the
PlanCompiler and partitions its output pages into per-consumer-task buffers
(PartitionedOutputOperator.java:58 semantics), which downstream tasks read as
their RemoteSourceNode input (ExchangeOperator.java:36 pull).  The same
task/buffer layout maps 1:1 onto the HTTP worker protocol (worker/) and onto
ICI all-to-all (parallel/exchange.py) when tasks sit on chips of one pod.

Partition routing hashes the LOGICAL value (strings by their bytes, not
their dictionary codes) so producers with different dictionaries agree.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..common.block import (Block, DictionaryBlock, FixedWidthBlock,
                            VariableWidthBlock, decode_to_flat)
from ..common.page import Page
from ..common.types import (CharType, Type, VarcharType)
from ..connectors import catalog, tpch
from ..spi import plan as P
from .pipeline import ExecutionConfig, PlanCompiler, TaskContext


@dataclass
class SchedulerConfig:
    exec_config: ExecutionConfig = field(default_factory=ExecutionConfig)
    # tasks per source-partitioned (scan) stage — the "worker count"
    source_tasks: int = 2
    # tasks per FIXED_HASH intermediate stage
    hash_tasks: int = 2


# ---------------------------------------------------------------------------
# host-side partition hashing (value-based, dictionary-independent)
# ---------------------------------------------------------------------------

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_NULL_HASH = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        return x ^ (x >> np.uint64(31))


def _utf8(s) -> bytes:
    return s.encode("utf-8") if isinstance(s, str) else bytes(s)


def _fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _hash_block(typ: Type, block: Block, n: int) -> np.ndarray:
    """Per-row uint64 value hash of one column."""
    if isinstance(typ, (VarcharType, CharType)):
        if isinstance(block, DictionaryBlock):
            inner = decode_to_flat(block.dictionary)
            entry_hash = np.array(
                [_NULL_HASH if s is None
                 else np.uint64(_fnv1a64(_utf8(s)))
                 for s in inner.to_pylist()], dtype=np.uint64)
            return entry_hash[block.ids]
        strings = decode_to_flat(block).to_pylist()
        return np.array([_NULL_HASH if s is None
                         else np.uint64(_fnv1a64(_utf8(s)))
                         for s in strings], dtype=np.uint64)
    flat = decode_to_flat(block)
    values = flat.values
    if values.dtype.kind == "f":
        values = values.view(np.uint64 if values.itemsize == 8 else np.uint32)
    h = _splitmix64(values.astype(np.int64).view(np.uint64))
    if flat.may_have_null:
        h = np.where(flat.null_mask(), _NULL_HASH, h)
    return h


def partition_targets(page: Page, types: List[Type], key_indices: List[int],
                      n_parts: int) -> np.ndarray:
    """Row -> target partition, combining the key columns' value hashes."""
    n = page.position_count
    h = np.full(n, np.uint64(1), dtype=np.uint64)
    with np.errstate(over="ignore"):
        for i in key_indices:
            hv = _hash_block(types[i], page.blocks[i], n)
            h = _splitmix64(h * np.uint64(31) + hv)
    return (h % np.uint64(n_parts)).astype(np.int64)


def split_page(page: Page, targets: np.ndarray, n_parts: int) -> List[Page]:
    out = []
    for p in range(n_parts):
        idx = np.flatnonzero(targets == p)
        if len(idx) == 0:
            out.append(None)
            continue
        out.append(Page([b.take(idx) for b in page.blocks], len(idx)))
    return out


# ---------------------------------------------------------------------------
# stage / buffer model
# ---------------------------------------------------------------------------

class OutputBuffers:
    """Per-fragment output: buffers[producer_task][partition] -> [Page].

    Partition semantics by output scheme (reference OutputBuffers):
      SINGLE            everything in partition 0 (gather consumers)
      FIXED_HASH        partition = hash(keys) % consumer task count
      FIXED_BROADCAST   partition 0 holds the full output; every consumer
                        task reads it (BroadcastOutputBuffer)
    """

    def __init__(self, n_tasks: int, n_partitions: int, broadcast: bool):
        self.broadcast = broadcast
        self.pages: List[Dict[int, List[Page]]] = [
            {p: [] for p in range(max(1, n_partitions))}
            for _ in range(n_tasks)]

    def add(self, task: int, partition: int, page: Page) -> None:
        self.pages[task][partition].append(page)

    def pages_for_consumer(self, consumer_task: int) -> List[Page]:
        part = 0 if self.broadcast else consumer_task
        out: List[Page] = []
        for task_pages in self.pages:
            out.extend(task_pages.get(part, ()))
        return out


@dataclass
class StageInfo:
    fragment: P.PlanFragment
    children: List["StageInfo"]
    n_tasks: int = 1
    n_partitions: int = 1      # consumer task count (output fan-out)
    buffers: Optional[OutputBuffers] = None


class InProcessScheduler:
    """Executes a SubPlan bottom-up.  Tasks run sequentially here; the HTTP
    worker runtime (worker/) and the ICI exchange (parallel/) distribute the
    same stage graph across processes/chips."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()

    # -- planning the stage tree -----------------------------------------
    def _build_stages(self, subplan: P.SubPlan) -> StageInfo:
        children = [self._build_stages(c) for c in subplan.children]
        frag = subplan.fragment
        if frag.partitioning == P.SOURCE_DISTRIBUTION:
            n_tasks = self.config.source_tasks
        elif frag.partitioning == P.FIXED_HASH_DISTRIBUTION:
            n_tasks = self.config.hash_tasks
        else:
            n_tasks = 1
        return StageInfo(frag, children, n_tasks)

    def _assign_partitions(self, stage: StageInfo,
                           consumer_tasks: int) -> None:
        stage.n_partitions = consumer_tasks
        handle = stage.fragment.output_partitioning_scheme.handle
        broadcast = handle == P.FIXED_BROADCAST_DISTRIBUTION
        n_parts = 1 if handle in (P.SINGLE_DISTRIBUTION,) or broadcast \
            else consumer_tasks
        stage.buffers = OutputBuffers(stage.n_tasks, n_parts, broadcast)
        for c in stage.children:
            self._assign_partitions(c, stage.n_tasks)

    # -- execution --------------------------------------------------------
    def execute(self, subplan: P.SubPlan) -> Iterator[Page]:
        root = self._build_stages(subplan)
        self._assign_partitions(root, 1)
        self._run_stage(root)
        yield from root.buffers.pages_for_consumer(0)

    def _run_stage(self, stage: StageInfo) -> None:
        for child in stage.children:
            self._run_stage(child)
        frag = stage.fragment
        scheme = frag.output_partitioning_scheme
        out_names = [v.name for v in frag.root.output_variables]
        out_types = [v.type for v in frag.root.output_variables]
        key_indices = [out_names.index(a.name) for a in scheme.arguments]
        hashed = scheme.handle == P.FIXED_HASH_DISTRIBUTION

        # split assignment per scan node: task i takes splits[i::n]
        scan_splits: Dict[str, List] = {}
        for node in P.walk_plan(frag.root):
            if isinstance(node, P.TableScanNode):
                th = node.table
                sf = dict(th.extra).get("scaleFactor", 0.01)
                n_splits = max(stage.n_tasks,
                               self.config.exec_config.splits_per_scan)
                scan_splits[node.id] = catalog.make_splits(
                    th.table_name, sf, n_splits, th.connector_id)

        remote_nodes = [n for n in P.walk_plan(frag.root)
                        if isinstance(n, P.RemoteSourceNode)]
        child_by_fid = {c.fragment.fragment_id: c for c in stage.children}

        for task_index in range(stage.n_tasks):
            ctx = TaskContext(config=self.config.exec_config,
                              task_index=task_index)
            for node_id, splits in scan_splits.items():
                ctx.splits[node_id] = splits[task_index::stage.n_tasks]
            for rnode in remote_nodes:
                sources = [child_by_fid[fid] for fid in
                           rnode.source_fragment_ids]
                ctx.remote_pages[rnode.id] = _remote_reader(
                    sources, task_index)
            compiler = PlanCompiler(ctx)
            for page in compiler.run_to_pages(frag.root):
                if hashed and stage.n_partitions > 1:
                    targets = partition_targets(
                        page, out_types, key_indices, stage.n_partitions)
                    for p, sub in enumerate(
                            split_page(page, targets, stage.n_partitions)):
                        if sub is not None:
                            stage.buffers.add(task_index, p, sub)
                else:
                    stage.buffers.add(task_index, 0, page)


def _remote_reader(sources: List[StageInfo], consumer_task: int):
    def read() -> Iterator[Page]:
        for src in sources:
            yield from src.buffers.pages_for_consumer(consumer_task)
    return read
