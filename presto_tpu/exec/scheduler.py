"""In-process distributed scheduler: runs a fragmented SubPlan as stages of
parallel tasks with partitioned / broadcast / gather exchanges between them.

The single-process analog of the reference's SqlQueryScheduler +
SqlStageExecution + exchange plumbing (SURVEY.md §2.4, §2.5): stages execute
bottom-up, each stage as N tasks; every task runs the fragment through the
PlanCompiler and partitions its output pages into per-consumer-task buffers
(PartitionedOutputOperator.java:58 semantics), which downstream tasks read as
their RemoteSourceNode input (ExchangeOperator.java:36 pull).  The same
task/buffer layout maps 1:1 onto the HTTP worker protocol (worker/) and onto
ICI all-to-all (parallel/exchange.py) when tasks sit on chips of one pod.

Partition routing hashes the LOGICAL value (strings by their bytes, not
their dictionary codes) so producers with different dictionaries agree.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..common.block import (Block, DictionaryBlock, FixedWidthBlock,
                            VariableWidthBlock, decode_to_flat)
from ..common.page import Page
from ..common.types import (CharType, Type, VarcharType)
from ..connectors import catalog, tpch
from ..spi import plan as P
from .pipeline import ExecutionConfig, PlanCompiler, TaskContext


@dataclass
class SchedulerConfig:
    exec_config: ExecutionConfig = field(default_factory=ExecutionConfig)
    # tasks per source-partitioned (scan) stage — the "worker count"
    source_tasks: int = 2
    # tasks per FIXED_HASH intermediate stage
    hash_tasks: int = 2
    # jax.sharding.Mesh over parallel.mesh.WORKER_AXIS: when set and a
    # hashed stage's task count equals the mesh size, tasks are pinned
    # 1:1 to mesh devices and the hash exchange runs as a jitted
    # all_to_all over ICI (parallel/exchange.py) instead of host-side
    # page splitting; other edges (gather/broadcast/cross-process) keep
    # the page path (SURVEY.md §5.8: HTTP stays for the coordinator and
    # cross-pod edges)
    mesh: object = None
    # BATCH MODE — the Presto-on-Spark analog (SURVEY.md §2.7,
    # PrestoSparkQueryExecutionFactory.java:164): stage outputs
    # MATERIALIZE to local temp storage between stages (the Spark-shuffle
    # analog of presto_cpp/main/operators/ShuffleWrite), so a failed task
    # retries from durable inputs instead of failing the query —
    # recoverable execution (RECOVERABLE_GROUPED_EXECUTION,
    # SystemSessionProperties.java:106,493)
    batch_mode: bool = False
    # per-task retry attempts on failure (0 = fail-fast MPP, the
    # streaming default)
    task_retries: int = 0
    # directory for materialized shuffle files (None = TemporaryDirectory)
    temp_dir: Optional[str] = None
    # test hook: fault_injector(stage_fragment_id, task_index, attempt)
    # raises to simulate a task failure (ErrorClassifier-style retryable)
    fault_injector: Optional[Callable] = None


# ---------------------------------------------------------------------------
# host-side partition hashing (value-based, dictionary-independent)
# ---------------------------------------------------------------------------

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_NULL_HASH = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        return x ^ (x >> np.uint64(31))


_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def _fnv1a64_rows(block) -> np.ndarray:
    """Vectorized FNV-1a over every row of a flat VariableWidthBlock: one
    numpy pass per BYTE POSITION (strings are short; rows are many), not a
    python loop per byte — the exchange-path fix for VERDICT weak #3."""
    offsets = block.offsets.astype(np.int64)
    data = block.data
    lengths = offsets[1:] - offsets[:-1]
    n = len(lengths)
    h = np.full(n, _FNV_OFFSET, dtype=np.uint64)
    if n == 0:
        return h
    # active-set shrink keeps this O(total_bytes): each pass only touches
    # rows still longer than j, so one long outlier string doesn't make
    # every row pay for its length
    active = np.flatnonzero(lengths > 0)
    j = 0
    with np.errstate(over="ignore"):
        while active.size:
            b = data[offsets[active] + j].astype(np.uint64)
            h[active] = (h[active] ^ b) * _FNV_PRIME
            j += 1
            active = active[lengths[active] > j]
    return h


def _hash_block(typ: Type, block: Block, n: int) -> np.ndarray:
    """Per-row uint64 value hash of one column."""
    if isinstance(typ, (VarcharType, CharType)):
        if isinstance(block, DictionaryBlock):
            # hash the (small) dictionary once, then one gather per page
            inner = decode_to_flat(block.dictionary)
            entry_hash = _fnv1a64_rows(inner)
            if inner.nulls is not None:
                entry_hash = np.where(inner.nulls, _NULL_HASH, entry_hash)
            return entry_hash[block.ids]
        flat = decode_to_flat(block)
        h = _fnv1a64_rows(flat)
        if flat.nulls is not None:
            h = np.where(flat.nulls, _NULL_HASH, h)
        return h
    flat = decode_to_flat(block)
    values = flat.values
    if values.dtype.kind == "f":
        values = values.view(np.uint64 if values.itemsize == 8 else np.uint32)
    h = _splitmix64(values.astype(np.int64).view(np.uint64))
    if flat.may_have_null:
        h = np.where(flat.null_mask(), _NULL_HASH, h)
    return h


def partition_targets(page: Page, types: List[Type], key_indices: List[int],
                      n_parts: int) -> np.ndarray:
    """Row -> target partition, combining the key columns' value hashes."""
    n = page.position_count
    h = np.full(n, np.uint64(1), dtype=np.uint64)
    with np.errstate(over="ignore"):
        for i in key_indices:
            hv = _hash_block(types[i], page.blocks[i], n)
            h = _splitmix64(h * np.uint64(31) + hv)
    return (h % np.uint64(n_parts)).astype(np.int64)


def split_page(page: Page, targets: np.ndarray, n_parts: int) -> List[Page]:
    out = []
    for p in range(n_parts):
        idx = np.flatnonzero(targets == p)
        if len(idx) == 0:
            out.append(None)
            continue
        out.append(Page([b.take(idx) for b in page.blocks], len(idx)))
    return out


# ---------------------------------------------------------------------------
# stage / buffer model
# ---------------------------------------------------------------------------

class OutputBuffers:
    """Per-fragment output: buffers[producer_task][partition] -> [Page].

    Partition semantics by output scheme (reference OutputBuffers):
      SINGLE            everything in partition 0 (gather consumers)
      FIXED_HASH        partition = hash(keys) % consumer task count
      FIXED_BROADCAST   partition 0 holds the full output; every consumer
                        task reads it (BroadcastOutputBuffer)
    """

    def __init__(self, n_tasks: int, n_partitions: int, broadcast: bool):
        self.broadcast = broadcast
        self.pages: List[Dict[int, List[Page]]] = [
            {p: [] for p in range(max(1, n_partitions))}
            for _ in range(n_tasks)]

    def add(self, task: int, partition: int, page: Page) -> None:
        self.pages[task][partition].append(page)

    def reset_task(self, task: int) -> None:
        """Drop a task's staged output (retry must not duplicate rows)."""
        self.pages[task] = {p: [] for p in self.pages[task]}

    def materialize(self, stage_dir: str) -> None:
        """Spill every (task, partition) page list to a shuffle file and
        replace the in-memory lists with lazy file readers — the batch
        (Presto-on-Spark) mode's durable-exchange step
        (presto_cpp/main/operators/ShuffleWrite / LocalPersistentShuffle
        semantics over SerializedPage framing)."""
        import os

        from ..common.serde import deserialize_page, serialize_page
        os.makedirs(stage_dir, exist_ok=True)

        class _FilePages:
            def __init__(self, path: str, count: int):
                self.path, self.count = path, count

            def __iter__(self):
                with open(self.path, "rb") as f:
                    raw = f.read()
                pos = 0
                for _ in range(self.count):
                    page, pos = deserialize_page(raw, pos)
                    yield page

            def __len__(self):
                return self.count

        for ti, parts in enumerate(self.pages):
            for p, pages in parts.items():
                if not isinstance(pages, list):
                    continue
                path = os.path.join(stage_dir, f"t{ti}_p{p}.shuffle")
                with open(path, "wb") as f:
                    for page in pages:
                        f.write(serialize_page(page))
                parts[p] = _FilePages(path, len(pages))

    def pages_for_consumer(self, consumer_task: int) -> List[Page]:
        part = 0 if self.broadcast else consumer_task
        out: List[Page] = []
        for task_pages in self.pages:
            out.extend(task_pages.get(part, ()))
        return out


@dataclass
class StageInfo:
    fragment: P.PlanFragment
    children: List["StageInfo"]
    n_tasks: int = 1
    n_partitions: int = 1      # consumer task count (output fan-out)
    buffers: Optional[OutputBuffers] = None
    # ICI exchange result: consumer task -> device-resident Batch (rows
    # whose hash targets that consumer), plus the producer's output
    # column order for positional renaming at the consumer
    device_out: Optional[list] = None
    out_names: Optional[List[str]] = None
    # concurrency telemetry: per-task wall seconds and the stage wall —
    # overlap quality = stage_wall / sum(task_walls)
    task_walls: Optional[List[float]] = None
    stage_wall: Optional[float] = None


class InProcessScheduler:
    """Executes a SubPlan bottom-up.  Tasks run sequentially here; the HTTP
    worker runtime (worker/) and the ICI exchange (parallel/) distribute the
    same stage graph across processes/chips."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()

    # -- planning the stage tree -----------------------------------------
    def _build_stages(self, subplan: P.SubPlan) -> StageInfo:
        children = [self._build_stages(c) for c in subplan.children]
        frag = subplan.fragment
        if frag.partitioning == P.SOURCE_DISTRIBUTION:
            n_tasks = self.config.source_tasks
        elif frag.partitioning == P.FIXED_HASH_DISTRIBUTION:
            n_tasks = self.config.hash_tasks
        else:
            n_tasks = 1
        return StageInfo(frag, children, n_tasks)

    def _assign_partitions(self, stage: StageInfo,
                           consumer_tasks: int) -> None:
        stage.n_partitions = consumer_tasks
        handle = stage.fragment.output_partitioning_scheme.handle
        broadcast = handle == P.FIXED_BROADCAST_DISTRIBUTION
        n_parts = 1 if handle in (P.SINGLE_DISTRIBUTION,) or broadcast \
            else consumer_tasks
        stage.buffers = OutputBuffers(stage.n_tasks, n_parts, broadcast)
        for c in stage.children:
            self._assign_partitions(c, stage.n_tasks)

    # -- execution --------------------------------------------------------
    def execute(self, subplan: P.SubPlan) -> Iterator[Page]:
        root = self._build_stages(subplan)
        self._assign_partitions(root, 1)
        self._run_stage(root)
        yield from root.buffers.pages_for_consumer(0)

    def _mesh_size(self) -> int:
        from ..parallel.mesh import WORKER_AXIS
        return (0 if self.config.mesh is None
                else self.config.mesh.shape[WORKER_AXIS])

    def _batch_dir(self, fragment_id: str) -> str:
        """Shuffle-file directory for one stage (batch mode)."""
        import os
        if self.config.temp_dir is None:
            import tempfile
            self._tmp = getattr(self, "_tmp", None) \
                or tempfile.TemporaryDirectory(prefix="presto_tpu_shuffle_")
            base = self._tmp.name
        else:
            base = self.config.temp_dir
        return os.path.join(base, f"stage_{fragment_id}")

    def _run_stage(self, stage: StageInfo) -> None:
        for child in stage.children:
            self._run_stage(child)
        frag = stage.fragment
        scheme = frag.output_partitioning_scheme
        out_names = [v.name for v in frag.root.output_variables]
        out_types = [v.type for v in frag.root.output_variables]
        key_indices = [out_names.index(a.name) for a in scheme.arguments]
        hashed = scheme.handle == P.FIXED_HASH_DISTRIBUTION
        stage.out_names = out_names

        # ICI eligibility: hashed fan-out, tasks 1:1 with mesh devices
        # (SURVEY.md §5.8: intra-pod hash exchange rides ICI; gather /
        # broadcast / cross-process edges keep the page path)
        mesh = self.config.mesh
        ici = (hashed and stage.n_partitions > 1
               and stage.n_tasks == stage.n_partitions
               and stage.n_tasks == self._mesh_size()
               # batch mode wants every exchange durable on disk (retry
               # re-reads it); device-resident shards are not durable
               and not self.config.batch_mode)

        # split assignment per scan node: task i takes splits[i::n]
        scan_splits: Dict[str, List] = {}
        for node in P.walk_plan(frag.root):
            if isinstance(node, P.TableScanNode):
                th = node.table
                sf = dict(th.extra).get("scaleFactor", 0.01)
                n_splits = max(stage.n_tasks,
                               self.config.exec_config.splits_per_scan)
                scan_splits[node.id] = catalog.make_splits(
                    th.table_name, sf, n_splits, th.connector_id)

        remote_nodes = [n for n in P.walk_plan(frag.root)
                        if isinstance(n, P.RemoteSourceNode)]
        child_by_fid = {c.fragment.fragment_id: c for c in stage.children}

        # consuming device shards requires task<->device pinning too;
        # a node mixing device and page children, or device children whose
        # string dictionaries disagree, reads everything as pages (the
        # device children are converted lazily in _remote_reader)
        device_inputs = {}
        for rnode in remote_nodes:
            sources = [child_by_fid[fid]
                       for fid in rnode.source_fragment_ids]
            device_inputs[rnode.id] = (
                all(s.device_out is not None for s in sources)
                and _device_dicts_agree(sources))
        pin = (ici or any(device_inputs.values())) \
            and stage.n_tasks == self._mesh_size()
        devices = (list(mesh.devices.flat)
                   if pin or ici else [None] * stage.n_tasks)

        import contextlib
        import time as _time
        import jax

        # one traced program per stage, shared by its tasks (the tasks
        # compile byte-identical step closures; Python tracing is
        # GIL-serialized, so without sharing an N-task stage pays N
        # traces on one core — PlanCompiler.shared_jit)
        stage_jits: Dict = {}

        # lifespan sharding: a grouped-eligible source stage gives every
        # task the FULL split set plus a disjoint round-robin subset of
        # the bucket layout — K lifespans spread over N tasks instead of
        # each task re-bucketing a split subset (which _full_coverage
        # would reject, forfeiting grouped execution entirely)
        from .grouped import stage_shards_lifespans
        grouped_shards = (
            stage.n_tasks > 1
            and frag.partitioning == P.SOURCE_DISTRIBUTION
            and stage_shards_lifespans(frag.root,
                                       self.config.exec_config))

        def run_task(task_index: int):
            """One task's fragment execution; returns (batch-or-None for
            ICI stages, wall seconds)."""
            t0 = _time.perf_counter()
            ctx = TaskContext(config=self.config.exec_config,
                              task_index=task_index,
                              shared_jits=stage_jits)
            if grouped_shards:
                ctx.grouped_shard = (task_index, stage.n_tasks)
            for node_id, splits in scan_splits.items():
                ctx.splits[node_id] = (list(splits) if grouped_shards
                                       else splits[task_index::stage.n_tasks])
            for rnode in remote_nodes:
                sources = [child_by_fid[fid] for fid in
                           rnode.source_fragment_ids]
                if device_inputs[rnode.id] and pin:
                    ctx.remote_batches[rnode.id] = _device_reader(
                        sources, task_index, rnode)
                else:
                    ctx.remote_pages[rnode.id] = _remote_reader(
                        sources, task_index,
                        client_threads=
                        self.config.exec_config.exchange_client_threads)
            compiler = PlanCompiler(ctx)
            dev_ctx = (jax.default_device(devices[task_index])
                       if pin else contextlib.nullcontext())
            out = None
            with dev_ctx:
                if ici:
                    from .pipeline import _compact_concat
                    batches = [b for b in
                               compiler.run_to_batches(frag.root)]
                    out = _compact_concat(batches) if batches else None
                else:
                    for page in compiler.run_to_pages(frag.root):
                        if hashed and stage.n_partitions > 1:
                            targets = partition_targets(
                                page, out_types, key_indices,
                                stage.n_partitions)
                            for p, sub in enumerate(
                                    split_page(page, targets,
                                               stage.n_partitions)):
                                if sub is not None:
                                    stage.buffers.add(task_index, p, sub)
                        else:
                            stage.buffers.add(task_index, 0, page)
            return out, _time.perf_counter() - t0

        def run_task_retrying(task_index: int):
            """Batch (Presto-on-Spark) mode: a failed task re-runs from
            its materialized inputs (children already spilled their
            shuffle files), the recoverable-execution contract
            (PrestoSparkTaskExecutorFactory retry via Spark /
            RECOVERABLE_GROUPED_EXECUTION).  Streaming mode keeps
            fail-fast MPP semantics (task_retries=0).  Retry is gated by
            the shared error classifier (ErrorClassifier.java analog):
            USER_ERROR — bad SQL, bad input — fails fast; only
            infrastructure-shaped failures consume retry attempts."""
            from ..common.errors import is_retryable
            attempts = 1 + max(0, self.config.task_retries)
            for attempt in range(attempts):
                try:
                    if self.config.fault_injector is not None:
                        self.config.fault_injector(
                            frag.fragment_id, task_index, attempt)
                    return run_task(task_index)
                except Exception as e:
                    stage.buffers.reset_task(task_index)
                    if attempt + 1 >= attempts or not is_retryable(e):
                        raise
            return None, 0.0

        # a stage's N tasks run CONCURRENTLY (reference
        # SqlStageExecution.scheduleTask / the worker TaskExecutor thread
        # pool): each task's host syncs release the GIL while waiting on
        # its device, so other tasks keep dispatching — stage wall
        # approaches the slowest task, not the sum.  jax.default_device
        # is thread-local, so per-device pinning survives threading.
        stage_t0 = _time.perf_counter()
        # concurrency requires memory isolation: pinned tasks own their
        # device; unpinned tasks share one device, so when a memory
        # budget is configured their independent per-task pools would
        # stack to n_tasks x budget — run those sequentially
        concurrent = stage.n_tasks > 1 and (
            pin or self.config.exec_config.memory_budget_bytes is None)
        if not concurrent:
            results = [run_task_retrying(i) for i in range(stage.n_tasks)]
        else:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=stage.n_tasks) as pool_ex:
                results = list(pool_ex.map(run_task_retrying,
                                           range(stage.n_tasks)))
        task_batches = [r[0] for r in results]
        stage.task_walls = [round(r[1], 4) for r in results]
        stage.stage_wall = round(_time.perf_counter() - stage_t0, 4)
        if ici:
            keys = tuple(out_names[i] for i in key_indices)
            if not self._ici_exchange(stage, task_batches, keys):
                # metadata mismatch across tasks: fall back to pages
                self._spill_batches_to_pages(
                    stage, task_batches, out_names, out_types,
                    key_indices)
        if self.config.batch_mode and stage.device_out is None:
            # durable inter-stage exchange (the Spark-shuffle analog)
            stage.buffers.materialize(self._batch_dir(frag.fragment_id))

    # -- ICI exchange -----------------------------------------------------
    _exch_cache: Dict = {}

    def _ici_exchange(self, stage: StageInfo, task_batches: List,
                      keys: Tuple[str, ...]) -> bool:
        """all_to_all the per-task output batches across the mesh; on
        success stage.device_out[consumer] holds that consumer's rows
        device-resident.  Returns False when per-task batch metadata
        (dictionaries / null-ness / schema) disagrees — the caller then
        falls back to the page exchange."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec
        from ..exec import operators as ops
        from ..exec.batch import Batch, Column
        from ..parallel.exchange import make_partitioned_exchange
        from ..parallel.mesh import WORKER_AXIS
        mesh = self.config.mesh
        devices = list(mesh.devices.flat)
        n = stage.n_tasks

        lives = [0 if b is None else int(jax.device_get(b.mask.sum()))  # lint: allow-host-sync
                 for b in task_batches]
        template = next((b for b in task_batches if b is not None), None)
        if template is None:
            stage.device_out = [None] * n
            return True
        # schema/metadata must agree across tasks (scan dictionaries are
        # table-stable, so they normally do)
        tstruct = _batch_meta(template)
        for b in task_batches:
            if b is not None and _batch_meta(b) != tstruct:
                return False

        B = max(256, 1 << (max(max(lives), 1) - 1).bit_length())
        from .pipeline import _jit_compact
        norm = []
        for i, b in enumerate(task_batches):
            with jax.default_device(devices[i]):
                if b is None:
                    nb = _zeros_like_batch(template, B)
                elif b.capacity == B:
                    nb = b
                else:
                    nb = _jit_compact(b, B)
            norm.append(nb)

        sharding = NamedSharding(mesh, PartitionSpec(WORKER_AXIS))

        def to_global(arrays):
            arrays = [jax.device_put(a, devices[i])
                      for i, a in enumerate(arrays)]
            shape = (n * B,) + arrays[0].shape[1:]
            return jax.make_array_from_single_device_arrays(
                shape, sharding, arrays)

        cols = {}
        for name, c in template.columns.items():
            values = to_global([nb.columns[name].values for nb in norm])
            nulls = (to_global([nb.columns[name].null_mask()
                                for nb in norm])
                     if c.nulls is not None else None)
            cols[name] = Column(values, nulls, c.dictionary, c.lazy)
        gbatch = Batch(cols, to_global([nb.mask for nb in norm]))

        # quota retry: start near the balanced share, double on overflow
        # (the device-side overflow flag is the module's promised
        # split-and-retry recovery; quota == B always fits)
        quota = max(64, 1 << ((2 * max(max(lives), 1) // n) | 1)
                    .bit_length())
        quota = min(quota, B)
        while True:
            key = (tuple(devices), keys, quota, B)
            exch = self._exch_cache.get(key)
            if exch is None:
                exch = make_partitioned_exchange(mesh, keys, quota)
                self._exch_cache[key] = exch
            out, overflow = exch(gbatch)
            if not bool(jax.device_get(overflow)):  # lint: allow-host-sync
                break
            if quota >= B:
                raise RuntimeError("ICI exchange overflow at full quota")
            quota = min(B, quota * 2)

        shard_cap = n * quota
        by_dev = {}
        first_col = next(iter(out.columns.values())).values
        for s in first_col.addressable_shards:
            by_dev[s.device] = None
        stage.device_out = []
        for i in range(n):
            ccols = {}
            for name, c in out.columns.items():
                ccols[name] = Column(
                    _shard_on(c.values, devices[i]),
                    (_shard_on(c.nulls, devices[i])
                     if c.nulls is not None else None),
                    c.dictionary, c.lazy)
            stage.device_out.append(
                Batch(ccols, _shard_on(out.mask, devices[i])))
        return True

    def _spill_batches_to_pages(self, stage: StageInfo, task_batches,
                                out_names, out_types, key_indices) -> None:
        from .batch import batch_to_page
        for task_index, b in enumerate(task_batches):
            if b is None:
                continue
            page = batch_to_page(b, out_names, out_types)
            if not page.position_count:
                continue
            targets = partition_targets(page, out_types, key_indices,
                                        stage.n_partitions)
            for p, sub in enumerate(
                    split_page(page, targets, stage.n_partitions)):
                if sub is not None:
                    stage.buffers.add(task_index, p, sub)


def _batch_meta(b) -> tuple:
    return tuple(sorted(
        (name, str(c.values.dtype), c.nulls is not None, c.dictionary,
         c.lazy) for name, c in b.columns.items()))


def _zeros_like_batch(template, B: int):
    import jax.numpy as jnp
    from ..exec.batch import Batch, Column
    cols = {}
    for name, c in template.columns.items():
        v = jnp.zeros((B,) + c.values.shape[1:], c.values.dtype)
        nn = jnp.zeros(B, dtype=bool) if c.nulls is not None else None
        cols[name] = Column(v, nn, c.dictionary, c.lazy)
    return Batch(cols, jnp.zeros(B, dtype=bool))


def _shard_on(arr, device):
    for s in arr.addressable_shards:
        if s.device == device:
            return s.data
    raise RuntimeError(f"no shard on {device}")


def _device_reader(sources: List[StageInfo], consumer_task: int, rnode):
    """Consumer-side ICI input: the device-resident shard for this task,
    renamed positionally to the RemoteSourceNode's output variables."""
    from ..exec.batch import Batch
    names = [v.name for v in rnode.outputs]

    def read():
        for src in sources:
            b = src.device_out[consumer_task]
            if b is None:
                continue
            prod = src.out_names
            cols = {names[j]: b.columns[prod[j]]
                    for j in range(len(names))}
            yield Batch(cols, b.mask)
    return read


def _device_dicts_agree(sources: List[StageInfo]) -> bool:
    """Device batches skip the union-dictionary remap of the page path
    (exec/batch.py pages_to_batches), so the device reader is only safe
    when every source fragment ships identical per-column dictionary /
    lazy metadata."""
    seen: Dict[int, tuple] = {}
    for src in sources:
        for b in src.device_out or []:
            if b is None:
                continue
            cols = [b.columns[n] for n in src.out_names]
            for j, c in enumerate(cols):
                meta = (c.dictionary, c.lazy)
                if seen.setdefault(j, meta) != meta:
                    return False
    return True


def _remote_reader(sources: List[StageInfo], consumer_task: int,
                   client_threads: int = 1):
    """Page reader; ICI children (device_out) are converted to pages
    lazily so mixed device/page source sets lose no rows.  With
    client_threads > 1 the sources drain concurrently through the
    local-exchange arrival-order queue (the in-process mirror of the
    HTTP ExchangeClient; cross-source page order carries no semantics —
    ordering, if any, is applied inside the consuming fragment)."""
    def _source_pages(src: StageInfo) -> Iterator[Page]:
        if src.device_out is not None:
            from .batch import batch_to_page
            b = src.device_out[consumer_task]
            if b is not None:
                types = [v.type for v in
                         src.fragment.root.output_variables]
                page = batch_to_page(b, src.out_names, types)
                if page.position_count:
                    yield page
            return
        yield from src.buffers.pages_for_consumer(consumer_task)

    def read() -> Iterator[Page]:
        if client_threads > 1 and len(sources) > 1:
            from .local_exchange import parallel_drain
            thunks = [(lambda s=src: _source_pages(s)) for src in sources]
            yield from parallel_drain(thunks, client_threads)
        else:
            for src in sources:
                yield from _source_pages(src)
    return read
