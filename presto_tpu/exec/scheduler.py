"""In-process distributed scheduler: runs a fragmented SubPlan as stages of
parallel tasks with partitioned / broadcast / gather exchanges between them.

The single-process analog of the reference's SqlQueryScheduler +
SqlStageExecution + exchange plumbing (SURVEY.md §2.4, §2.5): stages execute
bottom-up, each stage as N tasks; every task runs the fragment through the
PlanCompiler and partitions its output pages into per-consumer-task buffers
(PartitionedOutputOperator.java:58 semantics), which downstream tasks read as
their RemoteSourceNode input (ExchangeOperator.java:36 pull).  The same
task/buffer layout maps 1:1 onto the HTTP worker protocol (worker/) and onto
ICI all-to-all (parallel/exchange.py) when tasks sit on chips of one pod.

Partition routing hashes the LOGICAL value (strings by their bytes, not
their dictionary codes) so producers with different dictionaries agree.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..common.block import (Block, DictionaryBlock, FixedWidthBlock,
                            VariableWidthBlock, decode_to_flat)
from ..common.page import Page
from ..common.types import (CharType, Type, VarcharType)
from ..connectors import catalog, tpch
from ..spi import plan as P
from .adaptive import (AdaptiveState, DynamicFilterCollector,
                       DynamicFilterSummary, ExchangeDecision,
                       decide_exchange, decide_side_swap,
                       summaries_to_runtime, summarize_key_column)
from .pipeline import ExecutionConfig, PlanCompiler, TaskContext


@dataclass
class SchedulerConfig:
    exec_config: ExecutionConfig = field(default_factory=ExecutionConfig)
    # tasks per source-partitioned (scan) stage — the "worker count"
    source_tasks: int = 2
    # tasks per FIXED_HASH intermediate stage
    hash_tasks: int = 2
    # broadcast row budget for runtime partitioned->broadcast flips
    # (exec/adaptive.decide_exchange) — mirrors the fragmenter's
    # plan-time FragmenterConfig.broadcast_threshold
    broadcast_threshold: int = 600_000
    # jax.sharding.Mesh over parallel.mesh.WORKER_AXIS: when set and a
    # hashed stage's task count equals the mesh size, tasks are pinned
    # 1:1 to mesh devices and the hash exchange runs as a jitted
    # all_to_all over ICI (parallel/exchange.py) instead of host-side
    # page splitting; other edges (gather/broadcast/cross-process) keep
    # the page path (SURVEY.md §5.8: HTTP stays for the coordinator and
    # cross-pod edges)
    mesh: object = None
    # BATCH MODE — the Presto-on-Spark analog (SURVEY.md §2.7,
    # PrestoSparkQueryExecutionFactory.java:164): stage outputs
    # MATERIALIZE to local temp storage between stages (the Spark-shuffle
    # analog of presto_cpp/main/operators/ShuffleWrite), so a failed task
    # retries from durable inputs instead of failing the query —
    # recoverable execution (RECOVERABLE_GROUPED_EXECUTION,
    # SystemSessionProperties.java:106,493)
    batch_mode: bool = False
    # per-task retry attempts on failure (0 = fail-fast MPP, the
    # streaming default)
    task_retries: int = 0
    # directory for materialized shuffle files (None = TemporaryDirectory)
    temp_dir: Optional[str] = None
    # test hook: fault_injector(stage_fragment_id, task_index, attempt)
    # raises to simulate a task failure (ErrorClassifier-style retryable)
    fault_injector: Optional[Callable] = None


def merge_node_stats(dst: Dict[str, dict], src: Dict[str, dict]) -> None:
    """Merge one task's per-plan-node operator stats into a rollup map —
    the task -> stage -> coordinator merge semantics (reference
    OperatorStats.add): additive fields sum, markers (fused /
    operatorType) are kept from the first task that reported them, and
    per-driver walls concatenate."""
    for nid, s in src.items():
        ent = dst.setdefault(nid, {"rows": 0, "wall_s": 0.0, "batches": 0})
        for k, v in s.items():
            if k in ("rows", "batches", "bytes",
                     "dynamicFilterRowsDropped"):
                ent[k] = ent.get(k, 0) + v
            elif k == "wall_s":
                ent[k] = ent.get(k, 0.0) + v
            elif k == "driver_walls":
                ent.setdefault(k, []).extend(v)
            else:
                ent.setdefault(k, v)


# ---------------------------------------------------------------------------
# host-side partition hashing (value-based, dictionary-independent)
# ---------------------------------------------------------------------------

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_NULL_HASH = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        return x ^ (x >> np.uint64(31))


_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def _fnv1a64_rows(block) -> np.ndarray:
    """Vectorized FNV-1a over every row of a flat VariableWidthBlock: one
    numpy pass per BYTE POSITION (strings are short; rows are many), not a
    python loop per byte — the exchange-path fix for VERDICT weak #3."""
    offsets = block.offsets.astype(np.int64)
    data = block.data
    lengths = offsets[1:] - offsets[:-1]
    n = len(lengths)
    h = np.full(n, _FNV_OFFSET, dtype=np.uint64)
    if n == 0:
        return h
    # active-set shrink keeps this O(total_bytes): each pass only touches
    # rows still longer than j, so one long outlier string doesn't make
    # every row pay for its length
    active = np.flatnonzero(lengths > 0)
    j = 0
    with np.errstate(over="ignore"):
        while active.size:
            b = data[offsets[active] + j].astype(np.uint64)
            h[active] = (h[active] ^ b) * _FNV_PRIME
            j += 1
            active = active[lengths[active] > j]
    return h


def _hash_block(typ: Type, block: Block, n: int) -> np.ndarray:
    """Per-row uint64 value hash of one column."""
    if isinstance(typ, (VarcharType, CharType)):
        if isinstance(block, DictionaryBlock):
            # hash the (small) dictionary once, then one gather per page
            inner = decode_to_flat(block.dictionary)
            entry_hash = _fnv1a64_rows(inner)
            if inner.nulls is not None:
                entry_hash = np.where(inner.nulls, _NULL_HASH, entry_hash)
            return entry_hash[block.ids]
        flat = decode_to_flat(block)
        h = _fnv1a64_rows(flat)
        if flat.nulls is not None:
            h = np.where(flat.nulls, _NULL_HASH, h)
        return h
    flat = decode_to_flat(block)
    values = flat.values
    if values.dtype.kind == "f":
        values = values.view(np.uint64 if values.itemsize == 8 else np.uint32)
    h = _splitmix64(values.astype(np.int64).view(np.uint64))
    if flat.may_have_null:
        h = np.where(flat.null_mask(), _NULL_HASH, h)
    return h


def partition_targets(page: Page, types: List[Type], key_indices: List[int],
                      n_parts: int) -> np.ndarray:
    """Row -> target partition, combining the key columns' value hashes."""
    n = page.position_count
    h = np.full(n, np.uint64(1), dtype=np.uint64)
    with np.errstate(over="ignore"):
        for i in key_indices:
            hv = _hash_block(types[i], page.blocks[i], n)
            h = _splitmix64(h * np.uint64(31) + hv)
    return (h % np.uint64(n_parts)).astype(np.int64)


class StageAbortedError(RuntimeError):
    """A sibling task of the same stage failed terminally: this task (or
    this in-flight exchange drain) stops early instead of finishing work
    whose stage is already doomed — the in-process analog of the worker
    protocol's should_abort propagation."""


def _block_bytes(b: Block) -> int:
    """Host bytes one block occupies on the exchange wire (the page-split
    path's analog of the ICI path's device-buffer accounting)."""
    if isinstance(b, DictionaryBlock):
        n = b.ids.nbytes + _block_bytes(b.dictionary)
    elif isinstance(b, VariableWidthBlock):
        n = b.data.nbytes + b.offsets.nbytes
    elif isinstance(b, FixedWidthBlock):
        n = b.values.nbytes
    else:  # RunLengthBlock and friends: count the payload if it has one
        inner = getattr(b, "value", None)
        n = _block_bytes(inner) if inner is not None else 0
    nulls = getattr(b, "nulls", None)
    return n + (nulls.nbytes if nulls is not None else 0)


def _page_bytes(page: Page) -> int:
    return sum(_block_bytes(b) for b in page.blocks)


def split_page(page: Page, targets: np.ndarray, n_parts: int) -> List[Page]:
    out = []
    for p in range(n_parts):
        idx = np.flatnonzero(targets == p)
        if len(idx) == 0:
            out.append(None)
            continue
        out.append(Page([b.take(idx) for b in page.blocks], len(idx)))
    return out


# ---------------------------------------------------------------------------
# stage / buffer model
# ---------------------------------------------------------------------------

class OutputBuffers:
    """Per-fragment output: buffers[producer_task][partition] -> [Page].

    Partition semantics by output scheme (reference OutputBuffers):
      SINGLE            everything in partition 0 (gather consumers)
      FIXED_HASH        partition = hash(keys) % consumer task count
      FIXED_BROADCAST   partition 0 holds the full output; every consumer
                        task reads it (BroadcastOutputBuffer)
    """

    def __init__(self, n_tasks: int, n_partitions: int, broadcast: bool):
        self.broadcast = broadcast
        # runtime partitioned->broadcast flip (InProcessScheduler.
        # _adapt_exchanges): every consumer reads the UNION of the hash
        # partitions — the full producer output — instead of its slice
        self.read_all = False
        self.pages: List[Dict[int, List[Page]]] = [
            {p: [] for p in range(max(1, n_partitions))}
            for _ in range(n_tasks)]

    def add(self, task: int, partition: int, page: Page) -> None:
        self.pages[task][partition].append(page)

    def reset_task(self, task: int) -> None:
        """Drop a task's staged output (retry must not duplicate rows)."""
        self.pages[task] = {p: [] for p in self.pages[task]}

    def materialize(self, stage_dir: str) -> None:
        """Spill every (task, partition) page list to a shuffle file and
        replace the in-memory lists with lazy file readers — the batch
        (Presto-on-Spark) mode's durable-exchange step
        (presto_cpp/main/operators/ShuffleWrite / LocalPersistentShuffle
        semantics over SerializedPage framing)."""
        import os

        from ..common.serde import deserialize_page, serialize_page
        os.makedirs(stage_dir, exist_ok=True)

        class _FilePages:
            def __init__(self, path: str, count: int):
                self.path, self.count = path, count

            def __iter__(self):
                with open(self.path, "rb") as f:
                    raw = f.read()
                pos = 0
                for _ in range(self.count):
                    page, pos = deserialize_page(raw, pos)
                    yield page

            def __len__(self):
                return self.count

        for ti, parts in enumerate(self.pages):
            for p, pages in parts.items():
                if not isinstance(pages, list):
                    continue
                path = os.path.join(stage_dir, f"t{ti}_p{p}.shuffle")
                with open(path, "wb") as f:
                    for page in pages:
                        f.write(serialize_page(page))
                parts[p] = _FilePages(path, len(pages))

    def pages_for_consumer(self, consumer_task: int) -> List[Page]:
        out: List[Page] = []
        if self.read_all:
            for task_pages in self.pages:
                for part in sorted(task_pages):
                    out.extend(task_pages[part])
            return out
        part = 0 if self.broadcast else consumer_task
        for task_pages in self.pages:
            out.extend(task_pages.get(part, ()))
        return out


@dataclass
class StageInfo:
    fragment: P.PlanFragment
    children: List["StageInfo"]
    n_tasks: int = 1
    n_partitions: int = 1      # consumer task count (output fan-out)
    buffers: Optional[OutputBuffers] = None
    # ICI exchange result: consumer task -> list of device-resident chunk
    # Batches (rows whose hash targets that consumer, one Batch per
    # exchange chunk), plus the producer's output column order for
    # positional renaming at the consumer
    device_out: Optional[list] = None
    out_names: Optional[List[str]] = None
    # resolved fabric of this stage's OUTPUT edge ("http" | "ici",
    # parallel/fabric.py; None for the root stage) + why, set by
    # _plan_fabrics before partition assignment
    fabric: Optional[str] = None
    fabric_reason: Optional[str] = None
    # set when the first task of this stage fails terminally: sibling
    # tasks and in-flight exchange consumers abort promptly instead of
    # draining a doomed stage (threading.Event)
    abort: object = None
    # concurrency telemetry: per-task wall seconds and the stage wall —
    # overlap quality = stage_wall / sum(task_walls)
    task_walls: Optional[List[float]] = None
    stage_wall: Optional[float] = None


class InProcessScheduler:
    """Executes a SubPlan bottom-up.  Tasks run sequentially here; the HTTP
    worker runtime (worker/) and the ICI exchange (parallel/) distribute the
    same stage graph across processes/chips."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        import threading
        self.config = config or SchedulerConfig()
        from ..utils.runtime_stats import RuntimeStats
        # per-query fabric-tagged exchange stats (bytes moved, dispatch /
        # wait / drain walls), merged into QueryResult.runtime_stats by
        # DistributedQueryRunner — the RuntimeStats face of the same
        # surface FABRIC_METRICS exposes process-wide
        self.stats = RuntimeStats()
        # EXPLAIN ANALYZE sink: set to {} by the caller to collect the
        # per-plan-node operator stats of EVERY task, merged across tasks
        # (rows/bytes/batches/walls summed) — the coordinator-side rollup
        # the fragment annotations are printed from
        self.node_stats: Optional[Dict[str, dict]] = None
        self._stats_lock = threading.Lock()
        # span-recording tracer (utils/runtime_stats.Tracer); spans open
        # per fragment and per task under the caller's "query" span
        self.tracer = None
        # query-level memory context (created per execute()): every task
        # gets a CHILD context over ONE shared arbitrated pool, so the
        # query's aggregate reservation — and its revocable holders — are
        # visible in one place.  Budgeted unpinned stages already run
        # their tasks sequentially, so the shared pool never sees two
        # tasks' peaks stacked.
        self.memory: Optional["MemoryContext"] = None
        # adaptive execution: the per-query dynamic-filter collector plus
        # the exchange-decision log (exec/adaptive.py).  _dyn_filters is
        # the SHARED wire-form map handed to every TaskContext — scans
        # read it lazily, so summaries collected from a finished build
        # stage prune scans of later stages without any recompile.
        self.adaptive = AdaptiveState(DynamicFilterCollector(
            self.config.exec_config.dynamic_filtering_max_distinct))
        self._dyn_filters: Dict[str, dict] = {}

    # -- planning the stage tree -----------------------------------------
    def _build_stages(self, subplan: P.SubPlan) -> StageInfo:
        children = [self._build_stages(c) for c in subplan.children]
        frag = subplan.fragment
        if frag.partitioning == P.SOURCE_DISTRIBUTION:
            n_tasks = self.config.source_tasks
        elif frag.partitioning == P.FIXED_HASH_DISTRIBUTION:
            n_tasks = self.config.hash_tasks
        else:
            n_tasks = 1
        return StageInfo(frag, children, n_tasks)

    def _plan_fabrics(self, stage: StageInfo) -> None:
        """Resolve the fabric of every remote-exchange edge and CHOOSE
        task counts to fit the mesh: an ICI edge needs producer and
        consumer tasks pinned 1:1 to mesh devices, so both endpoint
        stages of an eligible hashed edge get n_tasks = mesh size
        (generalizing the old eligibility test, which only engaged when
        the configured task count happened to equal the mesh size).
        Runs BEFORE _assign_partitions so the chosen counts drive the
        output fan-out.  Mirrors sql/fragmenter.annotate_exchange_fabrics
        (both call parallel/fabric.resolve_fabric) and honors a
        pre-annotated scheme.fabric, writing the resolution back for
        EXPLAIN/stats parity."""
        from ..parallel.fabric import FABRIC_HTTP, FABRIC_ICI, resolve_fabric
        msize = self._mesh_size()
        requested = self.config.exec_config.exchange_fabric
        child_by_fid = {c.fragment.fragment_id: c for c in stage.children}
        for node in P.walk_plan(stage.fragment.root):
            if not isinstance(node, P.RemoteSourceNode):
                continue
            edges = []
            for fid in node.source_fragment_ids:
                child = child_by_fid.get(fid)
                if child is None:
                    continue
                scheme = child.fragment.output_partitioning_scheme
                fabric, why = resolve_fabric(
                    scheme.fabric or requested, handle=scheme.handle,
                    producer_partitioning=child.fragment.partitioning,
                    consumer_partitioning=stage.fragment.partitioning,
                    mesh_size=msize, batch_mode=self.config.batch_mode)
                edges.append((child, scheme, fabric, why))
            # a multi-source reader consumes all-device or nothing: mixed
            # resolutions demote every edge of this reader to http
            if len({f for _, _, f, _ in edges}) > 1:
                edges = [(c, s, FABRIC_HTTP, "mixed-fabric source set")
                         for c, s, _, w in edges]
            for child, scheme, fabric, why in edges:
                child.fabric = scheme.fabric = fabric
                child.fabric_reason = why
                if fabric == FABRIC_ICI:
                    child.n_tasks = msize
                    stage.n_tasks = msize
        for child in stage.children:
            self._plan_fabrics(child)

    def _assign_partitions(self, stage: StageInfo,
                           consumer_tasks: int) -> None:
        stage.n_partitions = consumer_tasks
        handle = stage.fragment.output_partitioning_scheme.handle
        broadcast = handle == P.FIXED_BROADCAST_DISTRIBUTION
        n_parts = 1 if handle in (P.SINGLE_DISTRIBUTION,) or broadcast \
            else consumer_tasks
        stage.buffers = OutputBuffers(stage.n_tasks, n_parts, broadcast)
        for c in stage.children:
            self._assign_partitions(c, stage.n_tasks)

    # -- execution --------------------------------------------------------
    def execute(self, subplan: P.SubPlan) -> Iterator[Page]:
        from .memory import MemoryContext, MemoryPool
        cfg = self.config.exec_config
        self.memory = MemoryContext(
            MemoryPool(cfg.memory_budget_bytes), "query",
            max_bytes=cfg.memory_max_query_bytes)
        root = self._build_stages(subplan)
        self._plan_fabrics(root)
        self._assign_partitions(root, 1)
        self._run_stage(root)
        yield from root.buffers.pages_for_consumer(0)

    def _mesh_size(self) -> int:
        from ..parallel.mesh import mesh_size
        return mesh_size(self.config.mesh)

    def _batch_dir(self, fragment_id: str) -> str:
        """Shuffle-file directory for one stage (batch mode)."""
        import os
        if self.config.temp_dir is None:
            import tempfile
            self._tmp = getattr(self, "_tmp", None) \
                or tempfile.TemporaryDirectory(prefix="presto_tpu_shuffle_")
            base = self._tmp.name
        else:
            base = self.config.temp_dir
        return os.path.join(base, f"stage_{fragment_id}")

    # -- adaptive exchange strategy ---------------------------------------
    def _observed_rows(self, side, child_by_fid) -> Optional[int]:
        """Rows a completed child stage actually produced behind one join
        side, or None when they cannot be counted without device syncs /
        file reads (ICI device output, batch-mode shuffle files) or the
        side is not a direct remote source."""
        while isinstance(side, P.FilterNode):
            side = side.source
        if not isinstance(side, P.RemoteSourceNode):
            return None
        total = 0
        for fid in side.source_fragment_ids:
            ch = child_by_fid.get(fid)
            if ch is None or ch.buffers is None \
                    or ch.device_out is not None:
                return None
            for task_pages in ch.buffers.pages:
                for pages in task_pages.values():
                    if not isinstance(pages, list):
                        return None
                    total += sum(p.position_count for p in pages)
        return total

    def _adapt_exchanges(self, stage: StageInfo) -> None:
        """Re-decide exchange strategy at the stage boundary, AFTER the
        producer stages ran but BEFORE this consumer stage launches —
        the point where observed cardinality is free and the decision is
        still cheap to change (reference: adaptive join reordering /
        runtime broadcast in Presto-on-Spark's adaptive mode).

        Two moves, both plan mutations on the consumer fragment only:

        - INNER side swap: when the observed build is far larger than
          the observed probe, build the probe instead (same hash, same
          partition alignment — only the roles flip).
        - partitioned -> broadcast: when the observed build undershoots
          the planner's estimate by ADAPTIVE_RATIO and fits the
          broadcast threshold, every consumer task reads the UNION of
          the build's hash partitions (OutputBuffers.read_all) so the
          downstream join sees the full build side; the probe stays
          partitioned, so no output row duplicates.  FULL joins are
          excluded — their unmatched-build emission would duplicate
          across tasks."""
        if not self.config.exec_config.adaptive_exchange:
            return
        child_by_fid = {c.fragment.fragment_id: c
                        for c in stage.children}
        for node in P.walk_plan(stage.fragment.root):
            if not isinstance(node, P.JoinNode) \
                    or node.distribution != P.PARTITIONED \
                    or node.join_type not in (P.INNER, P.LEFT):
                continue
            observed_b = self._observed_rows(node.right, child_by_fid)
            observed_p = self._observed_rows(node.left, child_by_fid)
            acted = False
            if node.join_type == P.INNER and observed_b is not None \
                    and observed_p is not None \
                    and decide_side_swap(observed_p, observed_b):
                node.left, node.right = node.right, node.left
                node.criteria = [(r, l) for l, r in node.criteria]
                detail = (f"planned build {observed_b} rows >= 2x "
                          f"probe {observed_p}; sides swapped")
                observed_p, observed_b = observed_b, observed_p
                self.adaptive.record(ExchangeDecision(
                    node.id, "swap_sides", node.planned_build_rows,
                    observed_b, detail))
                self.stats.add("adaptiveSideSwaps", 1)
                acted = True
            if observed_b is not None and decide_exchange(
                    node.planned_build_rows, observed_b,
                    self.config.broadcast_threshold):
                side = node.right
                while isinstance(side, P.FilterNode):
                    side = side.source
                for fid in side.source_fragment_ids:
                    child_by_fid[fid].buffers.read_all = True
                node.distribution = P.REPLICATED
                self.adaptive.record(ExchangeDecision(
                    node.id, "broadcast", node.planned_build_rows,
                    observed_b,
                    f"observed {observed_b} rows vs planned "
                    f"{node.planned_build_rows}"))
                self.stats.add("adaptiveExchangeFlips", 1)
                acted = True
            if not acted and observed_b is not None:
                self.adaptive.record(ExchangeDecision(
                    node.id, "keep", node.planned_build_rows, observed_b))

    def _run_stage(self, stage: StageInfo) -> None:
        # dynamic-filter producers run before sibling consumers: stage
        # execution here is sequential bottom-up, so finishing the build
        # side first means its summaries are already collected when the
        # probe-side scan stage launches (the HTTP runtime instead waits
        # the bounded dynamic-filtering.wait-timeout — worker/task.py)
        for child in sorted(
                stage.children,
                key=lambda c: not c.fragment.dynamic_filter_sources):
            self._run_stage(child)
        self._adapt_exchanges(stage)
        frag = stage.fragment
        scheme = frag.output_partitioning_scheme
        out_names = [v.name for v in frag.root.output_variables]
        out_types = [v.type for v in frag.root.output_variables]
        key_indices = [out_names.index(a.name) for a in scheme.arguments]
        hashed = scheme.handle == P.FIXED_HASH_DISTRIBUTION
        stage.out_names = out_names

        # producer-side dynamic-filter summarization: the fragmenter
        # marked which of this fragment's output columns feed downstream
        # filters (PlanFragment.dynamic_filter_sources); each task folds
        # its output pages into one summary per filter id as they stream
        max_distinct = \
            self.config.exec_config.dynamic_filtering_max_distinct
        dyn_idx: List[Tuple[int, str]] = (
            [(out_names.index(col), fid)
             for col, fid in frag.dynamic_filter_sources.items()
             if col in out_names]
            if self.config.exec_config.dynamic_filtering else [])

        # fabric resolution happened in _plan_fabrics (SURVEY.md §5.8:
        # intra-pod hash exchange rides ICI; gather / broadcast /
        # cross-process edges keep the page path).  The task-count
        # re-check is defensive: _plan_fabrics chose n_tasks to fit the
        # mesh, so an ICI stage that no longer matches is a planner bug
        # better demoted than crashed
        from ..parallel.fabric import FABRIC_ICI, FABRIC_METRICS
        mesh = self.config.mesh
        ici = (stage.fabric == FABRIC_ICI and hashed
               and stage.n_partitions > 1
               and stage.n_tasks == stage.n_partitions
               and stage.n_tasks == self._mesh_size())

        # split assignment per scan node: task i takes splits[i::n]
        scan_splits: Dict[str, List] = {}
        for node in P.walk_plan(frag.root):
            if isinstance(node, P.TableScanNode):
                th = node.table
                sf = dict(th.extra).get("scaleFactor", 0.01)
                n_splits = max(stage.n_tasks,
                               self.config.exec_config.splits_per_scan)
                scan_splits[node.id] = catalog.make_splits(
                    th.table_name, sf, n_splits, th.connector_id)

        remote_nodes = [n for n in P.walk_plan(frag.root)
                        if isinstance(n, P.RemoteSourceNode)]
        child_by_fid = {c.fragment.fragment_id: c for c in stage.children}

        # consuming device shards requires task<->device pinning too;
        # a node mixing device and page children, or device children whose
        # string dictionaries disagree, reads everything as pages (the
        # device children are converted lazily in _remote_reader)
        device_inputs = {}
        for rnode in remote_nodes:
            sources = [child_by_fid[fid]
                       for fid in rnode.source_fragment_ids]
            device_inputs[rnode.id] = (
                all(s.device_out is not None for s in sources)
                and _device_dicts_agree(sources))
        pin = (ici or any(device_inputs.values())) \
            and stage.n_tasks == self._mesh_size()
        devices = (list(mesh.devices.flat)
                   if pin or ici else [None] * stage.n_tasks)

        import contextlib
        import threading
        import time as _time
        import jax

        # first terminal task failure aborts siblings and any in-flight
        # ICI consumption promptly (the in-process analog of the worker
        # protocol's should_abort propagation)
        stage.abort = abort = threading.Event()

        # one traced program per stage, shared by its tasks (the tasks
        # compile byte-identical step closures; Python tracing is
        # GIL-serialized, so without sharing an N-task stage pays N
        # traces on one core — PlanCompiler.shared_jit)
        stage_jits: Dict = {}

        # lifespan sharding: a grouped-eligible source stage gives every
        # task the FULL split set plus a disjoint round-robin subset of
        # the bucket layout — K lifespans spread over N tasks instead of
        # each task re-bucketing a split subset (which _full_coverage
        # would reject, forfeiting grouped execution entirely)
        from .grouped import stage_shards_lifespans
        grouped_shards = (
            stage.n_tasks > 1
            and frag.partitioning == P.SOURCE_DISTRIBUTION
            and stage_shards_lifespans(frag.root,
                                       self.config.exec_config))

        def run_task(task_index: int):
            """One task's fragment execution; returns (batch-or-None for
            ICI stages, wall seconds)."""
            t0 = _time.perf_counter()  # lint: allow-wall-clock
            # thread CPU time at the driver boundary: each task runs on
            # its own thread, so thread_time isolates ITS compute from
            # the waits (device sync, exchange, sibling contention) that
            # wall time folds in — the /v1/query and EXPLAIN ANALYZE
            # CPU-vs-wall attribution
            c0 = _time.thread_time()
            # device-pinned concurrent tasks keep PER-TASK pools (each
            # owns a device, so budgets must not stack in one pool);
            # everything else charges a child of the query context
            task_mem = None
            if self.memory is not None:
                if pin and stage.n_tasks > 1 \
                        and self.memory.budget is not None:
                    from .memory import MemoryContext, MemoryPool
                    task_mem = MemoryContext(
                        MemoryPool(self.memory.budget),
                        f"task/{stage.fragment.fragment_id}.{task_index}",
                        max_bytes=self.config.exec_config
                        .memory_max_query_bytes)
                else:
                    task_mem = self.memory.new_child(
                        f"task/{stage.fragment.fragment_id}.{task_index}")
            ctx = TaskContext(config=self.config.exec_config,
                              task_index=task_index,
                              shared_jits=stage_jits,
                              memory=task_mem,
                              runtime_stats=self.stats,
                              dynamic_filters=self._dyn_filters)
            if self.node_stats is not None:
                # EXPLAIN ANALYZE: per-node operator stats, merged into
                # the query-level rollup after the task drains
                ctx.stats = {}
            if grouped_shards:
                ctx.grouped_shard = (task_index, stage.n_tasks)
            for node_id, splits in scan_splits.items():
                ctx.splits[node_id] = (list(splits) if grouped_shards
                                       else splits[task_index::stage.n_tasks])
            for rnode in remote_nodes:
                sources = [child_by_fid[fid] for fid in
                           rnode.source_fragment_ids]
                if device_inputs[rnode.id] and pin:
                    ctx.remote_batches[rnode.id] = _device_reader(
                        sources, task_index, rnode, abort=abort,
                        stats=self.stats)
                else:
                    ctx.remote_pages[rnode.id] = _remote_reader(
                        sources, task_index,
                        client_threads=
                        self.config.exec_config.exchange_client_threads)
            compiler = PlanCompiler(ctx)
            dev_ctx = (jax.default_device(devices[task_index])
                       if pin else contextlib.nullcontext())
            span_ctx = (self.tracer.span(
                f"task {frag.fragment_id}.{task_index}",
                parent=f"fragment {frag.fragment_id}",
                task_index=task_index)
                if self.tracer is not None else contextlib.nullcontext())
            out = None
            split_wall, split_bytes = 0.0, 0
            task_sums: Dict[str, object] = {}
            with span_ctx, dev_ctx:
                if ici:
                    # device path: output stays device-resident; a host
                    # summarization sync here would serialize the async
                    # exchange dispatch, so ICI edges publish nothing
                    # (absent summary == unknown == prune nothing)
                    from .pipeline import _compact_concat
                    batches = [b for b in
                               compiler.run_to_batches(frag.root)]
                    out = _compact_concat(batches) if batches else None
                else:
                    for page in compiler.run_to_pages(frag.root):
                        if abort.is_set():
                            raise StageAbortedError(
                                f"sibling task of stage "
                                f"{frag.fragment_id} failed")
                        for j, fid in dyn_idx:
                            s = _summarize_page_block(
                                fid, page.blocks[j], max_distinct)
                            prev = task_sums.get(fid)
                            task_sums[fid] = s if prev is None \
                                else prev.merge(s, max_distinct)
                        if hashed and stage.n_partitions > 1:
                            s0 = _time.perf_counter()  # lint: allow-wall-clock
                            targets = partition_targets(
                                page, out_types, key_indices,
                                stage.n_partitions)
                            for p, sub in enumerate(
                                    split_page(page, targets,
                                               stage.n_partitions)):
                                if sub is not None:
                                    stage.buffers.add(task_index, p, sub)
                            split_wall += _time.perf_counter() - s0  # lint: allow-wall-clock
                            split_bytes += _page_bytes(page)
                        else:
                            stage.buffers.add(task_index, 0, page)
            if dyn_idx and not ici:
                # a task that produced no pages still publishes EMPTY
                # summaries — a zero-row build side legitimately prunes
                # every downstream chunk (min>max convention), which is
                # different from "never heard back" (prunes nothing)
                for _j, fid in dyn_idx:
                    if fid not in task_sums:
                        task_sums[fid] = DynamicFilterSummary(
                            fid, row_count=0)
                for s in task_sums.values():
                    self.adaptive.collector.publish(s)
            if self.node_stats is not None and ctx.stats:
                with self._stats_lock:
                    merge_node_stats(self.node_stats, ctx.stats)
            if self.tracer is not None and ctx.stats:
                # operator spans close out the query->fragment->task->
                # operator hierarchy; operators stream interleaved so their
                # intervals don't nest in real time — each span is emitted
                # at task end carrying its measured wall as an attribute
                for nid, s in ctx.stats.items():
                    with self.tracer.span(
                            f"operator {frag.fragment_id}.{task_index}."
                            f"{nid}",
                            parent=f"task {frag.fragment_id}.{task_index}",
                            plan_node_id=nid,
                            operator=s.get("operatorType", ""),
                            rows=s.get("rows", 0),
                            wall_s=s.get("wall_s", 0.0)):
                        pass
            if split_bytes or split_wall:
                # stats parity with the ICI path: the hashed page path IS
                # the http fabric in-process (its pages move host-side,
                # and cross-process they ride the ExchangeClient wire)
                FABRIC_METRICS.record(
                    "http", exchanges=1, chunks=1, bytes_moved=split_bytes,
                    host_bytes=split_bytes, exchange_wall_s=split_wall)
                self.stats.add("exchangeFabricHttpBytes", split_bytes,
                               "BYTE")
                self.stats.add("exchangeFabricHttpExchangeWallNanos",
                               split_wall * 1e9, "NANO")
            wall = _time.perf_counter() - t0  # lint: allow-wall-clock
            self.stats.add("driverCpuNanos",
                           (_time.thread_time() - c0) * 1e9, "NANO")
            self.stats.add("driverWallNanos", wall * 1e9, "NANO")
            return out, wall

        def run_task_retrying(task_index: int):
            """Batch (Presto-on-Spark) mode: a failed task re-runs from
            its materialized inputs (children already spilled their
            shuffle files), the recoverable-execution contract
            (PrestoSparkTaskExecutorFactory retry via Spark /
            RECOVERABLE_GROUPED_EXECUTION).  Streaming mode keeps
            fail-fast MPP semantics (task_retries=0).  Retry is gated by
            the shared error classifier (ErrorClassifier.java analog):
            USER_ERROR — bad SQL, bad input — fails fast; only
            infrastructure-shaped failures consume retry attempts."""
            from ..common.errors import is_retryable
            attempts = 1 + max(0, self.config.task_retries)
            for attempt in range(attempts):
                if abort.is_set():
                    raise StageAbortedError(
                        f"sibling task of stage {frag.fragment_id} failed")
                try:
                    if self.config.fault_injector is not None:
                        self.config.fault_injector(
                            frag.fragment_id, task_index, attempt)
                    return run_task(task_index)
                except StageAbortedError:
                    raise               # echo of a sibling's failure
                except Exception as e:
                    stage.buffers.reset_task(task_index)
                    if attempt + 1 >= attempts or not is_retryable(e):
                        # terminal: stop siblings and any in-flight ICI
                        # consumers of this stage promptly
                        abort.set()
                        raise
            return None, 0.0

        # a stage's N tasks run CONCURRENTLY (reference
        # SqlStageExecution.scheduleTask / the worker TaskExecutor thread
        # pool): each task's host syncs release the GIL while waiting on
        # its device, so other tasks keep dispatching — stage wall
        # approaches the slowest task, not the sum.  jax.default_device
        # is thread-local, so per-device pinning survives threading.
        stage_t0 = _time.perf_counter()  # lint: allow-wall-clock
        # concurrency requires memory isolation: pinned tasks own their
        # device; unpinned tasks share one device, so when a memory
        # budget is configured their independent per-task pools would
        # stack to n_tasks x budget — run those sequentially
        concurrent = stage.n_tasks > 1 and (
            pin or self.config.exec_config.memory_budget_bytes is None)
        # fabric/partitioning ride on the fragment span so an exported
        # OTLP trace (telemetry/otlp.py) shows which wire each inter-stage
        # edge took without joining against EXPLAIN output
        frag_span = (self.tracer.span(
            f"fragment {frag.fragment_id}",
            parent="query",
            n_tasks=stage.n_tasks,
            partitioning=str(frag.partitioning),
            fabric=str(getattr(frag.output_partitioning_scheme,
                               "fabric", None) or "http"))
                     if self.tracer is not None
                     else contextlib.nullcontext())
        with frag_span:
            if not concurrent:
                results = [run_task_retrying(i)
                           for i in range(stage.n_tasks)]
            else:
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(
                        max_workers=stage.n_tasks) as pool_ex:
                    results = list(pool_ex.map(run_task_retrying,
                                               range(stage.n_tasks)))
        task_batches = [r[0] for r in results]
        stage.task_walls = [round(r[1], 4) for r in results]
        stage.stage_wall = round(
            _time.perf_counter() - stage_t0, 4)  # lint: allow-wall-clock
        if dyn_idx and not ici:
            # the stage is complete, so each filter's merged summary is
            # final: expose it to every LATER stage's tasks through the
            # shared wire-form map (late binding — scans read it at
            # split drain time)
            ready = {}
            for _j, fid in dyn_idx:
                s = self.adaptive.collector.get(fid)
                if s is not None:
                    ready[fid] = s
            if ready:
                self._dyn_filters.update(summaries_to_runtime(ready))
                self.stats.add("dynamicFiltersCollected", len(ready))
        if ici:
            keys = tuple(out_names[i] for i in key_indices)
            if not self._ici_exchange(stage, task_batches, keys):
                # metadata disagreement across tasks (dictionaries /
                # schema / ARRAY columns): demote this edge to the page
                # fabric — correctness over the fast path
                from ..parallel.fabric import FABRIC_HTTP
                FABRIC_METRICS.record("ici", fallbacks=1)
                self.stats.add("exchangeFabricIciFallbacks", 1)
                stage.fabric = FABRIC_HTTP
                stage.fabric_reason = \
                    "runtime fallback: task batch metadata disagreed"
                self._spill_batches_to_pages(
                    stage, task_batches, out_names, out_types,
                    key_indices)
        if self.config.batch_mode and stage.device_out is None:
            # durable inter-stage exchange (the Spark-shuffle analog)
            stage.buffers.materialize(self._batch_dir(frag.fragment_id))

    # -- ICI exchange -----------------------------------------------------
    _exch_cache: Dict = {}

    def _ici_exchange(self, stage: StageInfo, task_batches: List,
                      keys: Tuple[str, ...]) -> bool:
        """all_to_all the per-task output batches across the mesh in
        fixed-size row chunks; on success stage.device_out[consumer]
        holds that consumer's rows device-resident as a list of chunk
        Batches.  Returns False when per-task batch metadata
        (dictionaries / null-ness / schema / ARRAY columns) disagrees
        with what the exchange kernel can carry — the caller then falls
        back to the page exchange.

        Chunking is what buys compute/collective overlap: with quota ==
        chunk rows, bucket overflow is STATICALLY impossible (a chunk of
        C rows per device can never put more than C rows in one bucket),
        so every chunk's collective is dispatched back-to-back with zero
        host syncs and JAX async dispatch keeps chunk k+1 on the wire
        while the consumer computes on chunk k (_device_reader measures
        the wait it actually eats).  The compiled exchange is keyed on
        (devices, keys, chunk rows) — NOT per-stage row counts — so one
        program and its donated staging buffers are reused across chunks
        and stages instead of re-padding to a fresh global max."""
        import time as _time

        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from ..exec.batch import Batch, Column
        from ..parallel.exchange import make_partitioned_exchange
        from ..parallel.fabric import FABRIC_METRICS
        from ..parallel.mesh import WORKER_AXIS
        mesh = self.config.mesh
        devices = list(mesh.devices.flat)
        n = stage.n_tasks

        template = next((b for b in task_batches if b is not None), None)
        if template is None:
            stage.device_out = [[] for _ in range(n)]
            return True
        # schema/metadata must agree across tasks (scan dictionaries are
        # table-stable, so they normally do); ARRAY columns carry a
        # ragged `lengths` companion the exchange kernel doesn't ship
        if any(c.lengths is not None for c in template.columns.values()):
            return False
        tstruct = _batch_meta(template)
        for b in task_batches:
            if b is not None and _batch_meta(b) != tstruct:
                return False

        t0 = _time.perf_counter()  # lint: allow-wall-clock
        # ONE device->host transfer covers every task's live-row count
        # (the _compact_concat idiom) — the only host sync on this path;
        # the old per-task device_get loop serialized n round-trips
        present = [b for b in task_batches if b is not None]
        counts = jax.device_get(  # lint: allow-host-sync
            [b.mask.sum() for b in present])
        max_live = max((int(c) for c in counts), default=0)

        # explicit exchange.ici-chunk-rows pins the chunk size; the
        # default (0) asks the tuner, which adapts the NEXT run's size
        # from this run's observed compute/collective overlap
        from ..parallel.fabric import ICI_CHUNK_TUNER
        rows_cfg = int(self.config.exec_config.ici_chunk_rows)
        C = rows_cfg if rows_cfg >= 1 else ICI_CHUNK_TUNER.chunk_rows()
        n_chunks = max(1, -(-max_live // C))
        B = n_chunks * C

        from .pipeline import _jit_compact
        norm = []
        for i, b in enumerate(task_batches):
            with jax.default_device(devices[i]):
                # compact packs live rows into a contiguous prefix, so
                # the fixed-size chunk slices below tile the live set
                nb = (_zeros_like_batch(template, B) if b is None
                      else _jit_compact(b, B))
            norm.append(nb)

        sharding = NamedSharding(mesh, PartitionSpec(WORKER_AXIS))

        def to_global(arrays):
            arrays = [jax.device_put(a, devices[i])
                      for i, a in enumerate(arrays)]
            shape = (n * C,) + arrays[0].shape[1:]
            return jax.make_array_from_single_device_arrays(
                shape, sharding, arrays)

        key = (tuple(devices), keys, C)
        exch = self._exch_cache.get(key)
        if exch is None:
            exch = make_partitioned_exchange(mesh, keys, quota=C,
                                             donate=True)
            self._exch_cache[key] = exch

        abort = stage.abort
        chunk_outs = []
        bytes_moved = 0
        for k in range(n_chunks):
            if abort is not None and abort.is_set():
                raise StageAbortedError(
                    f"stage {stage.fragment.fragment_id} aborted "
                    f"mid-exchange")
            lo, hi = k * C, (k + 1) * C
            cols = {}
            for name, c in template.columns.items():
                values = to_global(
                    [nb.columns[name].values[lo:hi] for nb in norm])
                nulls = (to_global([nb.columns[name].null_mask()[lo:hi]
                                    for nb in norm])
                         if c.nulls is not None else None)
                cols[name] = Column(values, nulls, c.dictionary, c.lazy)
                bytes_moved += values.nbytes + (
                    nulls.nbytes if nulls is not None else 0)
            gmask = to_global([nb.mask[lo:hi] for nb in norm])
            bytes_moved += gmask.nbytes
            # overflow is statically impossible at quota == C, so the
            # flag is DROPPED without a host read — nothing in this loop
            # blocks, which is the whole overlap story
            out, _overflow = exch(Batch(cols, gmask))
            chunk_outs.append(out)

        stage.device_out = [[] for _ in range(n)]
        for out in chunk_outs:
            for i in range(n):
                ccols = {}
                for name, c in out.columns.items():
                    ccols[name] = Column(
                        _shard_on(c.values, devices[i]),
                        (_shard_on(c.nulls, devices[i])
                         if c.nulls is not None else None),
                        c.dictionary, c.lazy)
                stage.device_out[i].append(
                    Batch(ccols, _shard_on(out.mask, devices[i])))
        wall = _time.perf_counter() - t0  # lint: allow-wall-clock
        FABRIC_METRICS.record("ici", exchanges=1, chunks=n_chunks,
                              bytes_moved=bytes_moved,
                              exchange_wall_s=wall)
        if rows_cfg < 1:
            # auto-tune feedback: the consumer-side walls land in
            # FABRIC_METRICS as the stage drains, so the fraction seen
            # here reflects completed exchanges up to this one
            ICI_CHUNK_TUNER.observe(FABRIC_METRICS.overlap_fraction("ici"))
        self.stats.add("exchangeFabricIciBytes", bytes_moved, "BYTE")
        self.stats.add("exchangeFabricIciChunks", n_chunks)
        self.stats.add("exchangeFabricIciDispatchWallNanos",
                       wall * 1e9, "NANO")
        return True

    def _spill_batches_to_pages(self, stage: StageInfo, task_batches,
                                out_names, out_types, key_indices) -> None:
        from .batch import batch_to_page
        for task_index, b in enumerate(task_batches):
            if b is None:
                continue
            page = batch_to_page(b, out_names, out_types)
            if not page.position_count:
                continue
            targets = partition_targets(page, out_types, key_indices,
                                        stage.n_partitions)
            for p, sub in enumerate(
                    split_page(page, targets, stage.n_partitions)):
                if sub is not None:
                    stage.buffers.add(task_index, p, sub)


def _summarize_page_block(fid: str, block: Block,
                          max_distinct: int) -> DynamicFilterSummary:
    """Dynamic-filter summary over one output page column (host blocks).
    Variable-width (string) keys publish the row count only: zone maps
    hold stored-unit ints, but a zero-row build side still prunes
    everything downstream via the empty-summary convention."""
    flat = decode_to_flat(block)
    if isinstance(flat, FixedWidthBlock):
        mask = ~flat.null_mask() if flat.may_have_null else None
        return summarize_key_column(fid, flat.values, mask, max_distinct)
    n = len(flat.offsets) - 1 if isinstance(flat, VariableWidthBlock) \
        else 0
    if getattr(flat, "nulls", None) is not None:
        n = int(n - np.count_nonzero(flat.nulls))
    return DynamicFilterSummary(fid, row_count=max(0, n))


def _batch_meta(b) -> tuple:
    return tuple(sorted(
        (name, str(c.values.dtype), c.nulls is not None,
         c.lengths is not None, c.dictionary, c.lazy)
        for name, c in b.columns.items()))


def _zeros_like_batch(template, B: int):
    import jax.numpy as jnp
    from ..exec.batch import Batch, Column
    cols = {}
    for name, c in template.columns.items():
        v = jnp.zeros((B,) + c.values.shape[1:], c.values.dtype)
        nn = jnp.zeros(B, dtype=bool) if c.nulls is not None else None
        cols[name] = Column(v, nn, c.dictionary, c.lazy)
    return Batch(cols, jnp.zeros(B, dtype=bool))


def _shard_on(arr, device):
    for s in arr.addressable_shards:
        if s.device == device:
            return s.data
    raise RuntimeError(f"no shard on {device}")


def _device_reader(sources: List[StageInfo], consumer_task: int, rnode,
                   abort=None, stats=None):
    """Consumer-side ICI input: this task's device-resident shard of each
    exchange chunk, renamed positionally to the RemoteSourceNode's output
    variables.

    Chunks were dispatched asynchronously by the producer stage
    (_ici_exchange), so the first touch of each chunk may have to wait
    for its collective.  The wait is measured by non-blocking is_ready()
    polling (so a sibling abort is honored promptly instead of being
    stuck in a blocking device sync) and reported against the
    generator's total drain wall: overlap = 1 - wait / drain, the
    fabric=ici half of the stats-parity story."""
    import time as _time

    from ..exec.batch import Batch
    from ..parallel.fabric import FABRIC_METRICS
    names = [v.name for v in rnode.outputs]

    def read():
        drain0 = _time.perf_counter()  # lint: allow-wall-clock
        wait = 0.0
        try:
            for src in sources:
                prod = src.out_names
                for b in src.device_out[consumer_task] or ():
                    w0 = _time.perf_counter()  # lint: allow-wall-clock
                    while not b.mask.is_ready():
                        if abort is not None and abort.is_set():
                            raise StageAbortedError(
                                "stage aborted while draining ICI "
                                "exchange")
                        _time.sleep(0)
                    wait += _time.perf_counter() - w0  # lint: allow-wall-clock
                    cols = {names[j]: b.columns[prod[j]]
                            for j in range(len(names))}
                    yield Batch(cols, b.mask)
        finally:
            drain = _time.perf_counter() - drain0  # lint: allow-wall-clock
            FABRIC_METRICS.record("ici", compute_wall_s=drain,
                                  wait_wall_s=wait)
            if stats is not None:
                stats.add("exchangeFabricIciDrainWallNanos",
                          drain * 1e9, "NANO")
                stats.add("exchangeFabricIciWaitWallNanos",
                          wait * 1e9, "NANO")
    return read


def _device_dicts_agree(sources: List[StageInfo]) -> bool:
    """Device batches skip the union-dictionary remap of the page path
    (exec/batch.py pages_to_batches), so the device reader is only safe
    when every source fragment ships identical per-column dictionary /
    lazy metadata."""
    seen: Dict[int, tuple] = {}
    for src in sources:
        for chunks in src.device_out or []:
            for b in chunks or ():
                cols = [b.columns[n] for n in src.out_names]
                for j, c in enumerate(cols):
                    meta = (c.dictionary, c.lazy)
                    if seen.setdefault(j, meta) != meta:
                        return False
    return True


def _remote_reader(sources: List[StageInfo], consumer_task: int,
                   client_threads: int = 1):
    """Page reader; ICI children (device_out) are converted to pages
    lazily so mixed device/page source sets lose no rows.  With
    client_threads > 1 the sources drain concurrently through the
    local-exchange arrival-order queue (the in-process mirror of the
    HTTP ExchangeClient; cross-source page order carries no semantics —
    ordering, if any, is applied inside the consuming fragment)."""
    def _source_pages(src: StageInfo) -> Iterator[Page]:
        if src.device_out is not None:
            from .batch import batch_to_page
            types = [v.type for v in
                     src.fragment.root.output_variables]
            for b in src.device_out[consumer_task] or ():
                page = batch_to_page(b, src.out_names, types)
                if page.position_count:
                    yield page
            return
        yield from src.buffers.pages_for_consumer(consumer_task)

    def read() -> Iterator[Page]:
        if client_threads > 1 and len(sources) > 1:
            from .local_exchange import parallel_drain
            thunks = [(lambda s=src: _source_pages(s)) for src in sources]
            yield from parallel_drain(thunks, client_threads)
        else:
            for src in sources:
                yield from _source_pages(src)
    return read
